"""GPTLike pretraining on wikitext with an in-tree BPE tokenizer.

TPU-native counterpart of the reference's
``LLM_Distributed_Trainning/PyTorch/transformer_basics/GPTLike_wikitext2*.py``
family: train a BPE tokenizer on the corpus, block-chunk it into (x, y)
shifted pairs, pretrain a pre-LN decoder-only LM with learned or sinusoidal
positions (``GPTLike_wikitext2_learned_pe.py`` / ``_fixed_pe.py``), plot the
loss curve (``GPTLike_wikitext2.py:166-175``), and sample.

Run: ``python examples/gptlike_wikitext.py [--pos learned|sinusoidal]``
(falls back to a deterministic synthetic corpus when the hub is offline).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

from llm_in_practise_tpu.data import (
    BPETokenizer,
    block_chunk,
    prepare_data,
    tokenize_corpus,
    train_val_split,
)
from llm_in_practise_tpu.infer.generate import generate
from llm_in_practise_tpu.models import GPT, gptlike_config
from llm_in_practise_tpu.train import Trainer, TrainerConfig


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dataset", default="wikitext-2")
    p.add_argument("--vocab_size", type=int, default=8000)
    p.add_argument("--block_size", type=int, default=256)
    p.add_argument("--pos", default="learned", choices=["learned", "sinusoidal"])
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--max_lines", type=int, default=4000)
    p.add_argument("--tokenizer_path", default="/tmp/gptlike_bpe.json")
    p.add_argument("--ckpt_dir", default="/tmp/gptlike_ckpt")
    p.add_argument("--loss_curve", default="/tmp/gptlike_loss.png")
    p.add_argument("--prompt", default="the")
    args = p.parse_args()

    lines = prepare_data(args.dataset)[: args.max_lines]
    print(f"corpus: {len(lines)} lines")
    if os.path.exists(args.tokenizer_path):
        tok = BPETokenizer.load(args.tokenizer_path)
    else:
        tok = BPETokenizer.train(lines, vocab_size=args.vocab_size)
        tok.save(args.tokenizer_path)
    print(f"tokenizer: vocab={tok.vocab_size}")

    ids = tokenize_corpus(lines, tok)
    x, y = block_chunk(ids, args.block_size)
    tr_idx, va_idx = train_val_split(len(x), val_fraction=0.1, seed=42)
    (xt, yt), (xv, yv) = (x[tr_idx], y[tr_idx]), (x[va_idx], y[va_idx])
    print(f"blocks: train={len(xt)} val={len(xv)}")

    model = GPT(gptlike_config(tok.vocab_size, pos_embedding=args.pos,
                               seq_len=args.block_size))
    cfg = TrainerConfig(
        lr=args.lr, epochs=args.epochs, batch_size=args.batch_size,
        schedule="cosine", warmup_steps=20, ckpt_dir=args.ckpt_dir,
        strategy="ddp",
    )
    trainer = Trainer(model, cfg, metadata={"tokenizer_path": args.tokenizer_path})
    history = trainer.train((xt, yt), eval_data=(xv, yv))

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        plt.plot([h["epoch"] for h in history], [h["train_loss"] for h in history],
                 label="train")
        if history and history[0].get("eval_loss") is not None:
            plt.plot([h["epoch"] for h in history],
                     [h["eval_loss"] for h in history], label="val")
        plt.xlabel("epoch"), plt.ylabel("loss"), plt.legend()
        plt.savefig(args.loss_curve)
        print(f"loss curve -> {args.loss_curve}")
    except ImportError:
        pass

    prompt = jnp.asarray(tok.encode(args.prompt))[None, :]
    out = generate(model, trainer.state.params, prompt, max_new_tokens=40,
                   temperature=0.8, top_k=50)
    print("sample:", repr(tok.decode(np.asarray(out[0]).tolist())))


if __name__ == "__main__":
    main()

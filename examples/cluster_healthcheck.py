"""Multi-host cluster health check — the Ray probe script's analog.

The reference validates its Ray cluster with remote CPU/GPU tasks on every
node plus a Plasma object-store round-trip
(``Deployment/Ray/scripts/ray_cluster_healthcheck.py:1-80``). The JAX
equivalent checks the layers that matter here:

1. process rendezvous (``jax.distributed.initialize`` reachable),
2. every process sees the full global device set,
3. a compiled all-device collective (psum) returns the exact expected
   value — proving ICI/DCN paths actually move data,
4. collective bandwidth estimate from a timed all-gather of a sizeable
   array (the object-store round-trip analog),
5. per-device HBM sanity: allocate/compute/fetch on each local device.

Run on every host (single host: just run it):
``python examples/cluster_healthcheck.py [--coordinator host0:1234
--process_id N --num_processes M]``. Exit code 0 = healthy.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--coordinator", default=None)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--mb", type=float, default=32.0,
                   help="array size for the bandwidth probe")
    args = p.parse_args()

    from llm_in_practise_tpu.core import dist

    dist.initialize(
        coordinator_address=args.coordinator,
        process_id=args.process_id,
        num_processes=args.num_processes,
    )

    import jax
    import jax.numpy as jnp
    import numpy as np

    ok = True
    n_local = len(jax.local_devices())
    n_global = len(jax.devices())
    print(f"[1] rendezvous: process {jax.process_index()}/{jax.process_count()}")
    print(f"[2] devices: {n_local} local, {n_global} global "
          f"({jax.devices()[0].platform})")
    if n_global < n_local or n_global % max(jax.process_count(), 1):
        print("    FAIL: global device count inconsistent")
        ok = False

    # [3] exact collective over every device
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()).reshape(n_global), ("d",))
    x = jnp.arange(n_global, dtype=jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("d")))

    @jax.jit
    def total(v):
        return v.sum()

    got = float(total(xs))
    want = n_global * (n_global - 1) / 2
    status = "ok" if got == want else f"FAIL (got {got}, want {want})"
    print(f"[3] all-device reduction: {status}")
    ok = ok and got == want

    # [4] collective bandwidth: timed all-gather of a sharded array
    if n_global > 1:
        n_elems = int(args.mb * 2**20 // 4 // n_global * n_global)
        big = jax.device_put(
            jnp.ones((n_elems,), jnp.float32), NamedSharding(mesh, P("d")))
        gather = jax.jit(
            lambda v: v * 1.0, out_shardings=NamedSharding(mesh, P()))
        jax.block_until_ready(gather(big))
        t0 = time.perf_counter()
        for _ in range(5):
            out = gather(big)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / 5
        gbps = n_elems * 4 * (n_global - 1) / n_global / dt / 1e9
        print(f"[4] all-gather {args.mb:.0f} MiB over {n_global} devices: "
              f"{dt * 1e3:.2f} ms (~{gbps:.1f} GB/s per link)")
    else:
        print("[4] single device: all-gather skipped")

    # [5] per-local-device HBM round-trip
    for d in jax.local_devices():
        a = jax.device_put(jnp.full((256, 256), 3.0), d)
        val = float((a @ jnp.eye(256)).sum())
        if val != 3.0 * 256 * 256:
            print(f"[5] device {d}: FAIL (got {val})")
            ok = False
    print(f"[5] per-device compute: {'ok' if ok else 'see failures above'}")

    print("HEALTHY" if ok else "UNHEALTHY")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""Text-classification fine-tune with accuracy metrics — HF_Basics parity.

Counterpart of the reference's HF Trainer teaching demos
(``HF_Basics/trainer_demo.py:86-127`` and ``accelerate_demo.py:75-141``:
sequence classification with ``TrainingArguments`` + a ``compute_metrics``
accuracy hook). Here the same shape on the in-tree stack: a synthetic
sentiment task, a GPT encoder with a mean-pool classification head, the
framework Trainer with a custom loss, and accuracy evaluated per epoch
through a callback (the ``compute_metrics`` analog).

Run: ``python examples/classifier_train.py [--epochs 3]``.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from llm_in_practise_tpu.data import BPETokenizer
from llm_in_practise_tpu.models import GPT, GPTConfig
from llm_in_practise_tpu.train import Trainer, TrainerConfig

POSITIVE = ["great", "excellent", "wonderful", "fast", "reliable", "loved"]
NEGATIVE = ["terrible", "broken", "slow", "awful", "crashed", "hated"]
NEUTRAL = ["the", "service", "was", "product", "it", "this", "update",
           "release", "today", "we", "found", "overall"]


def synth_reviews(n: int, seed: int = 0):
    """Labeled synthetic reviews: label = which sentiment lexicon dominates."""
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for _ in range(n):
        label = int(rng.integers(2))
        lexicon = POSITIVE if label else NEGATIVE
        words = [str(rng.choice(NEUTRAL)) for _ in range(int(rng.integers(6, 12)))]
        for _ in range(int(rng.integers(1, 4))):
            words.insert(int(rng.integers(len(words))), str(rng.choice(lexicon)))
        texts.append(" ".join(words))
        labels.append(label)
    return texts, np.asarray(labels, np.int32)


class Classifier(nn.Module):
    """GPT trunk + masked mean-pool + linear head."""

    backbone: GPT
    n_classes: int = 2

    @nn.compact
    def __call__(self, idx, *, deterministic: bool = True):
        h = self.backbone(idx, deterministic=deterministic, return_hidden=True)
        mask = (idx != 0)[..., None].astype(h.dtype)
        pooled = (h * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
        return nn.Dense(self.n_classes, name="cls_head")(pooled)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--n_train", type=int, default=800)
    p.add_argument("--n_eval", type=int, default=200)
    p.add_argument("--max_len", type=int, default=24)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args()

    train_texts, train_y = synth_reviews(args.n_train, seed=0)
    eval_texts, eval_y = synth_reviews(args.n_eval, seed=1)
    tok = BPETokenizer.train(train_texts, vocab_size=400, min_frequency=1)

    def encode(texts):
        out = np.zeros((len(texts), args.max_len), np.int32)
        for i, t in enumerate(texts):
            ids = tok.encode(t)[: args.max_len]
            out[i, : len(ids)] = ids
        return out

    x_train, x_eval = encode(train_texts), encode(eval_texts)

    backbone = GPT(GPTConfig(vocab_size=tok.vocab_size, seq_len=args.max_len,
                             n_layer=2, n_head=2, embed_dim=64, dropout=0.1))
    model = Classifier(backbone)

    import optax

    def loss_fn(params, apply_fn, batch, rng):
        x, y = batch
        logits = apply_fn({"params": params}, x, deterministic=False,
                          rngs={"dropout": rng})
        nll = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        return nll.mean(), {"n_valid": jnp.asarray(y.size, jnp.float32)}

    def eval_loss_fn(params, apply_fn, batch):
        x, y = batch
        logits = apply_fn({"params": params}, x, deterministic=True)
        nll = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        return nll.mean(), jnp.asarray(y.size, jnp.float32)

    class AccuracyCallback:
        """compute_metrics analog: accuracy on the eval split per epoch."""

        def on_epoch(self, trainer, epoch, record):
            logits = model.apply({"params": trainer.state.params},
                                 jnp.asarray(x_eval), deterministic=True)
            acc = float((np.asarray(logits).argmax(-1) == eval_y).mean())
            record["eval_accuracy"] = acc
            print(f"  epoch {epoch + 1}: eval accuracy {acc:.3f}")

    cfg = TrainerConfig(lr=args.lr, epochs=args.epochs, batch_size=32,
                        schedule="cosine", warmup_steps=10,
                        log_every_steps=0, strategy="ddp")
    trainer = Trainer(model, cfg, loss_fn=loss_fn, eval_loss_fn=eval_loss_fn,
                      callbacks=[AccuracyCallback()])
    history = trainer.train((x_train, train_y), eval_data=(x_eval, eval_y))
    final = history[-1]
    print(f"final: loss {final['train_loss']:.4f} | "
          f"accuracy {final.get('eval_accuracy', 0):.3f}")
    assert final.get("eval_accuracy", 0) > 0.8, "classifier failed to learn"


if __name__ == "__main__":
    main()

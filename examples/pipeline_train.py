"""Pipeline-parallel pretraining — GPipe microbatching over stage devices.

The reference only reaches pipeline parallelism at inference, through
vLLM's Ray executor across nodes (``Deployment/Ray/serve_deploy_examples/
qwen3_app_pipeline_parallel.yaml:22-30``). Here PP trains: transformer
blocks shard into stages along the ``model`` mesh axis, microbatches flow
through a ``ppermute`` ring (``llm_in_practise_tpu/parallel/pipeline.py``),
and autodiff differentiates through the schedule. GPipe is exact — this
script prints the pipelined loss next to the unpipelined one to show it.

Run (8 simulated devices, 4 stages):
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
  python examples/pipeline_train.py --stages 4``
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from llm_in_practise_tpu.data import BPETokenizer, block_chunk, prepare_data, tokenize_corpus
from llm_in_practise_tpu.models import GPT, gptlike_config
from llm_in_practise_tpu.parallel import pipeline as pp


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--n_micro", type=int, default=4)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--block_size", type=int, default=128)
    p.add_argument("--n_layer", type=int, default=8)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--max_lines", type=int, default=2000)
    args = p.parse_args()

    lines = prepare_data("wikitext-2")[: args.max_lines]
    tok = BPETokenizer.train(lines, vocab_size=2000)
    x_all, y_all = block_chunk(tokenize_corpus(lines, tok), args.block_size)
    print(f"vocab={tok.vocab_size} blocks={len(x_all)}")

    cfg = gptlike_config(tok.vocab_size, seq_len=args.block_size,
                         n_layer=args.n_layer, dropout=0.0)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 8), jnp.int32))["params"]
    stem, stacked = pp.split_gpt_params(params, cfg.n_layer)

    mesh = pp.pipeline_mesh(args.stages)
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"({cfg.n_layer // args.stages} layers/stage, "
          f"{args.n_micro} microbatches)")
    loss_fn = pp.make_pipeline_loss_fn(cfg, mesh, args.n_micro)

    tx = optax.adamw(args.lr, weight_decay=0.01)
    opt_state = tx.init((stem, stacked))

    @jax.jit
    def train_step(stem, stacked, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            stem, stacked, x, y)
        updates, opt_state = tx.update(grads, opt_state, (stem, stacked))
        stem, stacked = optax.apply_updates((stem, stacked), updates)
        return stem, stacked, opt_state, loss

    rng = np.random.default_rng(0)
    with mesh:
        for step in range(args.steps):
            idx = rng.integers(0, len(x_all), (args.batch_size,))
            x = jnp.asarray(x_all[idx])
            y = jnp.asarray(y_all[idx])
            t0 = time.time()
            stem, stacked, opt_state, loss = train_step(
                stem, stacked, opt_state, x, y)
            if step % 10 == 0 or step == args.steps - 1:
                print(f"step {step} | loss {float(loss):.4f} "
                      f"| {time.time() - t0:.2f}s")

    # GPipe exactness check against the unpipelined model
    merged = pp.merge_gpt_params(stem, stacked, cfg.n_layer)
    idx = rng.integers(0, len(x_all), (args.batch_size,))
    x, y = jnp.asarray(x_all[idx]), jnp.asarray(y_all[idx])
    with mesh:
        ploss = float(loss_fn(stem, stacked, x, y))
    rloss = float(pp.reference_loss(model, merged, x, y))
    print(f"pipelined loss {ploss:.6f} == unpipelined {rloss:.6f} "
          f"(diff {abs(ploss - rloss):.2e})")


if __name__ == "__main__":
    main()

"""Recipe-driven SFT — the LLaMA-Factory workflow analog.

The reference's LLaMA-Factory path runs LoRA SFT from a declarative YAML
recipe (``Fine-Tuning/LLaMA-Factory/deepseek-r1-0528-qwen3_lora_sft.yaml``:
model, dataset registration, ``lora_target: all``, cutoff_len, cosine LR,
bf16, output dir). Here the recipe is JSON with the same knob surface,
executed end-to-end by the in-tree stack: dataset (self-cognition stand-in
or an alpaca JSON file) → ChatML + label masking → LoRA → adapter save →
optional merge — no second framework.

Run: ``python examples/sft_recipe.py --recipe examples/recipes/lora_sft.json``
"""

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class SFTRecipe:
    """The LLaMA-Factory YAML knob surface, one dataclass."""

    # model
    model_dir: str | None = None          # HF dir; None -> tiny in-tree Qwen3
    # dataset: a registered name (see dataset_registry), "self_cognition",
    # or a direct path to an alpaca .json
    dataset: str = "self_cognition"
    # LLaMA-Factory dataset_info.json analog: {name: {path, format}};
    # paths resolve relative to the registry file
    dataset_registry: str | None = None
    bot_name: str = "MyBot"
    bot_author: str = "MyTeam"
    cutoff_len: int = 128                 # max_length
    # method: "lora" (bf16/f32 base) or "qlora" (NF4-quantized frozen base —
    # the reference's deepseek-r1-0528-qwen3-8b-qlora.dist.py path)
    finetuning_type: str = "lora"
    lora_rank: int = 8
    lora_alpha: float = 16.0
    lora_target: str = "all"              # "all" | regex over kernel paths
    # train
    learning_rate: float = 1e-3
    num_train_steps: int = 60
    per_device_train_batch_size: int = 8
    lr_scheduler_type: str = "cosine"
    warmup_steps: int = 5
    # output
    output_dir: str = "/tmp/sft_recipe_out"
    merge_after: bool = False


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--recipe", required=True)
    args = p.parse_args()
    with open(args.recipe) as f:
        recipe = SFTRecipe(**json.load(f))
    print(f"recipe: {recipe}")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from llm_in_practise_tpu.ckpt import checkpoint as ckpt
    from llm_in_practise_tpu.data import build_sft_dataset
    from llm_in_practise_tpu.data.converters import alpaca_to_messages
    from llm_in_practise_tpu.data.sft import (
        IGNORE_INDEX,
        render_chatml,
        self_cognition_records,
        tokenize_for_sft,
    )
    from llm_in_practise_tpu.models import Qwen3, qwen3_config
    from llm_in_practise_tpu.peft import (
        LoRAConfig,
        apply_lora,
        init_lora,
        merge_lora,
        trainable_report,
    )
    from llm_in_practise_tpu.train import schedules
    from examples.qwen3_lora_sft import build_tokenizer

    os.makedirs(recipe.output_dir, exist_ok=True)

    # --- dataset registration (dataset_info.json analog) ---------------------
    dataset = recipe.dataset
    if recipe.dataset_registry:
        with open(recipe.dataset_registry, encoding="utf-8") as f:
            registry = json.load(f)
        entry = registry.get(dataset)
        if entry is not None:
            fmt = entry.get("format", "alpaca")
            if fmt not in ("alpaca", "self_cognition"):
                raise ValueError(f"unknown dataset format {fmt!r}")
            if fmt == "self_cognition":
                dataset = "self_cognition"
            else:
                path = entry.get("path")
                if not path:
                    raise ValueError(
                        f"registry entry {recipe.dataset!r} has format "
                        f"{fmt!r} but no 'path'")
                dataset = os.path.join(
                    os.path.dirname(os.path.abspath(recipe.dataset_registry)),
                    path)
            print(f"dataset {recipe.dataset!r} -> {dataset} ({fmt})")
        elif dataset != "self_cognition" and not os.path.exists(dataset):
            raise ValueError(
                f"dataset {dataset!r} is neither registered in "
                f"{recipe.dataset_registry} nor a file")

    # --- dataset -------------------------------------------------------------
    if dataset == "self_cognition":
        records = self_cognition_records(n=64)
        tok = build_tokenizer(records, recipe.bot_name, recipe.bot_author,
                              os.path.join(recipe.output_dir, "tokenizer.json"))
        batch = build_sft_dataset(records, tok, name=recipe.bot_name,
                                  author=recipe.bot_author,
                                  max_length=recipe.cutoff_len)
    else:
        with open(dataset, encoding="utf-8") as f:
            alpaca = json.load(f)
        texts = [render_chatml(alpaca_to_messages(r)) for r in alpaca]
        from llm_in_practise_tpu.data import BPETokenizer
        from llm_in_practise_tpu.data.sft import IM_END, IM_START

        tok_path = os.path.join(recipe.output_dir, "tokenizer.json")
        if os.path.exists(tok_path):
            tok = BPETokenizer.load(tok_path)
        else:
            tok = BPETokenizer.train(
                texts, vocab_size=2000, min_frequency=1,
                special_tokens=("[PAD]", "[UNK]", IM_START, IM_END))
            tok.save(tok_path)
        batch = tokenize_for_sft(texts, tok, max_length=recipe.cutoff_len)
    print(f"dataset: {batch.input_ids.shape}")

    # --- model + adapter -----------------------------------------------------
    if recipe.model_dir:
        from llm_in_practise_tpu.models import hf_loader

        cfg = hf_loader.load_config(recipe.model_dir)
        model = Qwen3(cfg)
        params = hf_loader.load_qwen3(recipe.model_dir)[1]
    else:
        cfg = qwen3_config(tok.vocab_size, max_seq_len=recipe.cutoff_len,
                           compute_dtype="float32")
        model = Qwen3(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.ones((1, 8), jnp.int32),
                            deterministic=True)["params"]

    # "all" = every linear except the output head/embeddings — the
    # LLaMA-Factory meaning of lora_target: all (its 'all-linear' excludes
    # lm_head), not literally every kernel.
    patterns = (
        (r"^(?!.*(?:lm_head|embed)).*kernel$",) if recipe.lora_target == "all"
        else (recipe.lora_target,)
    )
    lcfg = LoRAConfig(r=recipe.lora_rank, alpha=recipe.lora_alpha,
                      target_patterns=patterns)
    lora = init_lora(params, lcfg, jax.random.PRNGKey(1))
    print(trainable_report(params, lora))

    # qlora: NF4-quantize the frozen base (reference
    # ``deepseek-r1-0528-qwen3-8b-qlora.dist.py`` BitsAndBytesConfig path);
    # the dequant runs inside the jitted loss, grads reach LoRA only
    if recipe.finetuning_type == "qlora":
        from llm_in_practise_tpu.peft.qlora import (
            memory_report, qlora_apply, quantize_base,
        )

        qparams = jax.jit(quantize_base)(params)
        print(memory_report(params, qparams))
        compute = jnp.dtype(cfg.compute_dtype)

        def effective(lp):
            return qlora_apply(qparams, lp, lcfg, dtype=compute)
    elif recipe.finetuning_type == "lora":
        def effective(lp):
            return apply_lora(params, lp, lcfg)
    else:
        raise ValueError(
            f"unknown finetuning_type {recipe.finetuning_type!r}")

    # --- train ---------------------------------------------------------------
    x = jnp.asarray(batch.input_ids)
    labels = jnp.asarray(batch.labels)

    def loss_fn(lp, idx):
        logits = model.apply({"params": effective(lp)},
                             x[idx], deterministic=True)
        lab = labels[idx]
        shift_logits = logits[:, :-1].astype(jnp.float32)
        shift_labels = lab[:, 1:]
        mask = shift_labels != IGNORE_INDEX
        logp = jax.nn.log_softmax(shift_logits)
        ll = jnp.take_along_axis(
            logp, jnp.maximum(shift_labels, 0)[..., None], -1)[..., 0]
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1)

    lr = schedules.by_name(recipe.lr_scheduler_type, recipe.learning_rate,
                           total_steps=recipe.num_train_steps,
                           warmup_steps=recipe.warmup_steps)
    tx = optax.adamw(lr)
    opt_state = tx.init(lora)
    step_fn = jax.jit(jax.value_and_grad(loss_fn))
    rng = np.random.default_rng(0)
    for step in range(recipe.num_train_steps):
        idx = jnp.asarray(rng.integers(
            0, len(x), (recipe.per_device_train_batch_size,)))
        loss, grads = step_fn(lora, idx)
        updates, opt_state = tx.update(grads, opt_state, lora)
        lora = optax.apply_updates(lora, updates)
        if step % 10 == 0 or step == recipe.num_train_steps - 1:
            print(f"step {step} | loss {float(loss):.4f}")

    ckpt.save_named(recipe.output_dir, lora, "adapter",
                    metadata={"lora_config": lcfg.to_dict(),
                              "recipe": dataclasses.asdict(recipe)})
    print(f"adapter -> {recipe.output_dir}/adapter.msgpack")
    if recipe.merge_after:
        merged = merge_lora(params, lora, lcfg)
        ckpt.save_named(recipe.output_dir, merged, "model",
                        metadata={"config": cfg.to_dict()})
        print(f"merged model -> {recipe.output_dir}/model.msgpack")


if __name__ == "__main__":
    main()

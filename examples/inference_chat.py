"""Checkpoint inference ladder: single-shot, sampling, multi-turn chat.

TPU-native counterpart of the reference's ``Scripts/inference/01..04-*.py``
(load → generate → decode; 04 adds multi-session history) and
``Fine-Tuning/inferences.py:29-86`` (ChatML prompt build over turns). Loads
the merged model from ``examples/merge_lora.py`` (or any ``save_named``
checkpoint + tokenizer), keeps a rolling message history, renders ChatML,
and samples with temperature/top-p. ``--stream`` prints tokens as they
decode (the ``TextIteratorStreamer`` behavior of ``06-…-streaming-infr.py``).

Run: ``python examples/inference_chat.py --prompt "Who are you?"``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

from llm_in_practise_tpu.ckpt import checkpoint as ckpt
from llm_in_practise_tpu.data import BPETokenizer
from llm_in_practise_tpu.data.sft import IM_END, IM_START, render_chatml
from llm_in_practise_tpu.infer.generate import generate, make_decode_fns
from llm_in_practise_tpu.infer.sampling import sample_token
from llm_in_practise_tpu.models import Qwen3, Qwen3Config


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_path", default="/tmp/qwen3_merged/model.msgpack")
    p.add_argument("--tokenizer_path", default="/tmp/qwen3_sft_bpe.json")
    p.add_argument(
        "--system",
        default="You are a helpful assistant named MyBot, trained by MyTeam.",
        help="system prompt (default matches examples/qwen3_lora_sft.py); "
             "pass '' for none",
    )
    p.add_argument("--prompt", default="Who are you?",
                   help="single-shot prompt; omit --interactive for one turn")
    p.add_argument("--interactive", action="store_true")
    p.add_argument("--max_new_tokens", type=int, default=48)
    p.add_argument("--temperature", type=float, default=0.7)
    p.add_argument("--top_p", type=float, default=0.9)
    p.add_argument("--greedy", action="store_true")
    p.add_argument("--stream", action="store_true")
    args = p.parse_args()

    tok = BPETokenizer.load(args.tokenizer_path)
    params, meta = ckpt.restore_checkpoint(args.model_path)
    model = Qwen3(Qwen3Config.from_dict(meta["config"]))
    eos = tok.token_to_id(IM_END)

    history: list[dict] = []
    if args.system:
        history.append({"role": "system", "content": args.system})

    def answer(user_text: str) -> str:
        history.append({"role": "user", "content": user_text})
        prompt = render_chatml(history) + f"{IM_START}assistant\n"
        ids = jnp.asarray(tok.encode(prompt))[None, :]
        if args.stream:
            # Incremental prefill+decode (the streamer-thread pattern of the
            # reference collapses to a plain loop over the jitted step).
            import jax

            cache = model.init_cache(1, model.config.max_seq_len,
                                     dtype=jnp.float32)
            prefill, decode_step = make_decode_fns(model)
            logits, cache = prefill(params, ids, cache)
            rng = jax.random.PRNGKey(0)
            out_ids: list[int] = []
            shown = ""
            text = ""
            for _ in range(args.max_new_tokens):
                rng, step_rng = jax.random.split(rng)
                tok_id = int(sample_token(
                    step_rng, logits, temperature=args.temperature,
                    top_p=args.top_p, greedy=args.greedy,
                )[0])
                if eos is not None and tok_id == eos:
                    break
                out_ids.append(tok_id)
                text = tok.decode(out_ids)
                print(text[len(shown):], end="", flush=True)
                shown = text
                logits, cache = decode_step(
                    params, jnp.asarray([tok_id], jnp.int32), cache)
            print()
        else:
            out = generate(
                model, params, ids, max_new_tokens=args.max_new_tokens,
                temperature=args.temperature, top_p=args.top_p,
                greedy=args.greedy, eos_id=eos,
            )
            text = tok.decode(np.asarray(out[0]).tolist()[ids.shape[1]:])
            print(text.strip())
        history.append({"role": "assistant", "content": text.strip()})
        return text

    if args.interactive:
        print("chat (empty line to exit)")
        while True:
            try:
                user = input("> ").strip()
            except EOFError:
                break
            if not user:
                break
            answer(user)
    else:
        print(f"> {args.prompt}")
        answer(args.prompt)


if __name__ == "__main__":
    main()

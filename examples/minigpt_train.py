"""MiniGPT char-level pretraining — the minimum end-to-end slice.

TPU-native counterpart of the reference's ``llm-demo/minigpt2/model.py``
__main__ (char vocab → sliding-window dataset → AdamW + clip loop →
checkpoint with vocab + config) and ``llm-demo/minigpt/generate.py`` (greedy
decode). Run: ``python examples/minigpt_train.py [--epochs N]``.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from llm_in_practise_tpu.ckpt import checkpoint as ckpt
from llm_in_practise_tpu.data.chardata import char_lm_examples
from llm_in_practise_tpu.data.loader import batch_iterator
from llm_in_practise_tpu.infer.generate import generate
from llm_in_practise_tpu.models.gpt import GPT, minigpt_config
from llm_in_practise_tpu.train import optim, step as step_lib

SAMPLE_TEXT = (
    "TPUs are matrix machines: feed the systolic array big batched matmuls, "
    "keep the data in bfloat16, and let the compiler fuse the rest. "
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--text", default=SAMPLE_TEXT * 4)
    p.add_argument("--seq_len", type=int, default=64)
    p.add_argument("--epochs", type=int, default=40)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--weight_decay", type=float, default=0.1)
    p.add_argument("--ckpt_dir", default="/tmp/minigpt_ckpt")
    p.add_argument("--prompt", default="TPUs are")
    args = p.parse_args()

    print(f"devices: {jax.devices()}")
    x, y, tok = char_lm_examples(args.text, args.seq_len)
    cfg = minigpt_config(tok.vocab_size, seq_len=args.seq_len)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0), x[:1])["params"]
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    print(f"vocab={tok.vocab_size} examples={len(x)} params={n_params:,}")

    tx = optim.adamw(args.lr, weight_decay=args.weight_decay, clip_norm=1.0)
    state = step_lib.create_train_state(model, params, tx, jax.random.PRNGKey(1))
    train_step = step_lib.make_train_step()

    for epoch in range(args.epochs):
        t0 = time.time()
        losses = []
        for batch in batch_iterator((x, y), args.batch_size, seed=0, epoch=epoch):
            state, metrics = train_step(state, batch)
            losses.append(metrics["loss"])
        if epoch % 5 == 0 or epoch == args.epochs - 1:
            mean_loss = float(jnp.mean(jnp.stack(losses)))
            print(f"epoch {epoch + 1}/{args.epochs} | loss {mean_loss:.4f} "
                  f"| {time.time() - t0:.2f}s")

    path = ckpt.save_checkpoint(
        args.ckpt_dir, {"params": state.params}, int(state.step),
        metadata={"config": cfg.to_dict(), "vocab": tok.to_dict()},
    )
    print(f"saved {path}")

    prompt = jnp.asarray(tok.encode(args.prompt)[None, :])
    out = generate(model, state.params, prompt, max_new_tokens=40, greedy=True,
                   cache_dtype=jnp.float32)
    print("sample:", repr(tok.decode(np.asarray(out[0]))))


if __name__ == "__main__":
    main()

"""OpenAI-compatible API server over a checkpoint — the serving entry point.

TPU-native counterpart of the reference's
``Scripts/inference/07-deepseek1.5b-api-infr.py`` (FastAPI
``/v1/chat/completions`` with usage accounting and uvicorn main) plus what
that script stubs out (``stream`` → 501, ``:110-112``): here streaming SSE
works, requests batch continuously onto KV-cache slots (vLLM-style), and
``/metrics`` exports the Prometheus names the reference's platform scrapes
(``Inference_Platfrom/README.md:1676-1692``).

Run: ``python examples/serve_openai.py [--port 8000]`` then
``curl localhost:8000/v1/chat/completions -d '{"messages": [...]}'``.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from llm_in_practise_tpu.ckpt import checkpoint as ckpt
from llm_in_practise_tpu.data import BPETokenizer
from llm_in_practise_tpu.models import Qwen3, Qwen3Config
from llm_in_practise_tpu.serve.api import OpenAIServer
from llm_in_practise_tpu.serve.engine import InferenceEngine


def validate_args(args, error) -> None:
    """Flag-combination validation, split from :func:`main` so the
    rules are unit-testable without loading a checkpoint
    (tests/test_tp_serving.py). ``error`` is ``parser.error`` (raises
    SystemExit with the message). Mutates ``args.speculative`` to the
    role-resolved value.

    ISSUE 10 deleted the ``--tensor-parallel-size`` fail-fasts against
    ``--quantized_dir`` (packed leaves now shard via
    quant/sharding.py component shardings) and ``--draft-model-path``
    (the small draft replicates across the mesh). ``--scan-layers``
    keeps its TP error: the stacked layout serves contiguous-only
    (no paged pool, no per-block TP rule table) and stays the
    single-chip flat-compile-time path.
    """
    if args.quantized_dir and args.lora_modules:
        error("--lora-modules with --quantized_dir is not supported "
              "(adapters cannot merge into packed 4-bit kernels)")
    if args.scan_layers and args.tp > 1:
        error("--scan-layers with --tensor-parallel-size is not "
              "supported: the stacked scan layout is contiguous-only "
              "(no paged pool, no stacked TP rule table — "
              "docs/serving-tp.md 'Limitations'); serve deep models "
              "sharded with the unrolled layout instead")
    if args.tp_quantized_collectives and args.tp <= 1:
        error("--tp-quantized-collectives requires "
              "--tensor-parallel-size > 1 (there is no collective to "
              "quantize on one chip)")
    if args.tp_quantized_collectives and args.quantized_dir:
        error("--tp-quantized-collectives with --quantized_dir is not "
              "supported: packed trees run their matmuls through the "
              "fused dequant interceptor, which the quantized-"
              "collective interceptor does not compose with")
    if args.scan_layers and args.lora_modules:
        error("--lora-modules with --scan-layers is not supported: "
              "adapters merge by unrolled block_i/... kernel paths, "
              "which do not exist in the stacked tree (they would "
              "silently serve base weights)")
    if args.lora_modules:
        # fail fast at the CLI — a typo'd spec or missing checkpoint
        # should not surface as a traceback after the (slow) base
        # checkpoint restore (ISSUE 15 registry wiring)
        import os as _os

        from llm_in_practise_tpu.serve.adapters import parse_lora_modules

        try:
            modules = parse_lora_modules(args.lora_modules)
        except ValueError as e:
            error(f"--lora-modules: {e}")
        for name, path in modules.items():
            if name == getattr(args, "model_name", None):
                error(f"--lora-modules: adapter name {name!r} collides "
                      "with --model_name (the base model's served name)")
            ckpt_file = (_os.path.join(path, "adapter.msgpack")
                         if _os.path.isdir(path) else path)
            if not _os.path.exists(ckpt_file):
                error(f"--lora-modules {name}: no adapter checkpoint "
                      f"at {path} (want adapter.msgpack + sidecar from "
                      "ckpt.save_named)")
    if args.role != "both" and not args.kv_remote:
        error(f"--role {args.role} requires --kv-remote: the KV handoff "
              "between the prefill and decode pools travels through the "
              "shared kv_pool server")
    if args.scan_layers and args.kv_layout == "paged":
        error("--scan-layers serves with --kv-layout contiguous only "
              "(the paged pool supports the unrolled cache layout; "
              "pass --kv-layout contiguous explicitly)")
    # a draft model still needs an EXPLICIT K (checked before the
    # decode-role default below resolves one, or the requirement would
    # be silently bypassed on --role decode)
    if args.draft_model_path and args.speculative is None:
        error("--draft-model-path requires --speculative K")
    # decode replicas default speculation ON (ISSUE 9 / ROADMAP item 4):
    # the fused verify-inside-the-block round is the production decode
    # path once no prefill ever shares the replica; --speculative 0
    # opts out explicitly. Only the ngram proposer can be defaulted
    # (the draft-model path was handled above).
    from llm_in_practise_tpu.serve.disagg import default_speculative_k

    resolved_spec = default_speculative_k(args.role, args.speculative)
    if args.role == "decode" and args.speculative is None:
        print(f"decode replica: ngram speculation ON by default "
              f"(k={resolved_spec}; --speculative 0 disables)")
    args.speculative = resolved_spec
    if args.draft_model_path and args.speculative is None:
        # --speculative 0 resolved the opt-out: a draft model with
        # speculation off is contradictory — fail at the CLI, not with
        # an engine ValueError traceback after the checkpoint loads
        error("--draft-model-path with --speculative 0 is "
              "contradictory: drop the draft model or pass a "
              "positive K")
    if args.draft_model_path and args.scan_layers:
        error("--draft-model-path with --scan-layers is not supported "
              "yet: the draft loads unstacked (cache slot axis 0) while "
              "the stacked target uses axis 1 — the engine would reject "
              "the layout mismatch after the full checkpoint restore")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_path", default="/tmp/qwen3_merged/model.msgpack")
    p.add_argument("--tokenizer_path", default="/tmp/qwen3_sft_bpe.json")
    p.add_argument("--model_name", default="qwen3-tpu")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--max_slots", type=int, default=8,
                   help="concurrent sequences in the continuous batch")
    p.add_argument("--cache_len", type=int, default=512)
    p.add_argument("--lora-modules", dest="lora_modules", nargs="*",
                   default=[], metavar="NAME=PATH",
                   help="serve LoRA adapters as extra model names "
                        "(vLLM --lora-modules parity)")
    p.add_argument("--enable-prefix-caching", dest="prefix_caching",
                   action="store_true",
                   help="reuse prompt-prefix KV across requests "
                        "(vLLM APC parity)")
    p.add_argument("--session-store", dest="session_store",
                   action="store_true",
                   help="session-native serving (serve/sessions.py): "
                        "requests carrying a session id (X-Session-ID "
                        "header or body field) keep their conversation "
                        "KV pinned across turns, and finished turns "
                        "publish to the kv-pool handoff namespace when "
                        "--kv-remote is set — the fleet-wide warm path "
                        "behind the gateway's --routing ring")
    p.add_argument("--session-ttl", dest="session_ttl", type=float,
                   default=600.0, metavar="SECONDS",
                   help="idle TTL for pinned session KV "
                        "(with --session-store)")
    p.add_argument("--enable-chunked-prefill", dest="chunked_prefill",
                   type=int, nargs="?", const=256, default=None,
                   metavar="CHUNK",
                   help="prefill long prompts in CHUNK-token steps "
                        "interleaved with decode (vLLM parity; default 256)")
    p.add_argument("--tensor-parallel-size", dest="tp", type=int, default=1,
                   help="shard the model over N devices for serving "
                        "(vLLM --tensor-parallel-size parity)")
    p.add_argument("--kv-offload", dest="kv_offload", action="store_true",
                   help="tiered KV: offload evicted/finished prefix KV to "
                        "host RAM and re-hit it (LMCache local-CPU parity)")
    p.add_argument("--kv-remote", dest="kv_remote", default=None,
                   metavar="HOST:PORT",
                   help="share prefix KV through a kv_pool server at "
                        "HOST:PORT (LMCache lm:// parity; start one with "
                        "python -m llm_in_practise_tpu.serve.kv_pool)")
    p.add_argument("--role", default="both",
                   choices=["prefill", "decode", "both"],
                   help="disaggregated serving role (llm-d parity): "
                        "'prefill' replicas only prefill and hand the "
                        "prompt KV to the pool's handoff namespace; "
                        "'decode' replicas claim it and run pure decode "
                        "(zero prefill interference); 'both' (default) "
                        "is a full replica. prefill/decode require "
                        "--kv-remote (the handoff travels through the "
                        "shared pool) and a gateway running the disagg "
                        "router (examples/serve_gateway.py --routing "
                        "disagg)")
    p.add_argument("--speculative", dest="speculative", type=int,
                   nargs="?", const=4, default=None, metavar="K",
                   help="ngram/prompt-lookup speculative decoding: draft K "
                        "tokens per step, verify in one forward (lossless "
                        "for greedy; vLLM ngram speculator parity). The "
                        "fused spec round verifies the K drafts AND runs "
                        "the rest of the --decode-steps block in ONE "
                        "dispatch. DEFAULT ON for --role decode replicas "
                        "(K=4) — pass --speculative 0 to disable there")
    p.add_argument("--decode-steps", dest="decode_steps", type=int,
                   default=1, metavar="N",
                   help="decode N tokens per jitted dispatch (vLLM "
                        "multi-step scheduling parity) — the lever when "
                        "host dispatch latency rivals the decode step")
    p.add_argument("--no-mixed-step", dest="mixed_step",
                   action="store_false", default=True,
                   help="disable the fused mixed-batch step (default ON: "
                        "while prompts chunk-prefill AND slots decode, one "
                        "dispatch advances every prefill chunk and runs "
                        "the full decode block — mixed-load steps cost 1 "
                        "dispatch instead of 2 and decoders keep their "
                        "--decode-steps amortization)")
    p.add_argument("--draft-model-path", dest="draft_model_path",
                   default=None,
                   help="checkpoint of a SMALLER model for draft-model "
                        "speculative decoding (requires --speculative; "
                        "vLLM speculative_model parity — the ngram "
                        "speculator runs when this is omitted)")
    p.add_argument("--max-queue", dest="max_queue", type=int, default=None,
                   metavar="N",
                   help="admission control: reject (HTTP 429 queue_full) "
                        "once N requests wait — ingress backpressure at "
                        "the engine")
    p.add_argument("--queue-timeout", dest="queue_timeout", type=float,
                   default=None, metavar="SECONDS",
                   help="admission control: shed requests that waited "
                        "past this deadline (HTTP 429 queue_full) — the "
                        "gateway's retry policy routes them elsewhere")
    p.add_argument("--trace-file", dest="trace_file", default=None,
                   metavar="PATH",
                   help="append Chrome trace events (one JSON per line) "
                        "for every request span to PATH — open in "
                        "Perfetto / chrome://tracing; the in-memory "
                        "span ring is always on at GET /debug/traces "
                        "(LLM_TPU_TRACE=off disables tracing)")
    p.add_argument("--ttft-slo", dest="ttft_slo", type=float, default=None,
                   metavar="SECONDS",
                   help="SLO goodput accounting: TTFT threshold — "
                        "tokens of requests that miss it count as "
                        "llm_goodput_tokens_total{slo=violated}")
    p.add_argument("--tpot-slo", dest="tpot_slo", type=float, default=None,
                   metavar="SECONDS",
                   help="SLO goodput accounting: per-token (TPOT) "
                        "threshold (docs/observability.md device plane)")
    p.add_argument("--kv-layout", dest="kv_layout", default="paged",
                   choices=["paged", "contiguous"],
                   help="KV cache layout (docs/paged-kv.md): 'paged' "
                        "(default) carves one pool into fixed-size "
                        "pages behind per-slot block tables — admission "
                        "reserves actual pages, prefixes share "
                        "refcounted pages (COW), handoff ships only "
                        "live pages (vLLM PagedAttention parity); "
                        "'contiguous' is the previous slot-owns-a-"
                        "cache_len-region layout, kept as a fallback "
                        "for one release (golden tokens are identical)")
    p.add_argument("--kv-page-size", dest="kv_page_size", type=int,
                   default=16, metavar="TOKENS",
                   help="tokens per KV page (paged layout; vLLM "
                        "block_size parity)")
    p.add_argument("--kv-pool-tokens", dest="kv_pool_tokens", type=int,
                   default=None, metavar="TOKENS",
                   help="page-pool capacity in tokens (paged layout); "
                        "default max_slots*cache_len — set LOWER than "
                        "that to serve more slots than worst-case "
                        "contexts would allow, relying on page-granular "
                        "admission + preemption")
    p.add_argument("--kv-cache-dtype", dest="kv_cache_dtype",
                   default="float32", choices=["float32", "bfloat16", "fp8"],
                   help="KV cache storage dtype; fp8 (e4m3) halves KV HBM "
                        "vs bf16 (vLLM --kv-cache-dtype fp8 parity)")
    p.add_argument("--tp-quantized-collectives",
                   dest="tp_quantized_collectives", action="store_true",
                   help="int8 activation all-reduce for the row-parallel "
                        "TP matmuls (ZeRO++ idiom, arxiv 2306.10209): "
                        "halves the per-token interconnect traffic. "
                        "LOSSY opt-in — greedy tokens are checked "
                        "against the plain path at startup and the flag "
                        "falls back (with a warning) on mismatch "
                        "(docs/serving-tp.md)")
    p.add_argument("--quantized_dir", default=None,
                   help="serve a packed 4-bit export from "
                        "examples/quantize_ptq.py (weights stay packed in "
                        "HBM, fused dequant matmuls — vLLM "
                        "compressed-tensors serving parity; composes "
                        "with --tensor-parallel-size via "
                        "quant/sharding.py component shardings)")
    p.add_argument("--scan-layers", dest="scan_layers",
                   action="store_true",
                   help="serve in the scan-layers layout: params and KV "
                        "cache stacked over depth, every engine program "
                        "compiles ONE block — flat compile time for deep "
                        "models (packed 4-bit weights ride the scan as "
                        "sideband inputs); Qwen3-family only")
    args = p.parse_args()
    validate_args(args, p.error)

    tok = BPETokenizer.load(args.tokenizer_path)

    # the mesh exists BEFORE the model loads: a packed QuantizedModel
    # needs it at construction (mesh -> the SPMD-partitionable XLA
    # dequant path; Pallas custom calls are opaque to the partitioner)
    mesh = None
    if args.tp > 1:
        from llm_in_practise_tpu.parallel import strategy as S

        strat = S.tensor_parallel(model=args.tp, data=1)
        mesh = strat.build_mesh(jax.devices()[: args.tp])

    if args.quantized_dir:
        from llm_in_practise_tpu.quant import io as quant_io
        from llm_in_practise_tpu.serve.quantized import QuantizedModel

        params, meta = quant_io.load_packed(args.quantized_dir)
        if meta.get("family") == "gpt":  # the hermetic PTQ demo's model
            from llm_in_practise_tpu.models import GPT, GPTConfig

            base = GPT(GPTConfig.from_dict(meta["config"]))
        else:
            base = Qwen3(Qwen3Config.from_dict(meta["config"]))
        model = QuantizedModel(base, mesh=mesh)
        print(f"packed 4-bit model: {args.quantized_dir} "
              f"({meta.get('method')}, ppl {meta.get('ppl')}) "
              f"| devices: {jax.devices()}")
    else:
        params, meta = ckpt.restore_checkpoint(args.model_path)
        model = Qwen3(Qwen3Config.from_dict(meta["config"]))
        print(f"model: {args.model_path} | devices: {jax.devices()}")

    from llm_in_practise_tpu.data.sft import IM_END

    if args.scan_layers:
        from llm_in_practise_tpu.models.qwen3 import (
            stack_layer_params_jitted,
        )
        from llm_in_practise_tpu.serve.quantized import (
            QuantizedModel as _QM,
        )

        inner = model.model if isinstance(model, _QM) else model
        if not isinstance(inner, Qwen3):
            p.error("--scan-layers requires a Qwen3-family model")
        scfg = inner.cfg.replace(scan_layers=True)
        params = stack_layer_params_jitted(params, scfg.n_layer)
        model = (_QM(Qwen3(scfg)) if isinstance(model, _QM)
                 else Qwen3(scfg))
        print(f"scan-layers serving: {scfg.n_layer} layers, "
              "one compiled block per engine program")

    shard_fn = None
    if args.tp > 1:
        from llm_in_practise_tpu.serve.engine import shard_params_for_serving

        # quant-aware (ISSUE 10): packed Int8/Int4/NF4/AWQ leaves get
        # component shardings from the same serving rule table, so an
        # int8 14B loads shard-parallel instead of failing fast
        shard_fn = lambda p: shard_params_for_serving(p, strat, mesh)
        params = shard_fn(params)
        print(f"tensor parallel over {args.tp} devices"
              + (" (packed quantized tree, component shardings)"
                 if args.quantized_dir else ""))
        if args.role == "decode":
            # the documented disagg fleet shape (docs/serving-tp.md):
            # multi-chip decode replicas fed by single-chip prefill
            print(f"fleet shape: --role decode with tp={args.tp} — "
                  "single-chip prefill replicas feed this replica "
                  "through the kv-pool handoff (entries reshard on "
                  "claim)")
    if args.tp_quantized_collectives:
        # golden-token-checked opt-in (ZeRO++ idiom, lossy): the int8
        # collective serves only if its greedy tokens match the plain
        # path on the probe prompt — else warn and fall back. One gate
        # policy, shared with tools/tp_ladder_bench.py.
        from llm_in_practise_tpu.parallel.collectives import (
            maybe_quantized_collectives,
        )

        model, _ = maybe_quantized_collectives(model, mesh, params)

    # KV is only valid under the weights that produced it, so every served
    # model (base + each adapter) gets its OWN tiered pool; the remote
    # server is shared but namespaced per model name (LMCache semantics).
    def make_kv_pool(model_name):
        if not (args.kv_offload or args.kv_remote):
            return None
        from llm_in_practise_tpu.serve.kv_pool import (
            HostKVPool, RemoteKVClient, TieredKV,
        )

        remote = None
        if args.kv_remote:
            rhost, rport = args.kv_remote.rsplit(":", 1)
            remote = RemoteKVClient((rhost, int(rport)),
                                    namespace=model_name)
        return TieredKV(HostKVPool(), remote)

    if args.kv_offload or args.kv_remote:
        tiers = "HBM->host" + ("->remote" if args.kv_remote else "")
        print(f"tiered KV pool: {tiers} (namespaced per model)")

    draft_model = draft_params = None
    if args.draft_model_path:  # combos validated at the argparse block
        draft_params, draft_meta = ckpt.restore_checkpoint(
            args.draft_model_path)
        draft_model = Qwen3(Qwen3Config.from_dict(draft_meta["config"]))
        print(f"draft model: {args.draft_model_path}")

    # disaggregated serving: the handoff store rides the shared pool
    # server (pin-until-claimed namespace, serve/disagg.py). Any replica
    # with a pool connection gets one — "both" replicas then still serve
    # /internal/handoff/prefill and claim entries when a role pool is
    # degraded. Per MODEL: each served name (base + every adapter) gets
    # its own namespace, so cross-model handoffs can never collide.
    def make_handoff(model_name):
        if not args.kv_remote:
            return None
        from llm_in_practise_tpu.serve.disagg import RemoteHandoff

        rhost, rport = args.kv_remote.rsplit(":", 1)
        return RemoteHandoff((rhost, int(rport)), namespace=model_name)

    handoff = make_handoff(args.model_name)
    if handoff is not None and args.role != "both":
        print(f"disaggregated role: {args.role} "
              f"(handoff via {args.kv_remote})")

    engine_kw = dict(
        max_slots=args.max_slots, cache_len=args.cache_len,
        eos_id=tok.token_to_id(IM_END),
        cache_dtype={"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                     "fp8": jnp.float8_e4m3fn}[args.kv_cache_dtype],
        prefix_cache=args.prefix_caching,
        chunked_prefill=args.chunked_prefill, mesh=mesh,
        speculative_k=args.speculative,
        decode_steps=args.decode_steps,
        mixed_step=args.mixed_step,
        max_queue=args.max_queue,
        queue_timeout_s=args.queue_timeout,
        ttft_slo_s=args.ttft_slo, tpot_slo_s=args.tpot_slo,
        draft_model=draft_model, draft_params=draft_params,
        kv_layout=args.kv_layout,
        kv_page_size=args.kv_page_size,
        kv_pool_tokens=args.kv_pool_tokens,
    )
    # batched multi-LoRA (ISSUE 15): adapters ride the BASE engine's
    # fused dispatch through an AdapterRegistry — one base-weight copy,
    # mixed-adapter slots in one step. The legacy engine-per-adapter
    # path remains only for tiered/remote KV setups, where each served
    # model needs its own pool + handoff namespace (one weight set per
    # engine); build_adapter_engines warns when it takes it.
    lora_modules = {}
    adapter_registry = None
    if args.lora_modules:
        from llm_in_practise_tpu.serve.adapters import parse_lora_modules

        lora_modules = parse_lora_modules(args.lora_modules)
        if not (args.kv_offload or args.kv_remote):
            from llm_in_practise_tpu.serve.multi_lora import AdapterRegistry

            adapter_registry = AdapterRegistry(params, mesh=mesh)
    session_store = None
    if args.session_store:
        from llm_in_practise_tpu.serve.sessions import SessionStore

        session_store = SessionStore(ttl_s=args.session_ttl)
        warm = ("fleet warm path via " + args.kv_remote
                if args.kv_remote else "local pins only (no --kv-remote)")
        print(f"session store: ttl {args.session_ttl:g}s, {warm}")
    engine = InferenceEngine(model, params,
                             kv_pool=make_kv_pool(args.model_name),
                             role=args.role, handoff=handoff,
                             adapter_registry=adapter_registry,
                             session_store=session_store,
                             **engine_kw)
    adapters = {}
    if lora_modules and adapter_registry is not None:
        from llm_in_practise_tpu.serve.multi_lora import AdapterHandle

        for name, path in lora_modules.items():
            adapter_registry.register(name, path)
        adapters = {name: AdapterHandle(engine, name)
                    for name in lora_modules}
        print(f"adapters (batched multi-LoRA, one shared engine): "
              f"{sorted(adapters)}")
    elif lora_modules:
        from llm_in_practise_tpu.serve.adapters import (
            build_adapter_engines,
        )

        # adapter engines skip the draft: the draft approximates the
        # BASE distribution, and each copy would cost its own draft KV
        adapter_kw = {k: v for k, v in engine_kw.items()
                      if not k.startswith("draft_")}
        adapters = build_adapter_engines(
            model, params, lora_modules,
            param_transform=shard_fn,
            # per-model tiers AND per-model handoff namespace: adapter
            # requests disaggregate exactly like the base model's
            engine_kw_for=lambda name: {"kv_pool": make_kv_pool(name),
                                        "role": args.role,
                                        "handoff": make_handoff(name)},
            **adapter_kw
        )
        print(f"adapters: {sorted(adapters)}")
    if args.trace_file:
        from llm_in_practise_tpu.obs.trace import get_tracer

        get_tracer().set_trace_file(args.trace_file)
        print(f"chrome trace events -> {args.trace_file} "
              "(open in Perfetto)")
    server = OpenAIServer(engine, tok, model_name=args.model_name,
                          adapters=adapters, role=args.role,
                          handoff=handoff)
    print(f"serving on {args.host}:{args.port} "
          f"(/v1/chat/completions, /v1/models, /health, /metrics, "
          f"/debug/traces)")
    server.serve(host=args.host, port=args.port)


if __name__ == "__main__":
    main()

"""RAG chat over a local knowledge base — the AnythingLLM analog.

The reference deploys AnythingLLM next to Ollama/Open-WebUI as its RAG
story (``Deployment/AnythingLLM/docker-compose.yml``): documents are
chunked, embedded, retrieved by cosine similarity, and stuffed into the
chat prompt. Same pipeline here, dependency-free and against this
framework's models:

- **chunk**: sliding window over words with overlap;
- **embed**: either the hashed bag-of-tokens embedding the gateway's
  semantic cache uses (no model, instant) or mean-pooled hidden states
  from an in-tree checkpoint (``--embedder model``);
- **retrieve**: cosine top-k over the chunk matrix (one matmul);
- **generate**: ChatML prompt with the retrieved context, decoded with
  the same generate loop every other example uses.

Run retrieval-only against the in-repo docs (hermetic, no checkpoint):

    python examples/rag_chat.py --ask "how does ring attention work?"

or with a fine-tuned checkpoint for grounded answers:

    python examples/rag_chat.py --model_path /tmp/qwen3_merged/model.msgpack \\
        --tokenizer_path /tmp/qwen3_sft_bpe.json --ask "..."
"""

import argparse
import hashlib
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --- knowledge base ----------------------------------------------------------


def chunk_text(text: str, *, size: int = 160, overlap: int = 40):
    """Sliding word-window chunks (the AnythingLLM chunker's shape)."""
    words = text.split()
    step = max(size - overlap, 1)
    out = []
    for start in range(0, max(len(words) - overlap, 1), step):
        piece = " ".join(words[start: start + size])
        if piece:
            out.append(piece)
    return out


def hash_embed(text: str, dim: int = 256):
    """Hashed bag-of-tokens embedding (the gateway semantic cache's
    embedder) — no model, deterministic, good enough to rank chunks."""
    vec = [0.0] * dim
    for word in text.lower().split():
        h = int.from_bytes(hashlib.sha1(word.encode()).digest()[:8], "big")
        vec[h % dim] += 1.0 if (h >> 63) else -1.0
    norm = math.sqrt(sum(v * v for v in vec)) or 1.0
    return [v / norm for v in vec]


class KnowledgeBase:
    def __init__(self, embed_fn):
        self.embed_fn = embed_fn
        self.chunks: list[tuple[str, str]] = []   # (source, text)
        self.vectors: list[list[float]] = []

    def add_file(self, path: str) -> int:
        with open(path, encoding="utf-8", errors="replace") as f:
            pieces = chunk_text(f.read())
        for piece in pieces:
            self.chunks.append((os.path.basename(path), piece))
            self.vectors.append(self.embed_fn(piece))
        return len(pieces)

    def search(self, query: str, k: int = 3):
        q = self.embed_fn(query)
        scored = [
            (sum(a * b for a, b in zip(q, v)), src, text)
            for v, (src, text) in zip(self.vectors, self.chunks)
        ]
        scored.sort(key=lambda s: -s[0])
        return scored[:k]


def model_embedder(model, params, tokenizer):
    """Mean-pooled final hidden states as the embedding — the in-tree
    counterpart of AnythingLLM's embedding service."""
    import jax.numpy as jnp
    import numpy as np

    def embed(text: str):
        ids = tokenizer.encode(text)[:256] or [0]
        h = model.apply({"params": params}, jnp.asarray([ids], jnp.int32),
                        deterministic=True, return_hidden=True)
        vec = np.asarray(h[0].mean(axis=0), np.float64)
        return list(vec / (np.linalg.norm(vec) or 1.0))

    return embed


# --- the chat loop -----------------------------------------------------------


def build_rag_prompt(question: str, hits) -> list[dict]:
    context = "\n\n".join(f"[{src}] {text}" for _, src, text in hits)
    return [
        {"role": "system",
         "content": "Answer using ONLY the provided context. Cite the "
                    f"source file in brackets.\n\nContext:\n{context}"},
        {"role": "user", "content": question},
    ]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--kb", default=None, nargs="*",
                   help="files/dirs to index (default: docs/tutorials)")
    p.add_argument("--ask", default=None, help="one-shot question")
    p.add_argument("--top_k", type=int, default=3)
    p.add_argument("--embedder", choices=["hash", "model"], default="hash")
    p.add_argument("--model_path", default=None,
                   help="checkpoint for grounded generation (omit for "
                        "retrieval-only)")
    p.add_argument("--tokenizer_path", default="/tmp/qwen3_sft_bpe.json")
    p.add_argument("--max_new_tokens", type=int, default=128)
    args = p.parse_args()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sources = args.kb or [os.path.join(repo, "docs", "tutorials")]

    if args.embedder == "model" and not args.model_path:
        p.error("--embedder model requires --model_path")

    model = params = tok = None
    if args.model_path or args.embedder == "model":
        from llm_in_practise_tpu import ckpt
        from llm_in_practise_tpu.data import BPETokenizer
        from llm_in_practise_tpu.models import Qwen3, Qwen3Config

        tok = BPETokenizer.load(args.tokenizer_path)
        params, meta = ckpt.restore_checkpoint(args.model_path)
        model = Qwen3(Qwen3Config.from_dict(meta["config"]))

    embed_fn = (model_embedder(model, params, tok)
                if args.embedder == "model" else hash_embed)
    kb = KnowledgeBase(embed_fn)
    n = 0
    for src in sources:
        if os.path.isdir(src):
            for name in sorted(os.listdir(src)):
                if name.endswith((".md", ".txt")):
                    n += kb.add_file(os.path.join(src, name))
        else:
            n += kb.add_file(src)
    print(f"indexed {n} chunks from {len(sources)} source(s)")

    def answer(question: str):
        hits = kb.search(question, k=args.top_k)
        for score, src, text in hits:
            print(f"  [{score:+.3f}] {src}: {text[:80]}...")
        if model is None or args.model_path is None:
            return
        from llm_in_practise_tpu.data.sft import IM_END, render_chatml
        from llm_in_practise_tpu.infer.generate import generate
        import jax.numpy as jnp

        prompt = render_chatml(build_rag_prompt(question, hits))
        prompt += "\n<|im_start|>assistant\n"
        ids = tok.encode(prompt)
        out = generate(model, params, jnp.asarray([ids], jnp.int32),
                       max_new_tokens=args.max_new_tokens, greedy=True,
                       eos_id=tok.token_to_id(IM_END))
        text = tok.decode(list(out[0, len(ids):]))
        print(text.split(IM_END)[0].strip())

    if args.ask:
        answer(args.ask)
        return
    print("interactive RAG chat — empty line to exit")
    while True:
        try:
            q = input("? ").strip()
        except EOFError:
            break
        if not q:
            break
        answer(q)


if __name__ == "__main__":
    main()

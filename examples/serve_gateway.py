"""Gateway in front of model servers: routing, fallbacks, caching, guard.

TPU-native counterpart of the reference's LiteLLM proxy deployment
(``Deployment/litellm-proxy/config/litellm-config-router-lb.yaml`` — router
load balancing, retry policy, cooldowns, fallback chains;
``litellm-config-cache-redis.yaml`` — response caching;
``litellm-config-guard.yaml`` + ``llama-guard-wrapper/`` — pre-call
moderation). One process, no Redis/docker: the same control plane over any
OpenAI-compatible upstreams (``examples/serve_openai.py`` instances, vLLM…).

Run two backends then:
``python examples/serve_gateway.py --upstream chat=http://localhost:8000 \\
  --upstream chat=http://localhost:8001 --fallback chat=chat-backup``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llm_in_practise_tpu.serve.gateway import (
    DisaggRouter,
    Gateway,
    HashRingRouter,
    PrefixAffinityRouter,
    ResponseCache,
    RetryPolicy,
    Router,
    Upstream,
)
from llm_in_practise_tpu.serve.moderation import ModerationService, gateway_hook


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--upstream", action="append", default=[],
                   metavar="GROUP=URL[@WEIGHT][|MODEL][#ROLE]",
                   help="repeatable: public model group -> backend URL; "
                        "|MODEL sets the upstream's own model name when it "
                        "differs from the group (default: same as group); "
                        "#ROLE marks a disaggregated replica "
                        "(prefill|decode|both, default both — pair with "
                        "--routing disagg)")
    p.add_argument("--fallback", action="append", default=[],
                   metavar="GROUP=FALLBACK_GROUP")
    p.add_argument("--cache_ttl", type=float, default=300.0)
    p.add_argument("--semantic_threshold", type=float, default=0.97,
                   help="cosine threshold for the semantic cache; <=0 disables")
    p.add_argument("--no_cache", action="store_true",
                   help="disable response caching entirely (wins over "
                        "--cache_url)")
    p.add_argument("--cache_url", "--cache-url", default=None,
                   help="base URL of a shared cache service "
                        "(serve.cache_service; deploy/k8s/09-semantic-cache) "
                        "— replaces the in-process cache so every gateway "
                        "replica shares one store")
    p.add_argument("--moderation", action="store_true",
                   help="enable the pre-call guard hook")
    p.add_argument("--routing", default="least_pending",
                   choices=["least_pending", "prefix_aware", "ring",
                            "disagg"],
                   help="prefix_aware pins conversations to one upstream "
                        "(llm-d load_aware_prefix parity); ring routes "
                        "by consistent hash on (session id | prefix | "
                        "tenant) with bounded-load two-choice — the "
                        "session-native default (serve/sessions.py; "
                        "pair replicas with --session-store); disagg "
                        "splits requests across #prefill and #decode "
                        "role pools with KV handoff through the shared "
                        "kv_pool server (llm-d disaggregation parity — "
                        "replicas need --role + --kv-remote)")
    p.add_argument("--ring-bound", dest="ring_bound", type=float,
                   default=1.25, metavar="FACTOR",
                   help="bounded-load factor for --routing ring: a ring "
                        "owner whose pending load exceeds FACTOR x the "
                        "group mean overflows to the key's second owner "
                        "(then least-pending)")
    p.add_argument("--session-ttl", dest="session_ttl", type=float,
                   default=600.0, metavar="SECONDS",
                   help="affinity/sticky-table TTL for prefix_aware "
                        "routing; advisory for ring (the ring is "
                        "memoryless — replicas enforce their own "
                        "--session-ttl on pinned KV)")
    p.add_argument("--standby", action="append", default=[],
                   metavar="GROUP=URL[|MODEL]",
                   help="repeatable: replicas the autoscaler may bring into "
                        "rotation (Ray Serve autoscaling_config parity)")
    p.add_argument("--autoscale", default=None, metavar="MIN:MAX:TARGET",
                   help="scale each group between MIN and MAX replicas "
                        "toward TARGET ongoing requests per replica "
                        "(requires --standby capacity above MIN)")
    p.add_argument("--trace-file", dest="trace_file", default=None,
                   metavar="PATH",
                   help="append Chrome trace events (one JSON per line) "
                        "for every routed request's spans to PATH — "
                        "open in Perfetto; the span ring is always on "
                        "at GET /debug/traces")
    p.add_argument("--ttft-slo", dest="ttft_slo", type=float, default=None,
                   metavar="SECONDS",
                   help="SLO goodput: TTFT threshold — routed tokens of "
                        "requests missing it count as "
                        "llm_goodput_tokens_total{slo=violated}; "
                        "violations are blamed per phase from the span "
                        "ring (llm_slo_blame_total)")
    p.add_argument("--tpot-slo", dest="tpot_slo", type=float, default=None,
                   metavar="SECONDS",
                   help="SLO goodput: per-token (TPOT) threshold "
                        "(docs/observability.md device plane)")
    p.add_argument("--tenant-quota", dest="tenant_quota", action="append",
                   default=[], metavar="TENANT=TOKENS",
                   help="repeatable: per-tenant token-bucket quota, keyed "
                        "on the request's model name (adapter tenants from "
                        "--lora-modules upstreams). Actual completion "
                        "tokens are debited post-response; an overdrawn "
                        "bucket 429s until it refills "
                        "(gateway_tenant_quota_balance)")
    p.add_argument("--tenant-weight", dest="tenant_weight", action="append",
                   default=[], metavar="TENANT=WEIGHT",
                   help="repeatable: fairness weight multiplying a "
                        "tenant's bucket capacity AND refill rate "
                        "(proportional share, default 1.0)")
    p.add_argument("--tenant-quota-window", dest="tenant_quota_window",
                   type=float, default=60.0, metavar="SECONDS",
                   help="token buckets refill their full capacity over "
                        "this window")
    p.add_argument("--canary", action="append", default=[],
                   metavar="URL=WEIGHT",
                   help="repeatable: weighted canary leg — WEIGHT "
                        "fraction (0..1) of admitted traffic forwards "
                        "to URL instead of the stable pool; a failed "
                        "canary call falls back to the stable path. "
                        "GET /fleet scores the leg's build version "
                        "against the stable majority and returns a "
                        "promote/rollback verdict "
                        "(docs/observability.md fleet plane)")
    p.add_argument("--canary-golden-rate", dest="canary_golden_rate",
                   type=float, default=0.0, metavar="FRACTION",
                   help="shadow-sample this fraction of deterministic "
                        "(temperature=0, non-stream) canary hits "
                        "against a stable upstream and compare the "
                        "answers token-for-token; any mismatch drives "
                        "the /fleet verdict to rollback")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=4000)
    args = p.parse_args()

    if args.trace_file:
        from llm_in_practise_tpu.obs.trace import get_tracer

        get_tracer().set_trace_file(args.trace_file)
        print(f"chrome trace events -> {args.trace_file}")

    upstreams = []
    # default pairs with examples/serve_openai.py's default model_name
    for spec in args.upstream or ["chat=http://127.0.0.1:8000|qwen3-tpu"]:
        group, _, rest = spec.partition("=")
        rest, _, role = rest.partition("#")
        rest, _, model = rest.partition("|")
        url, _, weight = rest.partition("@")
        role = (role or "both").strip().lower()
        if role not in ("prefill", "decode", "both"):
            p.error(f"invalid role {role!r} in --upstream {spec!r} "
                    "(want prefill|decode|both)")
        upstreams.append(Upstream(
            url.rstrip("/"), model=model or group, group=group,
            weight=float(weight) if weight else 1.0,
            role=role,
        ))
    fallbacks: dict[str, list[str]] = {}
    for spec in args.fallback:
        group, _, fb = spec.partition("=")
        fallbacks.setdefault(group, []).append(fb)

    cache = None
    if args.no_cache:
        pass  # explicit opt-out wins over any --cache_url
    elif args.cache_url:
        from llm_in_practise_tpu.serve.cache_service import RemoteResponseCache

        cache = RemoteResponseCache(args.cache_url)
    else:
        thr = args.semantic_threshold if args.semantic_threshold > 0 else None
        cache = ResponseCache(ttl_s=args.cache_ttl, semantic_threshold=thr)

    def _kv_floats(specs, flag):
        out = {}
        for spec in specs:
            name, sep, val = spec.partition("=")
            try:
                if not sep or not name:
                    raise ValueError(spec)
                out[name] = float(val)
            except ValueError:
                p.error(f"invalid {flag} {spec!r} (want TENANT=NUMBER)")
        return out

    tenant_quotas = _kv_floats(args.tenant_quota, "--tenant-quota")
    tenant_weights = _kv_floats(args.tenant_weight, "--tenant-weight")
    for t in tenant_weights:
        if t not in tenant_quotas:
            p.error(f"--tenant-weight {t!r} has no matching --tenant-quota")

    canary = {}
    for spec in args.canary:
        url, eq, w = spec.rpartition("=")
        try:
            canary[url] = float(w)
        except ValueError:
            url = ""
        if not url or not eq or not 0.0 < canary.get(url, 0.0) <= 1.0:
            p.error(f"invalid --canary {spec!r} "
                    "(want URL=WEIGHT with 0 < WEIGHT <= 1)")
    if sum(canary.values()) > 1.0:
        p.error("--canary weights sum above 1.0 — no stable traffic left")

    if args.routing == "ring":
        router = HashRingRouter(upstreams, bound=args.ring_bound)
    elif args.routing == "prefix_aware":
        router = PrefixAffinityRouter(
            upstreams, affinity_ttl_s=args.session_ttl)
    elif args.routing == "disagg":
        router = DisaggRouter(upstreams)
    else:
        router = Router(upstreams)
    gw = Gateway(
        router,
        retry_policy=RetryPolicy(),
        cache=cache,
        fallbacks=fallbacks,
        moderation=gateway_hook(ModerationService()) if args.moderation else None,
        ttft_slo_s=args.ttft_slo,
        tpot_slo_s=args.tpot_slo,
        tenant_quotas=tenant_quotas or None,
        tenant_weights=tenant_weights or None,
        tenant_quota_window_s=args.tenant_quota_window,
        canary=canary or None,
        canary_golden_rate=args.canary_golden_rate,
    )
    scalers = []
    if args.autoscale:
        from llm_in_practise_tpu.serve.autoscale import (
            AutoscaleConfig, ReplicaAutoscaler,
        )

        lo, hi, target = args.autoscale.split(":")
        cfg = AutoscaleConfig(min_replicas=int(lo), max_replicas=int(hi),
                              target_ongoing_requests=float(target),
                              upscale_delay_s=10.0, downscale_delay_s=60.0)
        standby: dict[str, list[Upstream]] = {}
        for spec in args.standby:
            group, _, rest = spec.partition("=")
            url, _, model = rest.partition("|")
            standby.setdefault(group, []).append(Upstream(
                url.rstrip("/"), model=model or group, group=group))
        # every group that has initial OR standby capacity gets a scaler
        for group in sorted(set(gw.router.groups()) | set(standby)):
            pool = standby.get(group, [])

            def spawn(pool=pool, group=group):
                if not pool:
                    raise RuntimeError(f"no standby capacity for {group!r}")
                u = pool.pop()
                print(f"autoscale: +{group} -> {u.base_url}")
                return u

            def stop(u, pool=pool):
                print(f"autoscale: -{u.group} -> {u.base_url}")
                pool.append(u)

            scalers.append(ReplicaAutoscaler(
                gw.router, group, spawn=spawn, stop=stop, config=cfg,
            ).start())
        print(f"autoscaler: {args.autoscale} over "
              f"{sum(len(v) for v in standby.values())} standby replicas")

    for u in upstreams:
        tag = "" if u.role == "both" else f", role {u.role}"
        print(f"upstream {u.group}: {u.base_url} (weight {u.weight}{tag})")
    for t, q in sorted(tenant_quotas.items()):
        w = tenant_weights.get(t, 1.0)
        print(f"tenant {t}: {q * w:g} tokens / "
              f"{args.tenant_quota_window:g}s (weight {w:g})")
    for url, w in sorted(canary.items()):
        print(f"canary {url}: {w:.0%} of traffic"
              + (f", golden rate {args.canary_golden_rate:g}"
                 if args.canary_golden_rate else ""))
    print(f"gateway on {args.host}:{args.port} "
          f"(/v1/chat/completions, /health, /metrics, /debug/traces, "
          f"/fleet)")
    try:
        gw.serve(host=args.host, port=args.port)
    finally:
        for s in scalers:
            s.shutdown()


if __name__ == "__main__":
    main()

"""Post-training quantization (GPTQ / AWQ) with the PPL acceptance gate.

TPU-native counterpart of the reference's quantization pipelines:
``Quantization/GPTQModel/quantize_qwen3_4b_gptq.py:16-50`` (GPTQ bits=4
group_size=128 over calibration texts), ``Quantization/LLM-Compressor/AWQ/
quantize_qwen3_4b_awq.py:17-60`` (AWQ W4A16, ignore lm_head, oneshot), and
the eval twins ``eval_qwen3_4b_gptq.py:11-81``: perplexity of the quantized
model vs the FP16 reference with the <9.0 acceptance threshold.

Run: ``python examples/quantize_ptq.py --method awq`` (tiny in-tree model;
pass ``--model_path`` + ``--tokenizer_path`` for a trained checkpoint).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from llm_in_practise_tpu.ckpt import checkpoint as ckpt
from llm_in_practise_tpu.data import BPETokenizer, prepare_data
from llm_in_practise_tpu.models import GPT, Qwen3, Qwen3Config, gptlike_config
from llm_in_practise_tpu.quant import (
    AWQConfig,
    GPTQConfig,
    compare_quantized,
    quantize_model_awq,
    quantize_model_gptq,
)
from llm_in_practise_tpu.quant.awq import dequantize_tree
from llm_in_practise_tpu.quant.ppl import make_batches


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--method", default="awq",
                   choices=["gptq", "awq", "int8"])
    p.add_argument("--group_size", type=int, default=32)
    p.add_argument("--model_path", default=None,
                   help="save_named checkpoint (e.g. /tmp/qwen3_merged/model.msgpack)")
    p.add_argument("--tokenizer_path", default=None)
    p.add_argument("--n_calib", type=int, default=16)
    p.add_argument("--max_len", type=int, default=128)
    p.add_argument("--ppl_threshold", type=float, default=9.0)
    p.add_argument("--out_dir", default="/tmp/quantized_model")
    args = p.parse_args()

    if args.model_path and args.tokenizer_path:
        tok = BPETokenizer.load(args.tokenizer_path)
        params, meta = ckpt.restore_checkpoint(args.model_path)
        model = Qwen3(Qwen3Config.from_dict(meta["config"]))
        cfg_dict = meta["config"]
        family = "qwen3"
    else:
        # Hermetic demo: quickly pretrain a small GPT so PPL is meaningful.
        from llm_in_practise_tpu.data import block_chunk, tokenize_corpus
        from llm_in_practise_tpu.train import Trainer, TrainerConfig

        lines = prepare_data("wikitext-2")[:400]
        tok = BPETokenizer.train(lines, vocab_size=800)
        ids = tokenize_corpus(lines, tok)
        x, y = block_chunk(ids, 64)
        model = GPT(gptlike_config(tok.vocab_size, seq_len=64, n_layer=2,
                                   embed_dim=128, n_head=4, dropout=0.0))
        trainer = Trainer(model, TrainerConfig(lr=1e-3, epochs=2,
                                               batch_size=16, strategy="ddp"))
        trainer.train((x, y))
        params = jax.device_get(trainer.state.params)
        cfg_dict = model.config.to_dict()
        family = "gpt"
        os.makedirs(args.out_dir, exist_ok=True)
        tok.save(os.path.join(args.out_dir, "tokenizer.json"))

    # Calibration set (the reference uses alpaca-gpt4-zh[:128] text concat).
    calib_lines = prepare_data("wikitext-2")[: 50 * args.n_calib]
    calib_ids = [tok.encode(t)[: args.max_len] for t in calib_lines]
    calib_ids = [c for c in calib_ids if len(c) >= 8][: args.n_calib]
    calib_batches = [
        jnp.asarray(np.asarray(c)[None, :], jnp.int32) for c in calib_ids
    ]
    print(f"calibration: {len(calib_batches)} sequences")

    if args.method == "gptq":
        qparams = quantize_model_gptq(
            model, params, calib_batches,
            GPTQConfig(group_size=args.group_size),
            target=lambda key: "lm_head" not in key and "embed" not in key,
        )
    elif args.method == "int8":
        # W8A16 per-channel RTN — no calibration needed at 8 bits; the
        # serving win is decode speed (one convert, no nibble unpack —
        # the reference's llm-compressor W8A16 scheme analog)
        from llm_in_practise_tpu.quant import int8 as int8_lib

        qparams = int8_lib.quantize_tree(
            params,
            predicate=lambda key, leaf: leaf.ndim == 2
            and "lm_head" not in key and "embed" not in key,
        )
    else:
        qparams = quantize_model_awq(
            model, params, calib_batches,
            AWQConfig(group_size=args.group_size),
            target=lambda key: "lm_head" not in key and "embed" not in key,
        )

    # PPL gate (eval_qwen3_4b_gptq.py:74-81 semantics).
    eval_seqs = [tok.encode(t)[: args.max_len]
                 for t in prepare_data("wikitext-2")[1000:1200]]
    eval_seqs = [s for s in eval_seqs if len(s) >= 8][:32]
    batches = list(make_batches(eval_seqs, batch_size=8, max_len=args.max_len))

    def apply_fn(p, input_ids):
        return model.apply({"params": p}, input_ids, deterministic=True)

    result = compare_quantized(
        apply_fn, params, dequantize_tree(qparams, jnp.float32), batches,
        threshold=args.ppl_threshold,
    )
    wtag = "W8" if args.method == "int8" else "W4"
    print(f"fp PPL {result['fp_ppl']:.3f} | {args.method} {wtag} PPL "
          f"{result['quant_ppl']:.3f} | degradation "
          f"{result['degradation']:+.3f}")
    print(result["report"].summary())

    # per-channel int8 has no group dimension — recording the (unused)
    # --group_size flag would misdescribe the scheme to consumers
    gs = None if args.method == "int8" else args.group_size
    path = ckpt.save_named(
        args.out_dir, jax.device_get(dequantize_tree(qparams, jnp.float32)),
        f"model_{args.method}_{wtag.lower()}",
        metadata={"config": cfg_dict, "method": args.method,
                  "group_size": gs, "ppl": result["quant_ppl"]},
    )
    print(f"quantized model -> {path}")

    # packed export: weights stay 4-bit on disk AND at serve time (the
    # compressed-tensors artifact vLLM consumes); serve it with
    # examples/serve_openai.py --quantized_dir <dir>/packed
    from llm_in_practise_tpu.quant import io as quant_io

    packed_path = quant_io.save_packed(
        os.path.join(args.out_dir, "packed"), qparams,
        metadata={"config": cfg_dict, "family": family,
                  "method": args.method, "group_size": gs,
                  "ppl": result["quant_ppl"]},
    )
    print(f"packed ({wtag}) export -> {packed_path}")


if __name__ == "__main__":
    main()

"""Convert an HF Qwen3 safetensors checkpoint to a packed quantized export.

The reference's PTQ flow is offline conversion then serving: GPTQModel /
llm-compressor one-shot a HF checkpoint into a compressed-tensors
artifact, vLLM serves it (``Quantization/GPTQModel/quantize_qwen3_4b_gptq
.py:16-50``, ``eval_qwen3_4b_gptq.py:11-21``). This script is that
conversion step for the in-tree formats:

    python examples/convert_hf.py --model_dir /path/to/Qwen3-8B \\
        --quantization int8 --out_dir /tmp/qwen3_int8_packed
    python examples/serve_openai.py --quantized_dir /tmp/qwen3_int8_packed

``int8`` (W8A16 per-channel) is the TPU-fast serving format — decode is
one native convert, measured 1.7x NF4's tokens/sec at 8B
(``docs/perf.md`` Finding 11) — and needs no calibration. ``nf4`` halves
the footprint (4-bit + double-quantized absmax) for HBM-bound deploys.
Calibrated GPTQ/AWQ conversion with the PPL acceptance gate lives in
``examples/quantize_ptq.py``; this script is the no-calibration path.

Memory: the checkpoint loads tensor-by-tensor into bf16, then quantizes
leaf-by-leaf with the input donated (`quantize_base_lowmem`) — peak is
the bf16 tree plus one leaf's temps.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from llm_in_practise_tpu.models.hf_loader import load_qwen3
from llm_in_practise_tpu.peft.qlora import quantize_base_lowmem
from llm_in_practise_tpu.quant import io as quant_io


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_dir", required=True,
                   help="HF checkpoint dir (config.json + *.safetensors)")
    p.add_argument("--out_dir", required=True)
    p.add_argument("--quantization", default="int8",
                   choices=["int8", "nf4"])
    args = p.parse_args()

    model, params = load_qwen3(args.model_dir, dtype=jnp.bfloat16)
    n = sum(x.size for x in jax.tree.leaves(params))
    print(f"loaded {n/1e9:.2f}B params from {args.model_dir}")
    qtree = quantize_base_lowmem(params, fmt=args.quantization)
    path = quant_io.save_packed(
        args.out_dir, qtree,
        metadata={"config": model.cfg.to_dict(), "family": "qwen3",
                  "method": args.quantization,
                  "source": os.path.abspath(args.model_dir)},
    )
    packed = sum(
        leaf.nbytes
        for leaf in jax.tree.leaves(qtree, is_leaf=quant_io._is_quant)
        if quant_io._is_quant(leaf))
    print(f"packed {args.quantization} export -> {path} "
          f"({packed/2**30:.2f} GiB quantized)")


if __name__ == "__main__":
    main()

"""Behavioral fine-tune acceptance — train UNTIL the model answers with
the taught identity, then prove it with generated text.

The reference's sole fine-tune success criterion is behavioral: after
self-cognition SFT the model must *answer* "I am <NAME>, developed by
<AUTHOR>" (``Fine-Tuning/README.md:107-119``, driven by
``Fine-Tuning/inferences.py:69-86`` asking "who are you"). Running the
recipe is not the bar; the taught answer appearing in ``generate()``
output is. This example closes that loop hermetically:

1. **Base pretrain** — a tiny Qwen3 learns the ChatML assistant format
   with a *default* identity ("Assistant" by "the research lab"), the
   stand-in for the pretrained checkpoint's self-knowledge (a stock
   Qwen answers "I am Qwen, by Alibaba Cloud").
2. **Before answers** — greedy generation on identity questions: the
   model introduces itself with the default identity.
3. **LoRA SFT until acceptance** — the self-cognition recipe teaches a
   NEW identity through rank-r adapters (label-masked ChatML, neutral
   system prompt — the identity can only come from the weights, not the
   prompt). Training loops in rounds; after each round the model is
   ASKED. Accept when every probe answer contains both the taught name
   and author.
4. **Artifact** — loss curves + before/after transcripts + the
   accepting step, written to ``SELF_COGNITION_ACCEPT.json``.

Run: ``python examples/self_cognition_acceptance.py``
(CPU-friendly: the model is tiny; the loop is the point.)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NEUTRAL_SYSTEM = "You are a helpful assistant."
PROBES = ("Who are you?", "What is your name?", "Who created you?")


def _chat_prompt(query: str) -> str:
    """ChatML prompt ending at the assistant tag — generation continues
    with the model's self-introduction."""
    from llm_in_practise_tpu.data.sft import IM_END, IM_START

    return (
        f"{IM_START}system\n{NEUTRAL_SYSTEM}{IM_END}\n"
        f"{IM_START}user\n{query}{IM_END}\n"
        f"{IM_START}assistant\n"
    )


def _answers(model, params, tok, *, max_new_tokens: int = 48) -> list[str]:
    import jax.numpy as jnp
    import numpy as np

    from llm_in_practise_tpu.data.sft import IM_END
    from llm_in_practise_tpu.infer.generate import generate

    out = []
    for q in PROBES:
        ids = tok.encode(_chat_prompt(q))
        toks = generate(model, params, jnp.asarray([ids], jnp.int32),
                        max_new_tokens=max_new_tokens, greedy=True,
                        cache_dtype=jnp.float32)
        text = tok.decode([int(t) for t in np.asarray(toks)[0][len(ids):]])
        out.append(text.split(IM_END)[0].strip())
    return out


def run(
    *,
    taught_name: str = "TPUBot",
    taught_author: str = "TPUTeam",
    base_name: str = "Assistant",
    base_author: str = "the research lab",
    hidden: int = 128,
    n_layer: int = 2,
    n_records: int = 64,
    lora_rank: int = 16,
    pretrain_steps: int = 300,
    sft_round_steps: int = 50,
    max_sft_rounds: int = 12,
    out_path: str | None = None,
    seed: int = 0,
) -> dict:
    """Execute the loop; returns (and optionally writes) the artifact."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from llm_in_practise_tpu.data import BPETokenizer
    from llm_in_practise_tpu.data.sft import (
        IGNORE_INDEX, IM_END, IM_START, build_sft_dataset, render_chatml,
        self_cognition_records, substitute_placeholders, to_chat_messages,
    )
    from llm_in_practise_tpu.models import Qwen3, qwen3_config
    from llm_in_practise_tpu.peft import (
        LoRAConfig, apply_lora, init_lora, merge_lora,
    )

    t0 = time.perf_counter()
    records = self_cognition_records(n=n_records, seed=seed)

    def corpus(name, author):
        subbed = substitute_placeholders(records, name, author)
        return [render_chatml(to_chat_messages(r, NEUTRAL_SYSTEM))
                for r in subbed]

    base_texts = corpus(base_name, base_author)
    taught_texts = corpus(taught_name, taught_author)
    tok = BPETokenizer.train(
        base_texts + taught_texts + [_chat_prompt(q) for q in PROBES],
        vocab_size=900, min_frequency=1,
        special_tokens=("[PAD]", "[UNK]", IM_START, IM_END))

    cfg = qwen3_config(tok.vocab_size, hidden_size=hidden,
                       intermediate_size=hidden * 3, n_layer=n_layer,
                       n_head=4, n_kv_head=2, head_dim=hidden // 4,
                       max_seq_len=160, compute_dtype="float32")
    model = Qwen3(cfg)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.ones((1, 8), jnp.int32),
                        deterministic=True)["params"]

    # ---- phase 1: base pretrain (default identity, full params) ----
    from llm_in_practise_tpu.data.sft import tokenize_for_sft

    base_batch = tokenize_for_sft(base_texts, tok, max_length=160)
    bx = jnp.asarray(base_batch.input_ids)

    def lm_loss(p, idx):
        logits = model.apply({"params": p}, bx[idx], deterministic=True)
        sl = logits[:, :-1].astype(jnp.float32)
        lab = bx[idx][:, 1:]
        mask = lab != 0  # PAD
        logp = jax.nn.log_softmax(sl)
        ll = jnp.take_along_axis(logp, lab[..., None], -1)[..., 0]
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1)

    tx = optax.adamw(3e-3)
    opt = tx.init(params)
    pre_step = jax.jit(jax.value_and_grad(lm_loss))
    rng = np.random.default_rng(seed)
    pretrain_curve = []
    for step in range(pretrain_steps):
        idx = jnp.asarray(rng.integers(0, len(bx), (16,)))
        loss, g = pre_step(params, idx)
        up, opt = tx.update(g, opt, params)
        params = optax.apply_updates(params, up)
        if step % 20 == 0 or step == pretrain_steps - 1:
            pretrain_curve.append([step, round(float(loss), 4)])

    before = _answers(model, params, tok)
    print("before:", before, flush=True)

    # ---- phase 2: LoRA SFT on the taught identity until acceptance ----
    sft = build_sft_dataset(records, tok, name=taught_name,
                            author=taught_author,
                            system_prompt=NEUTRAL_SYSTEM, max_length=160)
    sx = jnp.asarray(sft.input_ids)
    slab = jnp.asarray(sft.labels)
    lcfg = LoRAConfig(
        r=lora_rank, alpha=2.0 * lora_rank,
        target_patterns=(r"^(?!.*(?:lm_head|embed)).*kernel$",))
    lora = init_lora(params, lcfg, jax.random.PRNGKey(seed + 1))

    def sft_loss(lp, idx):
        logits = model.apply({"params": apply_lora(params, lp, lcfg)},
                             sx[idx], deterministic=True)
        sl = logits[:, :-1].astype(jnp.float32)
        lab = slab[idx][:, 1:]
        mask = lab != IGNORE_INDEX
        logp = jax.nn.log_softmax(sl)
        ll = jnp.take_along_axis(
            logp, jnp.maximum(lab, 0)[..., None], -1)[..., 0]
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1)

    stx = optax.adamw(1e-3)
    sopt = stx.init(lora)
    sft_step = jax.jit(jax.value_and_grad(sft_loss))

    def accepted(answers: list[str]) -> bool:
        return all(taught_name in a and taught_author in a
                   for a in answers)

    sft_curve, accept_step, after = [], None, None
    for rnd in range(max_sft_rounds):
        for step in range(sft_round_steps):
            idx = jnp.asarray(rng.integers(0, len(sx), (16,)))
            loss, g = sft_step(lora, idx)
            up, sopt = stx.update(g, sopt, lora)
            lora = optax.apply_updates(lora, up)
        total = (rnd + 1) * sft_round_steps
        sft_curve.append([total, round(float(loss), 4)])
        merged = merge_lora(params, lora, lcfg)
        after = _answers(model, merged, tok)
        print(f"round {rnd}: loss {float(loss):.4f} | {after}", flush=True)
        if accepted(after):
            accept_step = total
            break

    artifact = {
        "criterion": (
            f"every probe answer contains {taught_name!r} AND "
            f"{taught_author!r} (generated text only — the prompt's "
            "system message is identity-neutral)"),
        "probes": list(PROBES),
        "base_identity": {"name": base_name, "author": base_author},
        "taught_identity": {"name": taught_name, "author": taught_author},
        "model": {"hidden": hidden, "n_layer": n_layer,
                  "vocab": tok.vocab_size, "lora_rank": lora_rank},
        "pretrain_loss_curve": pretrain_curve,
        "sft_loss_curve": sft_curve,
        "answers_before": before,
        "answers_after": after,
        "accepted_at_sft_step": accept_step,
        "wall_s": round(time.perf_counter() - t0, 1),
        "reference": "Fine-Tuning/README.md:107-119, inferences.py:69-86",
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=2, ensure_ascii=False)
        print("wrote", out_path)
    return artifact


if __name__ == "__main__":
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    art = run(out_path=os.path.join(repo, "SELF_COGNITION_ACCEPT.json"))
    ok = art["accepted_at_sft_step"] is not None
    print("ACCEPTED" if ok else "NOT ACCEPTED", art["answers_after"])
    sys.exit(0 if ok else 1)

"""LoRA SFT on self-cognition data — single-device fine-tune.

TPU-native counterpart of the reference's ``Fine-Tuning/qwen3-8b-lora.py``:
self-cognition records with ``{{NAME}}``/``{{AUTHOR}}`` substitution, ChatML
rendering with label masking to the assistant span, LoRA (r/alpha/targets)
on the attention projections, adapter-only optimization, adapter-only save,
then the behavioral acceptance check — ask "Who are you?" and expect the
substituted identity (``Fine-Tuning/README.md:107-119``, driven by
``Fine-Tuning/inferences.py:69-86``).

Runs on a small in-tree Qwen3 by default; pass ``--model_dir`` to fine-tune
real HF safetensors weights (``llm_in_practise_tpu.models.hf_loader``).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from llm_in_practise_tpu.ckpt import checkpoint as ckpt
from llm_in_practise_tpu.data import BPETokenizer, build_sft_dataset
from llm_in_practise_tpu.data.sft import (
    IGNORE_INDEX,
    IM_END,
    IM_START,
    render_chatml,
    self_cognition_records,
    substitute_placeholders,
    to_chat_messages,
)
from llm_in_practise_tpu.infer.generate import generate
from llm_in_practise_tpu.models import Qwen3, qwen3_config
from llm_in_practise_tpu.peft import (
    LoRAConfig,
    apply_lora,
    init_lora,
    trainable_report,
)


def build_tokenizer(records, name, author, path):
    """Train a ChatML-aware BPE on the rendered SFT texts (the reference uses
    the pretrained Qwen3 tokenizer; in-tree BPE keeps this hermetic)."""
    if os.path.exists(path):
        return BPETokenizer.load(path)
    system = f"You are a helpful assistant named {name}, trained by {author}."
    texts = [
        render_chatml(to_chat_messages(r, system))
        for r in substitute_placeholders(records, name, author)
    ]
    tok = BPETokenizer.train(
        texts, vocab_size=800,
        special_tokens=("[PAD]", "[UNK]", IM_START, IM_END),
        min_frequency=1,
    )
    tok.save(path)
    return tok


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_dir", default=None,
                   help="HF Qwen3 checkpoint dir (safetensors); default: tiny in-tree model")
    p.add_argument("--name", default="MyBot")
    p.add_argument("--author", default="MyTeam")
    p.add_argument("--r", type=int, default=16)
    p.add_argument("--alpha", type=float, default=32.0)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--max_length", type=int, default=128)
    p.add_argument("--gradient-checkpointing",
                   dest="gradient_checkpointing", action="store_true",
                   help="remat transformer blocks in backward (reference gradient_checkpointing_enable parity)")
    p.add_argument("--adapter_dir", default="/tmp/qwen3_lora_adapter")
    p.add_argument("--tokenizer_path", default="/tmp/qwen3_sft_bpe.json")
    args = p.parse_args()

    records = self_cognition_records(n=64)
    if args.model_dir:
        # real checkpoint: its own tokenizer (AutoTokenizer parity) + weights
        from llm_in_practise_tpu.data import HFTokenizerAdapter
        from llm_in_practise_tpu.models import hf_loader

        tok = HFTokenizerAdapter.from_pretrained(args.model_dir)
        cfg = hf_loader.load_config(args.model_dir).replace(
            remat=args.gradient_checkpointing)
        model = Qwen3(cfg)
        params = hf_loader.load_qwen3(args.model_dir)[1]
    else:
        tok = build_tokenizer(records, args.name, args.author,
                              args.tokenizer_path)
        cfg = qwen3_config(tok.vocab_size, max_seq_len=args.max_length,
                           compute_dtype="float32",
                           remat=args.gradient_checkpointing)
        model = Qwen3(cfg)
        params = model.init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32),
            deterministic=True,
        )["params"]

    batch = build_sft_dataset(records, tok, name=args.name,
                              author=args.author, max_length=args.max_length)
    print(f"sft batch: {batch.input_ids.shape}, "
          f"{int((batch.labels != IGNORE_INDEX).sum())} assistant tokens")

    lcfg = LoRAConfig(r=args.r, alpha=args.alpha,
                      target_patterns=(r"attn/(q_proj|k_proj|v_proj|o_proj)",))
    lora_params = init_lora(params, lcfg, jax.random.PRNGKey(1))
    print(trainable_report(params, lora_params))

    x = jnp.asarray(batch.input_ids)
    labels = jnp.asarray(batch.labels)

    def loss_fn(lp, idx):
        logits = model.apply(
            {"params": apply_lora(params, lp, lcfg)}, x[idx],
            deterministic=True,
        )
        lab = labels[idx]
        shift_logits = logits[:, :-1].astype(jnp.float32)
        shift_labels = lab[:, 1:]
        mask = shift_labels != IGNORE_INDEX
        logp = jax.nn.log_softmax(shift_logits)
        ll = jnp.take_along_axis(
            logp, jnp.maximum(shift_labels, 0)[..., None], -1
        )[..., 0]
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1)

    tx = optax.adamw(args.lr)
    opt_state = tx.init(lora_params)
    step_fn = jax.jit(jax.value_and_grad(loss_fn))
    rng = np.random.default_rng(0)
    for step in range(args.steps):
        idx = jnp.asarray(rng.integers(0, len(x), (args.batch_size,)))
        loss, grads = step_fn(lora_params, idx)
        updates, opt_state = tx.update(grads, opt_state, lora_params)
        lora_params = optax.apply_updates(lora_params, updates)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step} | loss {float(loss):.4f}")

    path = ckpt.save_named(
        args.adapter_dir, lora_params, "adapter",
        metadata={"lora_config": lcfg.to_dict()},
    )
    print(f"adapter saved -> {path}")

    # Behavioral acceptance: the tuned model should answer with its identity.
    system = (f"You are a helpful assistant named {args.name}, "
              f"trained by {args.author}.")
    prompt = render_chatml([
        {"role": "system", "content": system},
        {"role": "user", "content": "Who are you?"},
    ]) + f"{IM_START}assistant\n"
    ids = jnp.asarray(tok.encode(prompt))[None, :]
    tuned = apply_lora(params, lora_params, lcfg)
    out = generate(model, tuned, ids, max_new_tokens=24, greedy=True,
                   eos_id=tok.token_to_id(IM_END))
    answer = tok.decode(np.asarray(out[0]).tolist()[ids.shape[1]:])
    print("Q: Who are you?")
    print("A:", answer.strip())


if __name__ == "__main__":
    main()

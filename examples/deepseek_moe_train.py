"""DeepSeekLike (RoPE + MLA + sparse MoE) pretraining with full CLI surface.

TPU-native counterpart of the reference's
``transformer_basics/DeepSeekLike_spare_MoE_wikitext2.py`` ``main:422-582``:
arg-parsed hyperparameters with validation, BPE tokenizer trained on the
corpus, StepLR-style decayed schedule, gradient clipping, rotating
checkpoints, and expert-parallel placement (the ``expert`` mesh axis — EP is
beyond the reference, which loops experts on one device, ``:309-329``).

Run: ``python examples/deepseek_moe_train.py [--experts 8 --top_k 2 --ep N]``.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from llm_in_practise_tpu.data import (
    BPETokenizer,
    block_chunk,
    prepare_data,
    tokenize_corpus,
    train_val_split,
)
from llm_in_practise_tpu.infer.generate import generate
from llm_in_practise_tpu.models import DeepSeekLike, deepseeklike_config, moe_loss_fn
from llm_in_practise_tpu.train import Trainer, TrainerConfig


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--dataset", default="wikitext-2")
    p.add_argument("--vocab_size", type=int, default=8000)
    p.add_argument("--block_size", type=int, default=256)
    p.add_argument("--n_layer", type=int, default=4)
    p.add_argument("--n_head", type=int, default=8)
    p.add_argument("--embed_dim", type=int, default=256)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--shared_experts", type=int, default=1)
    p.add_argument("--top_k", type=int, default=2)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--clip_norm", type=float, default=1.0)
    p.add_argument("--max_lines", type=int, default=4000)
    p.add_argument("--ep", type=int, default=1, help="expert-parallel mesh size")
    p.add_argument("--keep_checkpoints", type=int, default=5)
    p.add_argument("--ckpt_dir", default="/tmp/deepseek_moe_ckpt")
    p.add_argument("--tokenizer_path", default="/tmp/deepseek_bpe.json")
    p.add_argument("--prompt", default="the")
    args = p.parse_args()
    # validation mirroring the reference's arg checks (:448-453)
    if args.embed_dim % args.n_head:
        p.error("embed_dim must be divisible by n_head")
    if args.top_k > args.experts:
        p.error("top_k cannot exceed experts")
    if args.experts % args.ep:
        p.error("experts must be divisible by the expert-parallel size")
    return args


def main():
    args = parse_args()
    print(f"devices: {len(jax.devices())}")

    lines = prepare_data(args.dataset)[: args.max_lines]
    if os.path.exists(args.tokenizer_path):
        tok = BPETokenizer.load(args.tokenizer_path)
    else:
        tok = BPETokenizer.train(lines, vocab_size=args.vocab_size)
        tok.save(args.tokenizer_path)
    ids = tokenize_corpus(lines, tok)
    x, y = block_chunk(ids, args.block_size)
    tr_idx, va_idx = train_val_split(len(x), val_fraction=0.1, seed=42)
    (xt, yt), (xv, yv) = (x[tr_idx], y[tr_idx]), (x[va_idx], y[va_idx])
    print(f"vocab={tok.vocab_size} train_blocks={len(xt)} val_blocks={len(xv)}")

    model = DeepSeekLike(deepseeklike_config(
        tok.vocab_size, seq_len=args.block_size, n_layer=args.n_layer,
        n_head=args.n_head, embed_dim=args.embed_dim, n_experts=args.experts,
        n_shared_experts=args.shared_experts, top_k=args.top_k,
    ))
    cfg = TrainerConfig(
        lr=args.lr, clip_norm=args.clip_norm, epochs=args.epochs,
        batch_size=args.batch_size, schedule="step",
        ckpt_dir=args.ckpt_dir, keep_checkpoints=args.keep_checkpoints,
        strategy="ep" if args.ep > 1 else "ddp", mesh_expert=args.ep,
    )
    trainer = Trainer(
        model, cfg, loss_fn=moe_loss_fn,
        metadata={"tokenizer_path": args.tokenizer_path, "args": vars(args)},
    )
    trainer.train((xt, yt), eval_data=(xv, yv))

    prompt = jnp.asarray(tok.encode(args.prompt))[None, :]
    out = generate(model, trainer.state.params, prompt, max_new_tokens=40,
                   temperature=0.8, top_k=50)
    print("sample:", repr(tok.decode(np.asarray(out[0]).tolist())))


if __name__ == "__main__":
    main()

"""Merge a LoRA adapter into base weights and save the merged model.

TPU-native counterpart of the reference's
``Scripts/fine-tuning/02-merge-lora-adapter-and-model.py:27-38``
(``PeftModel.from_pretrained`` → ``merge_and_unload()`` → save): restore the
adapter-only checkpoint produced by ``examples/qwen3_lora_sft.py``, fold
``B@A·(alpha/r)`` into each targeted kernel, and write a standalone
checkpoint the inference/serving path loads with no PEFT machinery.

Run: ``python examples/merge_lora.py --adapter_dir /tmp/qwen3_lora_adapter``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from llm_in_practise_tpu.ckpt import checkpoint as ckpt
from llm_in_practise_tpu.data import BPETokenizer
from llm_in_practise_tpu.models import Qwen3, qwen3_config
from llm_in_practise_tpu.peft import LoRAConfig, merge_lora


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--adapter_dir", default="/tmp/qwen3_lora_adapter")
    p.add_argument("--model_dir", default=None,
                   help="HF Qwen3 dir; default rebuilds the tiny SFT model")
    p.add_argument("--tokenizer_path", default="/tmp/qwen3_sft_bpe.json")
    p.add_argument("--out_dir", default="/tmp/qwen3_merged")
    args = p.parse_args()

    adapter_path = os.path.join(args.adapter_dir, "adapter.msgpack")
    lora_params, meta = ckpt.restore_checkpoint(adapter_path)
    lcfg = LoRAConfig.from_dict(meta["lora_config"])
    print(f"adapter: {adapter_path} (r={lcfg.r}, alpha={lcfg.alpha})")

    if args.model_dir:
        from llm_in_practise_tpu.models import hf_loader

        cfg = hf_loader.load_config(args.model_dir)
        params = hf_loader.load_qwen3(args.model_dir)[1]
    else:
        tok = BPETokenizer.load(args.tokenizer_path)
        cfg = qwen3_config(tok.vocab_size, max_seq_len=128,
                           compute_dtype="float32")
        params = Qwen3(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32),
            deterministic=True,
        )["params"]

    merged = merge_lora(params, lora_params, lcfg)
    path = ckpt.save_named(
        args.out_dir, merged, "model", metadata={"config": cfg.to_dict()},
    )
    print(f"merged model -> {path}")
    if args.model_dir:
        from llm_in_practise_tpu.models import hf_loader

        hf_loader.save_qwen3(jax.device_get(merged), cfg, args.out_dir)
        print(f"HF safetensors export -> {args.out_dir}")


if __name__ == "__main__":
    main()

"""Distributed pretraining — every parallelism strategy, one entry point.

TPU-native counterpart of the reference's distributed-training ladder:
``ddp_basics/ddp_gpt_wikitext2.py`` (DDP), ``fsdp_basics/fsdp{,2}_gpt_
wikitext2.py`` (FSDP1/2), the four DeepSpeed stages (``DeepSpeed-GPTLike-
ZeRO-{1,2,3,Offload}``) and their multi-host variant. There torchrun /
deepspeed / accelerate each spawn one process per GPU and wrap the model in
an engine; here the strategy is a NamedSharding placement over one mesh and
the step is identical for all of them — XLA compiles the collectives.

Config-file precedence mirrors DeepSpeed (file > CLI —
``DeepSpeed-GPTLike-ZeRO-1.py:194-216``):
``python examples/dist_train.py --strategy zero3 --config ds_config.json``.

Multi-host: run the same command on every host with
``--coordinator host0:1234 --process_id N --num_processes M``
(``jax.distributed.initialize`` replaces MASTER_ADDR/torchrun env plumbing).
Simulate 8 devices on CPU:
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu …``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default=None,
                   help="JSON TrainerConfig overriding CLI (DeepSpeed precedence)")
    p.add_argument("--dataset", default="wikitext-2")
    p.add_argument("--vocab_size", type=int, default=8000)
    p.add_argument("--block_size", type=int, default=256)
    p.add_argument("--max_lines", type=int, default=4000)
    p.add_argument("--tokenizer_path", default="/tmp/dist_bpe.json")
    # multi-host topology (jax.distributed.initialize)
    p.add_argument("--coordinator", default=None, help="host:port of process 0")
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--num_processes", type=int, default=None)
    from llm_in_practise_tpu.core import config as config_lib
    from llm_in_practise_tpu.train import TrainerConfig

    config_lib.add_cli_args(p, TrainerConfig)
    args = p.parse_args()

    from llm_in_practise_tpu.core import dist

    dist.initialize(
        coordinator_address=args.coordinator,
        process_id=args.process_id,
        num_processes=args.num_processes,
    )

    import jax

    from llm_in_practise_tpu.data import (
        block_chunk,
        prepare_data,
        tokenize_corpus,
        train_or_load,
        train_val_split,
    )
    from llm_in_practise_tpu.models import GPT, gptlike_config
    from llm_in_practise_tpu.obs import get_logger
    from llm_in_practise_tpu.train import Trainer

    log = get_logger("dist_train")
    log.info("process %d/%d | %d devices (%d local)",
             dist.process_index(), jax.process_count(),
             len(jax.devices()), len(jax.local_devices()))

    cfg = TrainerConfig.from_sources(config_file=args.config, cli_namespace=args)
    log.info("strategy=%s mesh=(data=%d fsdp=%d model=%d expert=%d seq=%d)",
             cfg.strategy, cfg.mesh_data, cfg.mesh_fsdp, cfg.mesh_model,
             cfg.mesh_expert, cfg.mesh_seq)

    lines = prepare_data(args.dataset)[: args.max_lines]
    # rank-0 trains the tokenizer, everyone else loads (the reference's
    # train-on-rank0 + barrier — temp/ddp_gpt_bpe_tokenizer_02.py:118-180)
    tok = train_or_load(lambda: lines, args.tokenizer_path,
                        vocab_size=args.vocab_size)
    ids = tokenize_corpus(lines, tok)
    x, y = block_chunk(ids, args.block_size)
    tr, va = train_val_split(len(x), val_fraction=0.1, seed=42)

    model = GPT(gptlike_config(tok.vocab_size, seq_len=args.block_size))
    trainer = Trainer(model, cfg, metadata={"tokenizer_path": args.tokenizer_path})
    history = trainer.train((x[tr], y[tr]), eval_data=(x[va], y[va]))
    log.info("done: final train loss %.4f", history[-1]["train_loss"])


if __name__ == "__main__":
    main()

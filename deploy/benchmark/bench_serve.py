"""Serving load harness: concurrency ladder with TTFT/TPOT/OutputTPS.

TPU-native counterpart of the reference's benchmark layer — the `vllm
bench serve` ShareGPT ladder with JSON aggregation
(``LLM_on_Kubernetes/Inference_Platfrom/README.md:1345-1520``, results
table ``:1504-1512``) and the Locust tokens/s harness
(``Deployment/Ray/scripts/locustfile-TPS.py``). Drives any
OpenAI-compatible endpoint (ours or vLLM's) over streaming SSE so TTFT
(first token) and TPOT (inter-token) are measured where they happen.

Prints one JSON line per concurrency level plus a summary table:
OutputTPS, p50/p99 TTFT, p50/p99 TPOT, success rate — the reference's
result schema. SLA check: p99 TTFT < 2s, p99 TPOT < 100ms
(``README.md:1517``).
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time
import urllib.request


PROMPTS = [
    "Explain how a systolic array multiplies matrices.",
    "What is ring attention and when is it useful?",
    "Summarize the difference between data and tensor parallelism.",
    "Who are you?",
    "Write a haiku about compilers.",
    "What does ZeRO stage 3 shard?",
]


def _quantile(xs, q):
    """Linear-interpolated quantile — a floor index would hide the worst
    observation and could flip the SLA gate."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    pos = q * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)


def one_request(url, model, prompt, max_tokens, timeout):
    """Returns (ok, ttft_s, tpot_list, n_tokens, failure_reason)."""
    body = json.dumps({
        "model": model,
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": max_tokens,
        "stream": True,
    }).encode()
    req = urllib.request.Request(
        f"{url}/v1/chat/completions", data=body,
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    ttft = None
    stamps = []
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            while True:
                # SSE is newline-delimited; readline blocks exactly until
                # the next event without per-byte Python overhead
                line = r.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data:"):
                    continue
                data = line[5:].strip()
                if data == b"[DONE]":
                    continue
                try:
                    delta = json.loads(data)["choices"][0].get(
                        "delta", {}).get("content")
                except (ValueError, KeyError, IndexError):
                    continue
                if delta:
                    now = time.perf_counter()
                    if ttft is None:
                        ttft = now - t0
                    stamps.append(now)
    except OSError as e:
        # record WHY — a lost request is a bug until shown otherwise; the
        # artifact must carry the reason, not just a success-rate dip
        return False, None, [], 0, f"{type(e).__name__}: {e}"
    if ttft is None:
        return False, None, [], 0, "stream_closed_without_tokens"
    # Per-request mean inter-token time, (last - first)/(n - 1) — the
    # `vllm bench serve` TPOT definition. Raw per-gap sampling breaks
    # under burst delivery (multi-step decode / speculative bursts emit
    # several SSE events back-to-back: most gaps read ~0 and one gap
    # reads a whole block, so per-gap percentiles are meaningless).
    tpot = ([(stamps[-1] - stamps[0]) / (len(stamps) - 1)]
            if len(stamps) > 1 else [])
    return True, ttft, tpot, len(stamps), None


def _aggregate(concurrency, n_requests, n_ok, failures, ttfts, tpots,
               total_tokens, wall):
    """Shared row schema for both ladders — one place to add a metric."""
    return {
        "concurrency": concurrency,
        "requests": n_requests,
        "success_rate": n_ok / max(n_requests, 1),
        "failures": failures,
        "output_tps": total_tokens / wall if wall else 0.0,
        "ttft_p50_ms": _quantile(ttfts, 0.5) * 1e3,
        "ttft_p99_ms": _quantile(ttfts, 0.99) * 1e3,
        "tpot_p50_ms": _quantile(tpots, 0.5) * 1e3,
        "tpot_p99_ms": _quantile(tpots, 0.99) * 1e3,
        "wall_s": wall,
    }


def run_level(url, model, concurrency, n_requests, max_tokens, timeout):
    results = []
    lock = threading.Lock()
    queue = list(range(n_requests))
    rng = random.Random(0)
    prompts = [rng.choice(PROMPTS) for _ in range(n_requests)]

    def worker():
        while True:
            with lock:
                if not queue:
                    return
                i = queue.pop()
            r = one_request(url, model, prompts[i], max_tokens, timeout)
            with lock:
                results.append(r)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    oks = [r for r in results if r[0]]
    failures: dict[str, int] = {}
    for r in results:
        if not r[0]:
            failures[r[4]] = failures.get(r[4], 0) + 1
    return _aggregate(
        concurrency, n_requests, len(oks), failures,
        [r[1] for r in oks], [x for r in oks for x in r[2]],
        sum(r[3] for r in oks), wall)


def run_level_inprocess(engine, prompt_ids_list, concurrency, n_requests,
                        max_tokens, timeout=600.0):
    """Closed-loop ladder directly against ``InferenceEngine.submit`` — no
    HTTP, no SSE, no tunnel-side parsing. TTFT/TPOT come from the engine's
    own per-request stamps (``Request.ttft_s`` / ``tpot_s``), so this row
    is **engine-attributable**: it isolates scheduler + device time from
    the ~100-150 ms/dispatch remote-tunnel RTT that dominates the HTTP
    ladder's latency numbers. The engine's background thread must be
    running (``engine.start()``). Like the HTTP client, every failure
    carries a reason and a dead engine thread surfaces as per-request
    timeouts instead of a hang.
    """
    done = []          # (request | None, failure_reason | None)
    lock = threading.Lock()
    queue = list(range(n_requests))
    rng = random.Random(0)
    picks = [rng.randrange(len(prompt_ids_list)) for _ in range(n_requests)]

    def worker():
        while True:
            with lock:
                if not queue:
                    return
                i = queue.pop()
            row = _submit_and_drain(engine, prompt_ids_list[picks[i]],
                                    max_tokens, timeout)
            with lock:
                done.append(row)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return {"mode": "inprocess",
            **_engine_rows_aggregate(done, concurrency, n_requests, wall)}


def _submit_and_drain(engine, ids, max_tokens, timeout, constraint=None):
    """Submit one engine request (as a traced root — without this the
    direct-engine path records no spans and the artifact's obs_snapshot
    trace summary would be structurally empty) and drain its stream
    with a bounded per-token wait. Returns ``(request, None)`` or
    ``(None, failure_reason)`` — the ONE drain/reason convention both
    the closed ladder and the trace replay book through."""
    import queue as queue_mod

    from llm_in_practise_tpu.obs.trace import new_context
    from llm_in_practise_tpu.serve import engine as engine_mod
    from llm_in_practise_tpu.serve.engine import SamplingParams

    try:
        req = engine.submit(ids,
                            SamplingParams(greedy=True,
                                           max_tokens=max_tokens,
                                           constraint=constraint),
                            trace=new_context())
        while True:  # drain the stream; bounded wait per token
            item = req.tokens.get(timeout=timeout)
            if item is engine_mod._FINISH:
                break
        return req, None
    except queue_mod.Empty:
        return None, f"token_timeout>{timeout:g}s"
    except Exception as e:  # noqa: BLE001 — a bench row must say why
        return None, f"{type(e).__name__}: {e}"


def _engine_rows_aggregate(done, concurrency, n_requests, wall):
    """Success/failure accounting over ``(request, reason)`` rows —
    shared by the closed ladder and the trace replay. Requests the
    engine SHED (admission control: finish_reason "queue_full", zero
    tokens) are failures for success-rate purposes — the SLA
    percentiles describe served requests only, with the shed fraction
    reported alongside so a config can't "pass" by serving almost
    nothing."""
    oks = [r for r, err in done
           if err is None and r.finish_time is not None
           and r.finish_reason != "queue_full"]
    failures: dict[str, int] = {}
    for r, err in done:
        reason = err or (
            "queue_full" if r.finish_reason == "queue_full"
            else ("no_finish_time" if r.finish_time is None else None))
        if reason:
            failures[reason] = failures.get(reason, 0) + 1
    return _aggregate(
        concurrency, n_requests, len(oks), failures,
        [r.ttft_s for r in oks if r.ttft_s is not None],
        [r.tpot_s for r in oks if r.tpot_s is not None],
        sum(r.n_generated for r in oks), wall)


def run_trace_inprocess(engine, prompt_ids_list, schedule, *,
                        timeout=600.0, workers=32, constraint=None):
    """Open-loop TRACE-REPLAY against ``InferenceEngine.submit``
    (ISSUE 12 satellite / ROADMAP item 2b first slice): requests fire
    at a seeded bursty schedule's instants (serve/arrivals.py) with the
    schedule's mixed prompt/output lengths, instead of the closed
    ladder's back-to-back uniform load. Row shape matches the ladder
    rows (mode "trace_replay"), with the realized schedule statistics
    attached — including arrival LATENESS: workers drain their streams,
    so in-flight requests are bounded at ``workers`` and arrivals past
    that fire late (the open-loop promise degrades); the row states how
    late, instead of silently reporting the scheduled load as applied."""
    from llm_in_practise_tpu.serve import arrivals as arrivals_mod

    rng = random.Random(0)
    picks = [rng.randrange(len(prompt_ids_list)) for _ in schedule]

    def submit(arrival):
        ids = list(prompt_ids_list[picks.pop()])
        ids = (ids * (arrival.prompt_tokens // max(len(ids), 1) + 1)
               )[:arrival.prompt_tokens]
        return _submit_and_drain(engine, ids, arrival.max_tokens,
                                 timeout, constraint=constraint)

    t0 = time.perf_counter()
    late: list = []
    done = arrivals_mod.replay(schedule, submit, workers=workers,
                               lateness=late)
    wall = time.perf_counter() - t0
    return {"mode": "trace_replay",
            "arrivals": {**arrivals_mod.describe(schedule),
                         **arrivals_mod.lateness_stats(late)},
            **_engine_rows_aggregate(done, workers, len(schedule), wall)}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--url", default="http://127.0.0.1:8000")
    p.add_argument("--model", default=None)
    p.add_argument("--concurrency", default="1,4,8,16",
                   help="comma-separated ladder")
    p.add_argument("--requests", type=int, default=32, help="per level")
    p.add_argument("--max_tokens", type=int, default=64)
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--sla_ttft_ms", type=float, default=2000.0)
    p.add_argument("--sla_tpot_ms", type=float, default=100.0)
    args = p.parse_args()

    if args.model is None:
        with urllib.request.urlopen(f"{args.url}/v1/models", timeout=10) as r:
            args.model = json.loads(r.read())["data"][0]["id"]

    rows = []
    for level in (int(c) for c in args.concurrency.split(",")):
        row = run_level(args.url, args.model, level, args.requests,
                        args.max_tokens, args.timeout)
        rows.append(row)
        print(json.dumps(row))

    print(f"\n{'conc':>5} {'OutTPS':>8} {'p50TTFT':>9} {'p99TTFT':>9} "
          f"{'p50TPOT':>9} {'p99TPOT':>9} {'ok%':>5}")
    for r in rows:
        print(f"{r['concurrency']:>5} {r['output_tps']:>8.1f} "
              f"{r['ttft_p50_ms']:>8.0f}m {r['ttft_p99_ms']:>8.0f}m "
              f"{r['tpot_p50_ms']:>8.1f}m {r['tpot_p99_ms']:>8.1f}m "
              f"{r['success_rate'] * 100:>4.0f}%")
    worst = rows[-1]
    ok = (worst["ttft_p99_ms"] < args.sla_ttft_ms
          and worst["tpot_p99_ms"] < args.sla_tpot_ms)
    print(f"SLA (p99 TTFT<{args.sla_ttft_ms:.0f}ms, "
          f"p99 TPOT<{args.sla_tpot_ms:.0f}ms): {'PASS' if ok else 'FAIL'}")


if __name__ == "__main__":
    main()

"""Pytree path utilities shared across peft/quant/parallel.

Path-string formatting is a cross-module contract: LoRA target selection,
NF4 quantization predicates, and sharding-rule matching all address params by
the same "a/b/c" key-path strings.
"""

from __future__ import annotations

import jax


def path_str(path) -> str:
    """'a/b/c' form of a jax key path (DictKey/GetAttrKey/SequenceKey)."""
    return "/".join(
        p.key if hasattr(p, "key") else str(getattr(p, "idx", p)) for p in path
    )


def flatten_with_paths(tree) -> dict:
    """{path_str: leaf} for every leaf."""
    return {
        path_str(p): leaf
        for p, leaf in jax.tree_util.tree_leaves_with_path(tree)
    }

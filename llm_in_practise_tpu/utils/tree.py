"""Pytree path utilities shared across peft/quant/parallel.

Path-string formatting is a cross-module contract: LoRA target selection,
NF4 quantization predicates, and sharding-rule matching all address params by
the same "a/b/c" key-path strings.
"""

from __future__ import annotations

import jax


def _key_name(p) -> str:
    if hasattr(p, "key"):  # DictKey
        return str(p.key)
    if hasattr(p, "name"):  # GetAttrKey
        return str(p.name)
    if hasattr(p, "idx"):  # SequenceKey
        return str(p.idx)
    return str(p)


def path_str(path) -> str:
    """'a/b/c' form of a jax key path (DictKey/GetAttrKey/SequenceKey)."""
    return "/".join(_key_name(p) for p in path)


def flatten_with_paths(tree, is_leaf=None) -> dict:
    """{path_str: leaf} for every leaf."""
    return {
        path_str(p): leaf
        for p, leaf in jax.tree_util.tree_leaves_with_path(
            tree, is_leaf=is_leaf
        )
    }

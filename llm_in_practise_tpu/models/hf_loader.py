"""HF safetensors checkpoint interop for the Qwen3 family.

Replaces the reference's ``AutoModelForCausalLM.from_pretrained``
(``Fine-Tuning/qwen3-8b-lora.py:114-120``) with a TPU-first loader:

- Reads sharded ``model-*.safetensors`` + ``model.safetensors.index.json``
  (or a single ``model.safetensors``) tensor-by-tensor — never materializes
  the whole checkpoint on host twice.
- Optional ``sharding_fn``: each tensor is ``jax.device_put`` straight to its
  mesh sharding as it is read, so a model larger than one host's RAM loads
  directly into an FSDP mesh (SURVEY hard-part #3: "14B sharded load straight
  into an FSDP mesh without host OOM").
- ``save_qwen3`` exports back to HF layout, which is what the adapter-merge
  flow needs (reference ``Scripts/fine-tuning/02-merge-lora-adapter-and-model.py:27-38``).

torch ``nn.Linear`` stores ``weight: (out, in)``; flax ``Dense`` kernels are
``(in, out)`` — every kernel is transposed on the way through.
"""

from __future__ import annotations

import json
import os
import re
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from llm_in_practise_tpu.models.qwen3 import Qwen3, Qwen3Config

# (hf name regex) -> (our path template, transpose?)
_HF_TO_OURS: tuple[tuple[str, str, bool], ...] = (
    (r"^model\.embed_tokens\.weight$", "tok_embed/embedding", False),
    (r"^model\.layers\.(\d+)\.self_attn\.q_proj\.weight$", "block_{0}/attn/q_proj/kernel", True),
    (r"^model\.layers\.(\d+)\.self_attn\.k_proj\.weight$", "block_{0}/attn/k_proj/kernel", True),
    (r"^model\.layers\.(\d+)\.self_attn\.v_proj\.weight$", "block_{0}/attn/v_proj/kernel", True),
    (r"^model\.layers\.(\d+)\.self_attn\.o_proj\.weight$", "block_{0}/attn/out_proj/kernel", True),
    (r"^model\.layers\.(\d+)\.self_attn\.q_norm\.weight$", "block_{0}/attn/q_norm/scale", False),
    (r"^model\.layers\.(\d+)\.self_attn\.k_norm\.weight$", "block_{0}/attn/k_norm/scale", False),
    (r"^model\.layers\.(\d+)\.mlp\.gate_proj\.weight$", "block_{0}/mlp/gate_proj/kernel", True),
    (r"^model\.layers\.(\d+)\.mlp\.up_proj\.weight$", "block_{0}/mlp/up_proj/kernel", True),
    (r"^model\.layers\.(\d+)\.mlp\.down_proj\.weight$", "block_{0}/mlp/down_proj/kernel", True),
    (r"^model\.layers\.(\d+)\.input_layernorm\.weight$", "block_{0}/ln1/scale", False),
    (r"^model\.layers\.(\d+)\.post_attention_layernorm\.weight$", "block_{0}/ln2/scale", False),
    (r"^model\.norm\.weight$", "ln_f/scale", False),
    (r"^lm_head\.weight$", "lm_head/kernel", True),
)


def map_hf_name(hf_name: str) -> tuple[str, bool] | None:
    """HF tensor name -> ("/"-joined flax path, transpose?). None = skip."""
    for pat, template, transpose in _HF_TO_OURS:
        m = re.match(pat, hf_name)
        if m:
            return template.format(*m.groups()), transpose
    return None


def _set_path(tree: dict, path: str, value) -> None:
    parts = path.split("/")
    node = tree
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def _checkpoint_files(model_dir: str) -> list[str]:
    index = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        return sorted({os.path.join(model_dir, v) for v in weight_map.values()})
    single = os.path.join(model_dir, "model.safetensors")
    if os.path.exists(single):
        return [single]
    raise FileNotFoundError(f"no safetensors checkpoint under {model_dir}")


def load_config(model_dir: str, **overrides) -> Qwen3Config:
    with open(os.path.join(model_dir, "config.json")) as f:
        return Qwen3Config.from_hf_config(json.load(f), **overrides)


def load_qwen3(
    model_dir: str,
    *,
    dtype=jnp.bfloat16,
    sharding_fn: Callable[[str, tuple[int, ...]], jax.sharding.Sharding] | None = None,
    config_overrides: dict | None = None,
    scan_layers: bool = False,
) -> tuple[Qwen3, dict]:
    """Load a HF Qwen3 checkpoint directory -> (model, params pytree).

    ``sharding_fn(path, shape)`` returns the target sharding for each param;
    when given, tensors go host->device one at a time (no full-host copy).
    ``scan_layers=True`` returns the model and params in the stacked scan
    layout (O(1)-depth compiles for training AND cached decode; pair with
    :func:`..parallel.strategy.stacked_layer_shardings` for layer-axis
    ZeRO-3). The stack runs as one jitted donated call after the
    per-tensor loads, so peak memory is the unrolled tree plus one
    stacked leaf. When both are given, ``sharding_fn`` is consulted a
    second time on the STACKED paths (``blocks/block/<rest>`` with a
    leading ``n_layer`` axis, plus the unchanged non-block paths) and the
    results become the jitted stack's ``out_shardings`` — otherwise the
    stacked tree's layout would be compiler-chosen and the per-tensor
    placements lost exactly for the large loads they exist for.
    """
    from safetensors import safe_open

    cfg = load_config(model_dir, **(config_overrides or {}))
    params: dict = {}
    seen = set()
    for fname in _checkpoint_files(model_dir):
        with safe_open(fname, framework="np") as f:
            for hf_name in f.keys():
                mapped = map_hf_name(hf_name)
                if mapped is None:
                    continue
                path, transpose = mapped
                if cfg.tie_word_embeddings and path == "lm_head/kernel":
                    continue
                tensor = f.get_tensor(hf_name)
                if tensor.dtype == np.dtype("V2"):  # raw bf16 comes out as void
                    tensor = tensor.view(np.uint16)
                    tensor = jax.lax.bitcast_convert_type(
                        jnp.asarray(tensor), jnp.bfloat16
                    )
                arr = jnp.asarray(tensor, dtype=dtype)
                if transpose:
                    arr = arr.T
                if sharding_fn is not None:
                    arr = jax.device_put(arr, sharding_fn(path, arr.shape))
                _set_path(params, path, arr)
                seen.add(path)
    if not seen:
        raise ValueError(f"no recognized Qwen3 tensors in {model_dir}")
    if scan_layers:
        cfg = cfg.replace(scan_layers=True)
    if cfg.scan_layers:
        # gate on the POST-override cfg so
        # config_overrides={"scan_layers": True} converts too — a
        # scan-flagged model with unrolled params would fail at apply
        from llm_in_practise_tpu.models.qwen3 import (
            stack_layer_params,
            stack_layer_params_jitted,
        )

        if sharding_fn is not None:
            from llm_in_practise_tpu.utils.tree import path_str

            stacked_shape = jax.eval_shape(
                lambda t: stack_layer_params(t, cfg.n_layer), params)
            out_shardings = jax.tree_util.tree_map_with_path(
                lambda p, leaf: sharding_fn(path_str(p), leaf.shape),
                stacked_shape)
            params = stack_layer_params_jitted(
                params, cfg.n_layer, out_shardings=out_shardings)
        else:
            # single-placement loads: per-leaf stacking — the whole-tree
            # jit peaks at 2x the tree, which a 14B-class single-chip
            # load cannot afford
            from llm_in_practise_tpu.models.qwen3 import (
                stack_layer_params_lowmem,
            )

            params = stack_layer_params_lowmem(params, cfg.n_layer)
    return Qwen3(cfg), params


def save_qwen3(params: dict, cfg: Qwen3Config, out_dir: str) -> None:
    """Export a params pytree to HF-layout safetensors (single shard).
    Scan-layout trees are unstacked first — HF's format is per-layer
    (and silently emitting zero layer weights was a real bug)."""
    from safetensors.numpy import save_file

    if "blocks" in params:
        from llm_in_practise_tpu.models.qwen3 import unstack_layer_params

        params = unstack_layer_params(params, cfg.n_layer)

    os.makedirs(out_dir, exist_ok=True)
    flat: dict[str, np.ndarray] = {}

    def emit(hf_name: str, path: str, transpose: bool):
        node = params
        for p in path.split("/"):
            if p not in node:
                return
            node = node[p]
        arr = np.asarray(jax.device_get(node), dtype=np.float32)
        # save_file serializes the raw buffer, ignoring strides — transposed
        # views (and some device_get results) MUST be made C-contiguous.
        flat[hf_name] = np.ascontiguousarray(arr.T if transpose else arr)

    emit("model.embed_tokens.weight", "tok_embed/embedding", False)
    for i in range(cfg.n_layer):
        b = f"block_{i}"
        emit(f"model.layers.{i}.self_attn.q_proj.weight", f"{b}/attn/q_proj/kernel", True)
        emit(f"model.layers.{i}.self_attn.k_proj.weight", f"{b}/attn/k_proj/kernel", True)
        emit(f"model.layers.{i}.self_attn.v_proj.weight", f"{b}/attn/v_proj/kernel", True)
        emit(f"model.layers.{i}.self_attn.o_proj.weight", f"{b}/attn/out_proj/kernel", True)
        emit(f"model.layers.{i}.self_attn.q_norm.weight", f"{b}/attn/q_norm/scale", False)
        emit(f"model.layers.{i}.self_attn.k_norm.weight", f"{b}/attn/k_norm/scale", False)
        emit(f"model.layers.{i}.mlp.gate_proj.weight", f"{b}/mlp/gate_proj/kernel", True)
        emit(f"model.layers.{i}.mlp.up_proj.weight", f"{b}/mlp/up_proj/kernel", True)
        emit(f"model.layers.{i}.mlp.down_proj.weight", f"{b}/mlp/down_proj/kernel", True)
        emit(f"model.layers.{i}.input_layernorm.weight", f"{b}/ln1/scale", False)
        emit(f"model.layers.{i}.post_attention_layernorm.weight", f"{b}/ln2/scale", False)
    emit("model.norm.weight", "ln_f/scale", False)
    if not cfg.tie_word_embeddings:
        emit("lm_head.weight", "lm_head/kernel", True)
    save_file(flat, os.path.join(out_dir, "model.safetensors"))
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(
            {
                "architectures": ["Qwen3ForCausalLM"],
                "model_type": "qwen3",
                "vocab_size": cfg.vocab_size,
                "hidden_size": cfg.hidden_size,
                "intermediate_size": cfg.intermediate_size,
                "num_hidden_layers": cfg.n_layer,
                "num_attention_heads": cfg.n_head,
                "num_key_value_heads": cfg.n_kv_head,
                "head_dim": cfg.head_dim,
                "rope_theta": cfg.rope_theta,
                "rms_norm_eps": cfg.rms_norm_eps,
                "max_position_embeddings": cfg.max_seq_len,
                "tie_word_embeddings": cfg.tie_word_embeddings,
                "torch_dtype": "float32",
            },
            f, indent=2,
        )

"""Qwen3 architecture in flax — the HF-interop model family.

Capability parity with the reference's fine-tuning targets (Qwen3-8B/14B and
DeepSeek-R1-0528-Qwen3-8B, loaded via ``AutoModelForCausalLM`` in
``Fine-Tuning/qwen3-8b-lora.py:114-120`` and
``Fine-Tuning/qwen3-14b-qlora-dist-deepspeed.py:95-107``), built TPU-first:

- GQA attention with per-head **QK-RMSNorm** (the Qwen3 signature), RoPE with
  theta 1e6, SwiGLU MLP, RMSNorm everywhere, no biases.
- KV cache stores only ``n_kv_head`` heads; the group-broadcast to ``n_head``
  happens inside the jitted step where XLA fuses it into the attention einsum.
- Everything static-shape; the same module serves training (no cache) and
  KV-cached decode.

Weights come from HF safetensors checkpoints via
:mod:`llm_in_practise_tpu.models.hf_loader`.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from llm_in_practise_tpu.models import layers
from llm_in_practise_tpu.ops import rope as rope_ops
from llm_in_practise_tpu.ops.attention import dot_product_attention

Cache = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class Qwen3Config:
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    n_layer: int
    n_head: int
    n_kv_head: int
    head_dim: int
    rope_theta: float = 1_000_000.0
    rms_norm_eps: float = 1e-6
    max_seq_len: int = 4096
    tie_word_embeddings: bool = False
    attn_impl: str = "auto"
    compute_dtype: str = "bfloat16"
    remat: bool = False  # gradient checkpointing: recompute blocks in bwd
    # Compile one block and lax.scan it over the depth axis: XLA program
    # size (and compile time) becomes O(1) in n_layer instead of O(n) —
    # at 28+ layers the unrolled HLO takes tens of minutes to compile.
    # Params are stored STACKED (leading n_layer axis, under "blocks");
    # use stack_layer_params / unstack_layer_params to convert to/from
    # the unrolled per-block layout (HF interop). Cached decode works in
    # BOTH layouts: under scan the KV cache is stacked too (leading
    # n_layer axis, slot axis 1 — see ``init_cache``) and each scan step
    # carries its layer's KV slice as a scanned input/output.
    scan_layers: bool = False
    # lax.scan unroll factor for the scan-layers paths: >1 puts N block
    # copies in the loop body (program size O(unroll), iterations
    # n_layer/unroll) — amortizes per-iteration loop mechanics at a
    # bounded compile-time cost. n_layer must be divisible by it.
    scan_unroll: int = 1

    def replace(self, **kw) -> "Qwen3Config":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Qwen3Config":
        return cls(**d)

    @classmethod
    def from_hf_config(cls, hf: dict, **overrides) -> "Qwen3Config":
        """Build from a HF ``config.json`` dict (transformers Qwen3Config)."""
        cfg = cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            n_layer=hf["num_hidden_layers"],
            n_head=hf["num_attention_heads"],
            n_kv_head=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get(
                "head_dim", hf["hidden_size"] // hf["num_attention_heads"]
            ),
            rope_theta=float(hf.get("rope_theta", 1_000_000.0)),
            rms_norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
            max_seq_len=int(hf.get("max_position_embeddings", 4096)),
            tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
        )
        return cfg.replace(**overrides)


def qwen3_config(vocab_size: int = 1024, **kw) -> Qwen3Config:
    """Tiny-default constructor for tests and examples."""
    defaults = dict(
        vocab_size=vocab_size, hidden_size=128, intermediate_size=256,
        n_layer=2, n_head=4, n_kv_head=2, head_dim=32, max_seq_len=256,
    )
    defaults.update(kw)
    return Qwen3Config(**defaults)


class RMSNorm(nn.Module):
    """RMSNorm with f32 accumulation (HF Qwen3RMSNorm semantics)."""

    eps: float = 1e-6

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dtype = x.dtype
        x = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        x = x * jax.lax.rsqrt(var + self.eps)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return (x * scale).astype(dtype)


def init_cache(
    cfg: Qwen3Config, batch: int, max_len: int, dtype=jnp.bfloat16
) -> list[Cache]:
    """Static-shape per-layer KV cache holding only the KV-head groups.

    Unrolled layout: one ``{k, v, index}`` dict per layer, slot (batch)
    axis 0. Scan layout (``cfg.scan_layers``): ONE dict whose k/v carry a
    leading ``n_layer`` axis (slot axis 1) and a single shared ``index``
    — every layer advances in lockstep, so per-layer indices are
    redundant. It is wrapped in a one-element list so engine code that
    iterates per-layer dicts traverses both layouts identically."""
    if cfg.scan_layers:
        return [
            {
                "k": jnp.zeros((cfg.n_layer, batch, max_len,
                                cfg.n_kv_head, cfg.head_dim), dtype),
                "v": jnp.zeros((cfg.n_layer, batch, max_len,
                                cfg.n_kv_head, cfg.head_dim), dtype),
                "index": jnp.zeros((), jnp.int32),
            }
        ]
    return [
        {
            "k": jnp.zeros((batch, max_len, cfg.n_kv_head, cfg.head_dim), dtype),
            "v": jnp.zeros((batch, max_len, cfg.n_kv_head, cfg.head_dim), dtype),
            "index": jnp.zeros((), jnp.int32),
        }
        for _ in range(cfg.n_layer)
    ]


class Qwen3Attention(nn.Module):
    """GQA + QK-RMSNorm + RoPE causal attention."""

    cfg: Qwen3Config

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        rope_tables: tuple[jax.Array, jax.Array],
        *,
        cache: Cache | None = None,
        positions: jax.Array | None = None,
    ) -> tuple[jax.Array, Cache | None]:
        cfg = self.cfg
        b, l, _ = x.shape
        # dtype pins the compute path: flax Dense with dtype=None promotes
        # bf16 activations against f32 params and the layer silently runs
        # f32 (params stay f32 masters either way)
        compute = jnp.dtype(cfg.compute_dtype)
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=compute, name=name)
        q = dense(cfg.n_head * cfg.head_dim, "q_proj")(x)
        k = dense(cfg.n_kv_head * cfg.head_dim, "k_proj")(x)
        v = dense(cfg.n_kv_head * cfg.head_dim, "v_proj")(x)
        q = q.reshape(b, l, cfg.n_head, cfg.head_dim)
        k = k.reshape(b, l, cfg.n_kv_head, cfg.head_dim)
        v = v.reshape(b, l, cfg.n_kv_head, cfg.head_dim)

        # Qwen3 signature: per-head RMSNorm on q and k before RoPE.
        q = RMSNorm(cfg.rms_norm_eps, name="q_norm")(q)
        k = RMSNorm(cfg.rms_norm_eps, name="k_norm")(k)

        cos, sin = rope_tables
        if positions is None and cache is not None:
            positions = layers.cache_positions(cache["index"], b, l)
        # HF rotate_half lane layout — required for checkpoint fidelity.
        # Rotation math rides the f32 tables; result returns to the
        # compute dtype so attention keeps its bf16 MXU path.
        q = rope_ops.apply_rotary_emb(
            q, cos, sin, positions=positions, interleaved=False
        ).astype(compute)
        k = rope_ops.apply_rotary_emb(
            k, cos, sin, positions=positions, interleaved=False
        ).astype(compute)

        q_offset = None
        if cache is not None:
            q_offset = cache["index"]
            k_cache = layers.cache_update(cache["k"], k, cache["index"])
            v_cache = layers.cache_update(cache["v"], v, cache["index"])
            cache = {"k": k_cache, "v": v_cache, "index": cache["index"] + l}
            k, v = k_cache.astype(q.dtype), v_cache.astype(q.dtype)

        # GQA: k/v go in with their n_kv_head heads — the dense path
        # contracts against them grouped (no broadcast ever exists in
        # HBM; a materialized jnp.repeat here measured ~256 MB/layer/step
        # at 8B decode, docs/perf.md Finding 14), and the flash path
        # repeats internally only when actually taken.
        out = dot_product_attention(
            q, k, v,
            causal=True, q_offset=q_offset,
            impl=cfg.attn_impl,
        )
        out = out.reshape(b, l, cfg.n_head * cfg.head_dim)
        return dense(cfg.hidden_size, "out_proj")(out), cache


class Qwen3MLP(nn.Module):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    cfg: Qwen3Config

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        compute = jnp.dtype(cfg.compute_dtype)  # see Qwen3Attention
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=compute, name=name)
        gate = dense(cfg.intermediate_size, "gate_proj")(x)
        up = dense(cfg.intermediate_size, "up_proj")(x)
        return dense(cfg.hidden_size, "down_proj")(nn.silu(gate) * up)


class Qwen3Block(nn.Module):
    cfg: Qwen3Config

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        rope_tables: tuple[jax.Array, jax.Array],
        *,
        cache: Cache | None = None,
        positions: jax.Array | None = None,
    ) -> tuple[jax.Array, Cache | None]:
        cfg = self.cfg
        a, cache = Qwen3Attention(cfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, name="ln1")(x), rope_tables,
            cache=cache, positions=positions,
        )
        x = x + a
        x = x + Qwen3MLP(cfg, name="mlp")(RMSNorm(cfg.rms_norm_eps, name="ln2")(x))
        return x, cache


class _ScanBody(nn.Module):
    """One scan step: positional-only signature for ``nn.scan`` (carry = the
    hidden stream; rope tables and positions ride as broadcast inputs).
    ``sideband`` (scanned, may be None) is this layer's slice of
    caller-provided side inputs — stacked packed quantized weights and/or
    stacked LoRA factors — published via :func:`..layers.scan_sideband`
    so method interceptors (peft/fused.py) can serve the current layer's
    tensors; gradients flow through it (it is ordinary scanned ``xs``),
    which is what makes full-depth QLoRA training under scan work."""

    cfg: Qwen3Config

    @nn.compact
    def __call__(self, x, sideband, rope_tables, positions):
        block_cls = (
            nn.remat(Qwen3Block, prevent_cse=False)
            if self.cfg.remat else Qwen3Block
        )
        with layers.scan_sideband(sideband):
            x, _ = block_cls(self.cfg, name="block")(
                x, rope_tables, cache=None, positions=positions)
        return x, None


class _ScanDecodeBody(nn.Module):
    """One cached-decode scan step: the layer's KV slice rides as a
    scanned input and the refreshed slice as the scanned output, so the
    decode program compiles ONE block regardless of depth (the serving
    analog of the training-path ``_ScanBody``). The write ``index`` is
    shared by every layer (lockstep) and is broadcast, not scanned; the
    per-layer index the block returns is dropped — the caller advances
    the shared one once. ``sideband`` (scanned, may be empty) is this
    layer's slice of caller-provided side inputs — e.g. packed quantized
    weights — published via :func:`..layers.scan_sideband` for method
    interceptors (peft/fused.py) during the body's trace."""

    cfg: Qwen3Config

    @nn.compact
    def __call__(self, x, kv, index, sideband, rope_tables, positions):
        layer_cache = {"k": kv["k"], "v": kv["v"], "index": index}
        with layers.scan_sideband(sideband):
            x, new = Qwen3Block(self.cfg, name="block")(
                x, rope_tables, cache=layer_cache, positions=positions)
        return x, {"k": new["k"], "v": new["v"]}


def stack_layer_params(params: dict, n_layer: int) -> dict:
    """Unrolled ``block_i`` subtrees -> the scan layout (stacked leaves
    with a leading ``n_layer`` axis under ``blocks/block``)."""
    rest = {k: v for k, v in params.items()
            if not k.startswith("block_")}
    blocks = [params[f"block_{i}"] for i in range(n_layer)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls, axis=0), *blocks)
    return {**rest, "blocks": {"block": stacked}}


def stack_layer_params_jitted(params: dict, n_layer: int,
                              out_shardings=None) -> dict:
    """:func:`stack_layer_params` as one jitted call with the input
    DONATED — peak memory is the unrolled tree plus one stacked leaf,
    not two full trees. ``out_shardings`` (a pytree of shardings
    matching the STACKED layout) pins the result's placement — without
    it the compiler chooses, typically replicating. The shared
    conversion used by the bench, the serve example, and the HF
    loader."""
    kw = {} if out_shardings is None else {"out_shardings": out_shardings}
    return jax.jit(
        lambda t: stack_layer_params(t, n_layer), donate_argnums=0, **kw
    )(params)


def stack_layer_params_lowmem(params: dict, n_layer: int) -> dict:
    """:func:`stack_layer_params` leaf-group by leaf-group: one jitted
    donated stack per component, so peak memory is the unrolled tree
    plus ONE stacked leaf — not tree + stacked tree, which is what the
    whole-tree jit (:func:`stack_layer_params_jitted`) holds at its
    peak and what OOMs when the packed tree alone is half of HBM (an
    int8 8B is 6.9 GiB, a 14B NF4 base 7.4 GiB: 2x either + KV cache
    exceeds a 16 GiB chip)."""
    rest = {k: v for k, v in params.items()
            if not k.startswith("block_")}
    blocks = [params[f"block_{i}"] for i in range(n_layer)]
    stack1 = jax.jit(lambda *ls: jnp.stack(ls, axis=0),
                     donate_argnums=tuple(range(n_layer)))
    stacked = jax.tree.map(lambda *ls: stack1(*ls), *blocks)
    return {**rest, "blocks": {"block": stacked}}


def unstack_layer_params(params: dict, n_layer: int) -> dict:
    """Scan layout -> unrolled ``block_i`` subtrees (serving / HF export)."""
    rest = {k: v for k, v in params.items() if k != "blocks"}
    stacked = params["blocks"]["block"]
    for i in range(n_layer):
        rest[f"block_{i}"] = jax.tree.map(lambda x: x[i], stacked)
    return rest


class Qwen3(nn.Module):
    """Qwen3 causal LM. ``model(idx) -> logits``; optional KV cache pytree."""

    cfg: Qwen3Config

    @nn.compact
    def __call__(
        self,
        idx: jax.Array,
        *,
        deterministic: bool = True,  # accepted for train-step compatibility
        cache: list[Cache] | None = None,
        positions: jax.Array | None = None,
        return_hidden: bool = False,  # final-norm hidden states (embedder use)
        # Per-layer side inputs for the scan paths (leading n_layer axis;
        # e.g. stacked packed quantized weights, stacked LoRA factors) —
        # scanned alongside each layer's slice and published to
        # interceptors via the layers.scan_sideband channel. Training
        # scan and cached-decode scan both thread it; requires
        # scan_layers=True.
        scan_sideband: Any = None,
    ):
        cfg = self.cfg
        compute_dtype = jnp.dtype(cfg.compute_dtype)
        if scan_sideband is not None and not cfg.scan_layers:
            raise ValueError(
                "scan_sideband is only consumed by the scan-layers paths "
                "(set scan_layers=True)")
        embed = nn.Embed(
            cfg.vocab_size, cfg.hidden_size,
            embedding_init=nn.initializers.normal(0.02), name="tok_embed",
        )
        x = embed(idx).astype(compute_dtype)
        # One table pair per forward; constant-folded under jit.
        rope_tables = rope_ops.precompute_cos_sin(
            cfg.head_dim, cfg.max_seq_len, cfg.rope_theta
        )
        new_caches: list[Cache] | None = [] if cache is not None else None
        if cfg.scan_layers:
            if cache is not None:
                stacked = cache[0]
                if positions is None:
                    positions = layers.cache_positions(
                        stacked["index"], idx.shape[0], idx.shape[1])
                scan = nn.scan(
                    _ScanDecodeBody,
                    variable_axes={"params": 0},
                    split_rngs={"params": True, "dropout": True},
                    in_axes=(0, nn.broadcast, 0, nn.broadcast,
                             nn.broadcast),
                    out_axes=0,
                    length=cfg.n_layer,
                    unroll=cfg.scan_unroll,
                )
                x, kv = scan(cfg, name="blocks")(
                    x, {"k": stacked["k"], "v": stacked["v"]},
                    stacked["index"], scan_sideband, rope_tables,
                    positions)
                new_caches = [{"k": kv["k"], "v": kv["v"],
                               "index": stacked["index"] + idx.shape[1]}]
            else:
                scan = nn.scan(
                    _ScanBody,
                    variable_axes={"params": 0},
                    split_rngs={"params": True, "dropout": True},
                    in_axes=(0, nn.broadcast, nn.broadcast),
                    length=cfg.n_layer,
                    unroll=cfg.scan_unroll,
                )
                x, _ = scan(cfg, name="blocks")(
                    x, scan_sideband, rope_tables, positions)
        else:
            for i in range(cfg.n_layer):
                layer_cache = cache[i] if cache is not None else None
                block = Qwen3Block(cfg, name=f"block_{i}")
                if cfg.remat and cache is None:
                    # gradient checkpointing (the reference fine-tunes all
                    # call gradient_checkpointing_enable —
                    # qwen3-8b-lora.py:128-144)
                    x = layers.remat_apply(
                        block, x, rope_tables, cache=None,
                        positions=positions)
                else:
                    x, layer_cache = block(
                        x, rope_tables, cache=layer_cache,
                        positions=positions
                    )
                if new_caches is not None:
                    new_caches.append(layer_cache)
        x = RMSNorm(cfg.rms_norm_eps, name="ln_f")(x)
        if return_hidden:
            # with a cache the refreshed cache must come back too, or the
            # caller's KV writes are dead code and get eliminated
            return (x, new_caches) if cache is not None else x
        if cfg.tie_word_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            logits = nn.Dense(
                cfg.vocab_size, use_bias=False, name="lm_head"
            )(x.astype(jnp.float32))
        if cache is not None:
            return logits, new_caches
        return logits

    # -- convenience API shared by every in-tree model family -----------------
    @property
    def config(self) -> Qwen3Config:
        return self.cfg

    def init_params(self, rng, example_len: int = 8):
        return self.init(rng, jnp.ones((1, example_len), jnp.int32))["params"]

    def init_cache(self, batch: int, max_len: int, dtype=jnp.bfloat16):
        return init_cache(self.cfg, batch, max_len, dtype)

    @property
    def cache_slot_axis(self) -> int:
        """Which axis of the KV buffers indexes the slot (batch): 0 in
        the unrolled layout, 1 under the stacked scan layout (axis 0 is
        the layer). Serving code reads this to stay layout-agnostic."""
        return 1 if self.cfg.scan_layers else 0

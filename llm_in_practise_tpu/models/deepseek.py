"""DeepSeekLike: RoPE + MLA (low-rank KV) + sparse MoE, TPU-first.

Capability parity with the reference's flagship from-scratch models
(``LLM_Distributed_Trainning/PyTorch/transformer_basics/``):

- ``DeepSeekLike_wikitext2.py:122-294`` — RoPE, MLA, dense MoE with per-k
  one-hot masks, shared experts, softmax-renormalized top-k gates.
- ``DeepSeekLike_spare_MoE_wikitext2.py:131-333`` — cos/sin RoPE, MLA with
  per-head latent compression, **sparse dispatch** via data-dependent
  ``index_select`` / ``index_add_`` gather/scatter.

The TPU redesign keeps the math and changes the mechanics:

- **MLA** is a shared (not per-head) low-rank factorization: ``kv_down``
  projects to a ``kv_rank`` latent, ``k_up``/``v_up`` decompress to heads;
  queries go through ``q_down``/``q_up``. The decode cache stores the
  *latent* — ``kv_rank`` floats/token instead of ``2·n_head·head_dim`` —
  which is the actual point of MLA; decompression is a batched matmul that
  rides the MXU.
- **MoE routing is static-shape**: the reference's ``index_add_`` scatter has
  data-dependent sizes and cannot jit. Here tokens are dispatched into a
  fixed ``(n_experts, capacity)`` buffer with first-choice priority via
  cumsum positions and one-hot einsums — the standard XLA MoE formulation.
  Dropped tokens (over capacity) fall through to the shared experts /
  residual path. Gates are softmax-over-top-k renormalized, and the
  switch-style load-balance aux loss plus router z-loss are sown into the
  ``losses`` collection.
- Stacked expert weights live at ``experts/fc_in|fc_out`` so the sharding
  rule table partitions them over the ``expert`` mesh axis (expert
  parallelism — described-but-absent in the reference,
  ``DeepSpeed/README.md:17-18``).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from llm_in_practise_tpu.models import layers
from llm_in_practise_tpu.ops import rope as rope_ops
from llm_in_practise_tpu.ops.attention import dot_product_attention

Cache = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class DeepSeekConfig:
    vocab_size: int
    seq_len: int = 256
    n_layer: int = 4
    n_head: int = 8
    embed_dim: int = 256
    # MLA ranks (reference uses latent = head_dim // 4 per head;
    # here a shared latent across heads, same compression ratio by default).
    q_rank: int | None = None      # None → embed_dim // 2
    kv_rank: int | None = None     # None → embed_dim // 4
    # MoE
    n_experts: int = 8
    n_shared_experts: int = 1
    top_k: int = 2
    capacity_factor: float = 1.25
    expert_hidden: int | None = None  # None → embed_dim * mlp_ratio / top_k
    first_dense_layers: int = 1       # leading dense-MLP blocks (DeepSeek style)
    aux_loss_coef: float = 0.01
    z_loss_coef: float = 0.001
    mlp_ratio: float = 4.0
    dropout: float = 0.0
    rope_theta: float = 10000.0
    activation: str = "gelu"
    attn_impl: str = "auto"
    compute_dtype: str = "float32"
    remat: bool = False  # gradient checkpointing: recompute blocks in bwd
    cache_mode: str = "latent"  # "latent" (MLA cache) | "full" (k/v cache)

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.n_head

    @property
    def q_rank_(self) -> int:
        return self.q_rank or self.embed_dim // 2

    @property
    def kv_rank_(self) -> int:
        return self.kv_rank or self.embed_dim // 4

    @property
    def expert_hidden_(self) -> int:
        if self.expert_hidden:
            return self.expert_hidden
        return max(8, int(self.embed_dim * self.mlp_ratio) // max(1, self.top_k))

    def replace(self, **kw) -> "DeepSeekConfig":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "DeepSeekConfig":
        valid = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in valid})


class MLA(nn.Module):
    """Multi-head Latent Attention: shared low-rank Q and KV factorizations.

    Parity: reference ``CausalMLA`` (``DeepSeekLike_spare_MoE_wikitext2.py:
    180-233``) compresses Q/K/V per head to ``head_dim//4`` and decompresses
    before RoPE + standard causal attention. Same compress→decompress→RoPE
    data flow here, with the latent shared across heads so the decode cache
    shrinks from ``2·H·hd`` to ``kv_rank`` per token.
    """

    config: DeepSeekConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        deterministic: bool = True,
        cache: Cache | None = None,
        positions: jax.Array | None = None,
    ) -> tuple[jax.Array, Cache | None]:
        cfg = self.config
        b, l, _ = x.shape
        h, hd = cfg.n_head, cfg.head_dim
        dense = lambda feat, name: nn.Dense(
            feat, kernel_init=layers.dense_init, use_bias=False, name=name
        )

        # Low-rank query: D -> q_rank -> H*hd
        q_latent = dense(cfg.q_rank_, "q_down")(x)
        q = dense(h * hd, "q_up")(q_latent).reshape(b, l, h, hd)
        # Shared low-rank KV latent: D -> kv_rank
        kv_latent = dense(cfg.kv_rank_, "kv_down")(x)

        if positions is None:
            start = cache["index"] if cache is not None else 0
            positions = layers.cache_positions(start, b, l)

        k_up = dense(h * hd, "k_up")
        v_up = dense(h * hd, "v_up")
        # RoPE tables must cover the cache length, which may exceed seq_len
        # (init_cache(max_len=...)); otherwise position gathers past the table
        # would clamp silently and corrupt phases.
        table_len = cfg.seq_len
        if cache is not None:
            table_len = max(
                table_len,
                (cache["kv"] if "kv" in cache else cache["k"]).shape[1],
            )
        cos, sin = rope_ops.precompute_cos_sin(hd, table_len, cfg.rope_theta)

        q = rope_ops.apply_rotary_emb(q, cos, sin, positions=positions)

        q_offset = None
        if cache is None:
            k = k_up(kv_latent).reshape(b, l, h, hd)
            v = v_up(kv_latent).reshape(b, l, h, hd)
            k = rope_ops.apply_rotary_emb(k, cos, sin, positions=positions)
        elif cfg.cache_mode == "latent":
            # Cache the compressed latent; decompress the whole valid prefix
            # each step (batched matmul — MXU work, not HBM). RoPE phases are
            # reconstructed from absolute positions.
            lat_cache = layers.cache_update(
                cache["kv"], kv_latent, cache["index"]
            )
            q_offset = cache["index"]
            cache = {"kv": lat_cache, "index": cache["index"] + l}
            max_len = lat_cache.shape[1]
            lat = lat_cache.astype(x.dtype)
            k = k_up(lat).reshape(b, max_len, h, hd)
            v = v_up(lat).reshape(b, max_len, h, hd)
            all_pos = jnp.broadcast_to(jnp.arange(max_len)[None, :], (b, max_len))
            k = rope_ops.apply_rotary_emb(k, cos, sin, positions=all_pos)
        else:  # "full": decompressed k/v cache (standard layout)
            k = k_up(kv_latent).reshape(b, l, h, hd)
            v = v_up(kv_latent).reshape(b, l, h, hd)
            k = rope_ops.apply_rotary_emb(k, cos, sin, positions=positions)
            q_offset = cache["index"]
            k_cache = layers.cache_update(cache["k"], k, cache["index"])
            v_cache = layers.cache_update(cache["v"], v, cache["index"])
            cache = {"k": k_cache, "v": v_cache, "index": cache["index"] + l}
            k, v = k_cache.astype(q.dtype), v_cache.astype(q.dtype)

        dropout_rng = None
        if not deterministic and cfg.dropout > 0.0:
            dropout_rng = self.make_rng("dropout")
        out = dot_product_attention(
            q, k, v,
            causal=True,
            q_offset=q_offset,
            dropout_rate=0.0 if deterministic else cfg.dropout,
            dropout_rng=dropout_rng,
            impl=cfg.attn_impl,
        )
        out = out.reshape(b, l, h * hd)
        out = dense(cfg.embed_dim, "out_proj")(out)
        out = nn.Dropout(cfg.dropout)(out, deterministic=deterministic)
        return out, cache


class _StackedKernel(nn.Module):
    """A (n_experts, d_in, d_out) weight named ``<name>/kernel`` so the
    sharding rule table can target ``experts/fc_in/kernel`` etc."""

    shape: tuple[int, ...]

    @nn.compact
    def __call__(self) -> jax.Array:
        return self.param("kernel", layers.dense_init, self.shape)


class StackedExperts(nn.Module):
    """All expert MLPs as stacked tensors, applied with einsum over the
    (expert, capacity, dim) dispatch buffer."""

    n_experts: int
    d_model: int
    d_hidden: int
    activation: str = "gelu"

    @nn.compact
    def __call__(self, expert_inputs: jax.Array) -> jax.Array:
        # expert_inputs: (E, C, D)
        w_in = _StackedKernel((self.n_experts, self.d_model, self.d_hidden), name="fc_in")()
        w_out = _StackedKernel((self.n_experts, self.d_hidden, self.d_model), name="fc_out")()
        h = jnp.einsum("ecd,edh->ech", expert_inputs, w_in.astype(expert_inputs.dtype))
        h = layers._activation(self.activation)(h)
        return jnp.einsum("ech,ehd->ecd", h, w_out.astype(h.dtype))


class MoEFeedForward(nn.Module):
    """Top-k routed experts + always-on shared experts, static shapes.

    Parity: reference ``MoEFeedForward``
    (``DeepSeekLike_spare_MoE_wikitext2.py:253-333``) — top-k softmax gates
    renormalized over the selected experts, shared experts added
    unconditionally. The scatter/gather dispatch becomes one-hot einsums with
    a fixed per-expert capacity.
    """

    config: DeepSeekConfig

    @nn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        cfg = self.config
        b, l, d = x.shape
        n_tok = b * l
        e, k = cfg.n_experts, cfg.top_k
        tokens = x.reshape(n_tok, d)

        router_logits = nn.Dense(
            e, use_bias=False, kernel_init=layers.dense_init, name="router"
        )(tokens.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)                  # (N, E)
        gate_vals, expert_idx = jax.lax.top_k(probs, k)                 # (N, k)
        gate_vals = gate_vals / (gate_vals.sum(-1, keepdims=True) + 1e-9)

        # Aux losses (sown; no-ops unless the "losses" collection is mutable).
        # Switch-style load balance: E * Σ_e fraction_e * mean_prob_e.
        sel_onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)   # (N, k, E)
        fraction = sel_onehot.sum(1).mean(0)                            # (E,)
        balance = e * jnp.sum(fraction * probs.mean(0)) * k
        z_loss = jnp.mean(jax.nn.logsumexp(router_logits, axis=-1) ** 2)
        self.sow("losses", "moe_aux",
                 cfg.aux_loss_coef * balance + cfg.z_loss_coef * z_loss)

        experts = StackedExperts(
            e, d, cfg.expert_hidden_, cfg.activation, name="experts"
        )
        if deterministic:
            # Drop-free dense routing for eval/decode: every expert runs over
            # all tokens and the (N, E) gate matrix combines. O(N·E) memory —
            # no capacity buffer — and exact (nothing dropped), so cached
            # decode reproduces the full forward regardless of batch shape.
            gates_dense = (sel_onehot * gate_vals[..., None]).sum(1)    # (N, E)
            expert_inputs = jnp.broadcast_to(tokens[None], (e, n_tok, d))
            expert_out = experts(expert_inputs)                         # (E, N, D)
            routed = jnp.einsum(
                "ne,end->nd", gates_dense.astype(x.dtype), expert_out
            )
        else:
            # Training: capacity-based dispatch with first-choice priority —
            # flatten (k, N) slot-major so every token's 1st choice outranks
            # all 2nd choices; overflow tokens are dropped (gate mass lost),
            # the standard static-shape TPU MoE trade. Dispatch/combine are
            # scatter/gather on (expert, slot) coordinates: each (kN,)
            # choice owns a unique capacity slot, so no (N, k, E, C)
            # one-hot tensor is ever materialized (that buffer dominated
            # both HBM and time at real batch sizes).
            capacity = max(1, int(cfg.capacity_factor * n_tok * k / e))
            flat = sel_onehot.transpose(1, 0, 2).reshape(k * n_tok, e)  # (kN, E)
            # rank of each choice within its expert, priority-ordered
            slot_f = (jnp.cumsum(flat, axis=0) * flat).sum(-1) - 1.0    # (kN,)
            keep = (slot_f >= 0) & (slot_f < capacity)                  # (kN,)
            slot = jnp.where(keep, slot_f, 0).astype(jnp.int32)
            eid = expert_idx.transpose(1, 0).reshape(-1)                # (kN,)
            tok_idx = jnp.tile(jnp.arange(n_tok), k)                    # (kN,)
            contrib = tokens[tok_idx] * keep[:, None].astype(x.dtype)
            # every kept (eid, slot) pair is unique → add == set
            expert_inputs = jnp.zeros((e, capacity, d), x.dtype).at[
                eid, slot].add(contrib)
            expert_out = experts(expert_inputs)                         # (E, C, D)
            gathered = expert_out[eid, slot]                            # (kN, D)
            w = (gate_vals.transpose(1, 0).reshape(-1)
                 * keep.astype(jnp.float32))                            # (kN,)
            routed = (gathered.reshape(k, n_tok, d)
                      * w.reshape(k, n_tok, 1).astype(x.dtype)).sum(0)

        out = routed.reshape(b, l, d)
        for i in range(cfg.n_shared_experts):
            out = out + layers.MLP(
                d, cfg.expert_hidden_, cfg.dropout, cfg.activation,
                name=f"shared_expert_{i}",
            )(x, deterministic=deterministic)
        return nn.Dropout(cfg.dropout)(out, deterministic=deterministic)


class DeepSeekBlock(nn.Module):
    config: DeepSeekConfig
    use_moe: bool = True

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        deterministic: bool = True,
        cache: Cache | None = None,
        positions: jax.Array | None = None,
    ) -> tuple[jax.Array, Cache | None]:
        cfg = self.config
        a, cache = MLA(cfg, name="attn")(
            nn.LayerNorm(name="ln1")(x),
            deterministic=deterministic, cache=cache, positions=positions,
        )
        x = x + a
        h = nn.LayerNorm(name="ln2")(x)
        if self.use_moe:
            x = x + MoEFeedForward(cfg, name="moe")(h, deterministic=deterministic)
        else:
            x = x + layers.MLP(
                cfg.embed_dim, int(cfg.embed_dim * cfg.mlp_ratio),
                cfg.dropout, cfg.activation, name="mlp",
            )(h, deterministic=deterministic)
        return x, cache


class DeepSeekLike(nn.Module):
    """Decoder-only MLA+MoE LM (reference ``DeepSeekLike:354``)."""

    config: DeepSeekConfig

    @nn.compact
    def __call__(
        self,
        idx: jax.Array,
        *,
        deterministic: bool = True,
        cache: list[Cache] | None = None,
        positions: jax.Array | None = None,
    ):
        cfg = self.config
        compute_dtype = jnp.dtype(cfg.compute_dtype)
        x = nn.Embed(
            cfg.vocab_size, cfg.embed_dim,
            embedding_init=layers.dense_init, name="tok_embed",
        )(idx)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        x = x.astype(compute_dtype)

        new_cache = [] if cache is not None else None
        for i in range(cfg.n_layer):
            layer_cache = cache[i] if cache is not None else None
            block = DeepSeekBlock(
                cfg, use_moe=i >= cfg.first_dense_layers, name=f"block_{i}"
            )
            if cfg.remat and cache is None:
                # gradient checkpointing; the sown MoE aux losses thread
                # through the lifted remat unchanged (tested)
                x = layers.remat_apply(
                    block, x, deterministic=deterministic,
                    cache=None, positions=positions)
            else:
                x, layer_cache = block(
                    x, deterministic=deterministic, cache=layer_cache,
                    positions=positions)
            if new_cache is not None:
                new_cache.append(layer_cache)

        x = nn.LayerNorm(name="ln_f")(x.astype(jnp.float32))
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, kernel_init=layers.dense_init,
            name="lm_head",
        )(x)
        if cache is not None:
            return logits, new_cache
        return logits

    def init_cache(self, batch: int, max_len: int | None = None, dtype=jnp.bfloat16):
        cfg = self.config
        max_len = max_len or cfg.seq_len
        if cfg.cache_mode == "latent":
            return [
                {
                    "kv": jnp.zeros((batch, max_len, cfg.kv_rank_), dtype),
                    "index": jnp.zeros((), jnp.int32),
                }
                for _ in range(cfg.n_layer)
            ]
        return layers.init_cache(
            batch, max_len, cfg.n_head, cfg.head_dim, cfg.n_layer, dtype
        )


def moe_loss_fn(params, apply_fn, batch, rng):
    """Train-step loss fn adding the sown MoE aux losses to cross-entropy.

    Use as ``make_train_step(loss_fn=moe_loss_fn)`` — parity with the
    reference's single CE objective plus the load-balance term sparse MoE
    needs (absent in the reference, which load-balances implicitly via its
    softmax gates; required here by capacity routing).
    """
    from llm_in_practise_tpu.train.losses import cross_entropy

    x, y = batch
    logits, mut = apply_fn(
        {"params": params}, x,
        deterministic=False, rngs={"dropout": rng}, mutable=["losses"],
    )
    loss, n_valid = cross_entropy(logits, y)
    aux = sum(
        jnp.sum(jnp.asarray(v).astype(jnp.float32))
        for v in jax.tree_util.tree_leaves(mut.get("losses", {}))
    )
    return loss + aux, {"n_valid": n_valid, "moe_aux": aux, "ce_loss": loss}


def deepseeklike_config(vocab_size: int, **overrides) -> DeepSeekConfig:
    """Preset mirroring reference ``DeepSeekLike_spare_MoE_wikitext2.py``
    defaults (d_model 256, 4 layers, 8 heads, block 256, 8 experts top-2,
    1 shared)."""
    base = dict(
        seq_len=256, n_layer=4, n_head=8, embed_dim=256,
        n_experts=8, top_k=2, n_shared_experts=1, dropout=0.1,
    )
    base.update(overrides)
    return DeepSeekConfig(vocab_size=vocab_size, **base)

"""Shared transformer building blocks (flax.linen).

One block implementation serves the whole from-scratch model family of the
reference curriculum — MiniGPT (post-LN encoder blocks, reference
``llm-demo/minigpt2/model.py:40-74``), GPTLike (pre-LN decoder,
``GPTLike_wikitext2_learned_pe.py:118-160``) — via the ``norm_first`` switch.
Attention funnels through :func:`llm_in_practise_tpu.ops.attention.dot_product_attention`
so the Pallas flash kernel is picked up everywhere on TPU.

KV caches are explicit pytrees (dict with ``k``, ``v``, ``index``) threaded
through ``__call__`` — no mutable module state, so the decode step jits
cleanly and shards like any other value.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from llm_in_practise_tpu.ops import rope as rope_ops
from llm_in_practise_tpu.ops.attention import dot_product_attention

Cache = dict[str, Any]

dense_init = nn.initializers.normal(stddev=0.02)


# --- scan sideband ---------------------------------------------------------
# Trace-time channel between a scan-over-layers body and flax method
# interceptors installed OUTSIDE the scan (peft/fused.py): the body
# publishes its per-iteration sliced side inputs (e.g. one layer's packed
# quantized weights, arriving as scanned ``xs``) so the interceptor can
# serve the *current* layer's tensors even though its closure only holds
# the full stacked tree. The published values are tracers; they are only
# meaningful during the single trace of the scan body, which is exactly
# when interceptors run. Thread-local: engines trace their jitted
# programs from their own threads (one per engine under OpenAIServer
# adapters), and a shared stack would cross-talk between traces.
import threading as _threading

_SCAN_SIDEBAND = _threading.local()


class scan_sideband:
    """Context manager publishing ``value`` for the duration of a scan
    body's trace. Nested scans stack; per-thread."""

    def __init__(self, value):
        self.value = value

    def __enter__(self):
        stack = getattr(_SCAN_SIDEBAND, "stack", None)
        if stack is None:
            stack = _SCAN_SIDEBAND.stack = []
        stack.append(self.value)
        return self.value

    def __exit__(self, *exc):
        _SCAN_SIDEBAND.stack.pop()
        return False


def current_scan_sideband():
    """This thread's innermost published sideband value, or None outside
    a scan body's trace."""
    stack = getattr(_SCAN_SIDEBAND, "stack", None)
    return stack[-1] if stack else None


def remat_apply(block: nn.Module, *args, **call_kwargs):
    """Apply a transformer block under gradient checkpointing.

    Shared by every model family's ``cfg.remat`` path: wraps the block's
    ``__call__`` in flax's lifted ``nn.remat`` so activations are
    recomputed in backward instead of saved (exact — tested in
    tests/test_remat.py). ``call_kwargs`` are closed over (python bools
    stay static; traced arrays like ``positions`` become free variables,
    which ``jax.checkpoint`` handles); the block's cache output is
    dropped — remat only runs on the cache-free training forward.
    """
    def run(mdl, *a):
        return mdl(*a, **call_kwargs)[0]

    return nn.remat(run, prevent_cse=False)(block, *args)


def _activation(name: str):
    return {"gelu": nn.gelu, "relu": nn.relu, "silu": nn.silu}[name]


def cache_positions(index: jax.Array, batch: int, length: int) -> jax.Array:
    """(B, L) absolute positions for the current query block.

    ``index`` is the cache write index — a scalar (all sequences in lockstep,
    plain generate) or a ``(B,)`` vector (continuous batching: every slot at
    its own depth).
    """
    index = jnp.asarray(index)
    if index.ndim == 1:
        return index[:, None] + jnp.arange(length)[None, :]
    pos = index + jnp.arange(length)[None, :]
    return jnp.broadcast_to(pos, (batch, length))


def cache_update(buf: jax.Array, new: jax.Array, index: jax.Array) -> jax.Array:
    """Write ``new`` (B, L, ...) into ``buf`` (B, max_len, ...) at ``index``.

    Scalar index → one dynamic_update_slice; ``(B,)`` vector index → per-slot
    scatter (vmapped), the continuous-batching write path. Works for 4D KV
    buffers and the 3D MLA latent cache alike.
    """
    new = new.astype(buf.dtype)
    index = jnp.asarray(index)
    trailing = (0,) * (buf.ndim - 2)
    if index.ndim == 1:
        return jax.vmap(
            lambda b, n, i: jax.lax.dynamic_update_slice(b, n, (i, *trailing))
        )(buf, new, index)
    return jax.lax.dynamic_update_slice(buf, new, (0, index, *trailing))


def init_cache(
    batch: int, max_len: int, n_kv_head: int, head_dim: int, n_layer: int,
    dtype=jnp.bfloat16,
) -> list[Cache]:
    """Pre-allocated static-shape KV cache, one entry per layer."""
    return [
        {
            "k": jnp.zeros((batch, max_len, n_kv_head, head_dim), dtype),
            "v": jnp.zeros((batch, max_len, n_kv_head, head_dim), dtype),
            "index": jnp.zeros((), jnp.int32),
        }
        for _ in range(n_layer)
    ]


class CausalSelfAttention(nn.Module):
    """Multi-head causal self-attention with optional RoPE and KV cache."""

    embed_dim: int
    n_head: int
    dropout: float = 0.0
    use_rope: bool = False
    rope_theta: float = 10000.0
    max_seq_len: int = 2048
    attn_impl: str = "auto"
    # Compute dtype for the projections. flax Dense with dtype=None
    # PROMOTES bf16 activations against the f32 params — the whole layer
    # silently runs f32 and the MXU loses its bf16 peak; pass bfloat16
    # here (params stay f32 masters, cast per-call).
    dtype: object = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        deterministic: bool = True,
        cache: Cache | None = None,
        positions: jax.Array | None = None,
    ) -> tuple[jax.Array, Cache | None]:
        b, l, _ = x.shape
        head_dim = self.embed_dim // self.n_head
        qkv_dense = lambda name: nn.Dense(
            self.embed_dim, kernel_init=dense_init, dtype=self.dtype,
            name=name
        )
        q = qkv_dense("q_proj")(x).reshape(b, l, self.n_head, head_dim)
        k = qkv_dense("k_proj")(x).reshape(b, l, self.n_head, head_dim)
        v = qkv_dense("v_proj")(x).reshape(b, l, self.n_head, head_dim)

        if self.use_rope:
            cos, sin = rope_ops.precompute_cos_sin(
                head_dim, self.max_seq_len, self.rope_theta
            )
            if positions is None and cache is not None:
                positions = cache_positions(cache["index"], b, l)
            # rotation math in f32 (the tables are f32), result back in
            # the compute dtype so attention keeps its bf16 path
            dt = q.dtype
            q = rope_ops.apply_rotary_emb(
                q, cos, sin, positions=positions).astype(dt)
            k = rope_ops.apply_rotary_emb(
                k, cos, sin, positions=positions).astype(dt)

        q_offset = None
        if cache is not None:
            q_offset = cache["index"]  # absolute position of first query
            k_cache = cache_update(cache["k"], k, cache["index"])
            v_cache = cache_update(cache["v"], v, cache["index"])
            cache = {"k": k_cache, "v": v_cache, "index": cache["index"] + l}
            k, v = k_cache.astype(q.dtype), v_cache.astype(q.dtype)

        dropout_rng = None
        if not deterministic and self.dropout > 0.0:
            dropout_rng = self.make_rng("dropout")
        # With a cache, q_offset-based causal masking handles both future
        # prompt positions (multi-token prefill) and unwritten cache slots.
        out = dot_product_attention(
            q, k, v,
            causal=True,
            q_offset=q_offset,
            dropout_rate=0.0 if deterministic else self.dropout,
            dropout_rng=dropout_rng,
            impl=self.attn_impl,
        )
        out = out.reshape(b, l, self.embed_dim)
        out = nn.Dense(self.embed_dim, kernel_init=dense_init,
                       dtype=self.dtype, name="out_proj")(out)
        out = nn.Dropout(self.dropout)(out, deterministic=deterministic)
        return out, cache


class MLP(nn.Module):
    """Position-wise FFN: Dense → activation → Dense → dropout."""

    embed_dim: int
    hidden_dim: int
    dropout: float = 0.0
    activation: str = "gelu"
    dtype: object = None  # see CausalSelfAttention.dtype

    @nn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        h = nn.Dense(self.hidden_dim, kernel_init=dense_init,
                     dtype=self.dtype, name="fc_in")(x)
        h = _activation(self.activation)(h)
        h = nn.Dense(self.embed_dim, kernel_init=dense_init,
                     dtype=self.dtype, name="fc_out")(h)
        return nn.Dropout(self.dropout)(h, deterministic=deterministic)


class TransformerBlock(nn.Module):
    """Attention + FFN with residuals; pre-LN or post-LN."""

    embed_dim: int
    n_head: int
    mlp_ratio: float = 4.0
    dropout: float = 0.0
    norm_first: bool = True
    activation: str = "gelu"
    use_rope: bool = False
    rope_theta: float = 10000.0
    max_seq_len: int = 2048
    attn_impl: str = "auto"
    dtype: object = None  # see CausalSelfAttention.dtype

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        deterministic: bool = True,
        cache: Cache | None = None,
        positions: jax.Array | None = None,
    ) -> tuple[jax.Array, Cache | None]:
        attn = CausalSelfAttention(
            self.embed_dim, self.n_head, self.dropout,
            use_rope=self.use_rope, rope_theta=self.rope_theta,
            max_seq_len=self.max_seq_len, attn_impl=self.attn_impl,
            dtype=self.dtype, name="attn",
        )
        mlp = MLP(
            self.embed_dim, int(self.embed_dim * self.mlp_ratio),
            self.dropout, self.activation, dtype=self.dtype, name="mlp",
        )

        def _ln(name):
            # statistics in f32 (dtype=None promotes), output back in the
            # block's compute dtype so residuals stay bf16
            ln = nn.LayerNorm(name=name)
            if self.dtype is None:
                return ln
            return lambda v: ln(v).astype(self.dtype)

        ln1 = _ln("ln1")
        ln2 = _ln("ln2")
        if self.norm_first:
            a, cache = attn(
                ln1(x), deterministic=deterministic, cache=cache, positions=positions
            )
            x = x + a
            x = x + mlp(ln2(x), deterministic=deterministic)
        else:  # post-LN (torch TransformerEncoderLayer default)
            a, cache = attn(
                x, deterministic=deterministic, cache=cache, positions=positions
            )
            x = ln1(x + a)
            x = ln2(x + mlp(x, deterministic=deterministic))
        return x, cache

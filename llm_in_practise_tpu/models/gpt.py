"""Decoder-only GPT family: one module covering MiniGPT and GPTLike.

Capability parity (behavior, not code) with the reference's from-scratch GPTs:

- MiniGPT v2 — post-LN encoder blocks, learned position-embedding parameter,
  N(0, 0.02) init, final LN + head (reference ``llm-demo/minigpt2/model.py:40-74``).
- GPTLike (learned PE) — pre-LN blocks, learned ``nn.Embedding`` positions,
  weight tying (reference ``GPTLike_wikitext2_learned_pe.py:118-205``).
- GPTLike (fixed PE) — sinusoidal position table registered as a constant
  (reference ``GPTLike_wikitext2_fixed_pe.py:178-230``).

The variants are expressed as :class:`GPTConfig` presets, not separate model
code; factories below give each reference model its named constructor.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from llm_in_practise_tpu.models import layers
from llm_in_practise_tpu.ops.rope import sinusoidal_embeddings


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int
    seq_len: int = 256
    n_layer: int = 4
    n_head: int = 4
    embed_dim: int = 128
    mlp_ratio: float = 4.0
    dropout: float = 0.1
    pos_embedding: str = "learned"  # "learned" | "sinusoidal" | "rope"
    norm_first: bool = True
    tie_weights: bool = False
    activation: str = "gelu"
    rope_theta: float = 10000.0
    attn_impl: str = "auto"
    compute_dtype: str = "float32"
    remat: bool = False  # gradient checkpointing: recompute blocks in bwd

    def replace(self, **kw) -> "GPTConfig":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "GPTConfig":
        valid = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in valid})


class GPT(nn.Module):
    """Decoder-only LM. ``__call__(idx) -> logits`` (+ updated KV cache)."""

    config: GPTConfig

    @nn.compact
    def __call__(
        self,
        idx: jax.Array,
        *,
        deterministic: bool = True,
        cache: list[layers.Cache] | None = None,
        positions: jax.Array | None = None,
        return_hidden: bool = False,
    ):
        cfg = self.config
        b, l = idx.shape
        compute_dtype = jnp.dtype(cfg.compute_dtype)

        embed = nn.Embed(
            cfg.vocab_size, cfg.embed_dim,
            embedding_init=layers.dense_init, name="tok_embed",
        )
        x = embed(idx)

        if positions is None:
            start = cache[0]["index"] if cache is not None else 0
            positions = layers.cache_positions(start, b, l)
        if cfg.pos_embedding == "learned":
            pos_table = self.param(
                "pos_embed", layers.dense_init, (cfg.seq_len, cfg.embed_dim)
            )
            x = x + pos_table[positions]
        elif cfg.pos_embedding == "sinusoidal":
            x = x + sinusoidal_embeddings(cfg.seq_len, cfg.embed_dim)[positions]
        # "rope" applies inside attention.

        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        x = x.astype(compute_dtype)

        new_cache = [] if cache is not None else None
        block_pos = positions if cfg.pos_embedding == "rope" else None
        for i in range(cfg.n_layer):
            layer_cache = cache[i] if cache is not None else None
            block = layers.TransformerBlock(
                cfg.embed_dim, cfg.n_head, cfg.mlp_ratio, cfg.dropout,
                norm_first=cfg.norm_first, activation=cfg.activation,
                use_rope=cfg.pos_embedding == "rope",
                rope_theta=cfg.rope_theta, max_seq_len=cfg.seq_len,
                attn_impl=cfg.attn_impl, dtype=compute_dtype,
                name=f"block_{i}",
            )
            if cfg.remat and cache is None:
                # gradient checkpointing (reference
                # gradient_checkpointing_enable parity)
                x = layers.remat_apply(
                    block, x, deterministic=deterministic,
                    cache=None, positions=block_pos)
            else:
                x, layer_cache = block(
                    x, deterministic=deterministic, cache=layer_cache,
                    positions=block_pos)
            if new_cache is not None:
                new_cache.append(layer_cache)

        x = nn.LayerNorm(name="ln_f")(x.astype(jnp.float32))
        if return_hidden:
            # trunk output for downstream heads (classification fine-tunes —
            # the HF_Basics sequence-classification demos); the LM head's
            # params are simply never created in this configuration
            return (x, new_cache) if cache is not None else x
        if cfg.tie_weights:
            logits = embed.attend(x)
        else:
            logits = nn.Dense(
                cfg.vocab_size, kernel_init=layers.dense_init, name="lm_head"
            )(x)
        if cache is not None:
            return logits, new_cache
        return logits

    def init_cache(self, batch: int, max_len: int | None = None, dtype=jnp.bfloat16):
        cfg = self.config
        return layers.init_cache(
            batch, max_len or cfg.seq_len, cfg.n_head,
            cfg.embed_dim // cfg.n_head, cfg.n_layer, dtype,
        )


# --- Named presets mirroring the reference's model zoo -----------------------

def minigpt_config(vocab_size: int, **overrides) -> GPTConfig:
    """MiniGPT v2 preset (reference ``minigpt2/model.py:5-14`` Config)."""
    base = dict(
        seq_len=256, n_layer=4, n_head=4, embed_dim=128, dropout=0.1,
        pos_embedding="learned", norm_first=False, tie_weights=False,
    )
    base.update(overrides)
    return GPTConfig(vocab_size=vocab_size, **base)


def minigpt_v1_config(vocab_size: int, **overrides) -> GPTConfig:
    """MiniGPT v1 preset: char-level toy, seq 16, d_model 64
    (reference ``llm-demo/minigpt/model.py:5-31``)."""
    base = dict(
        seq_len=16, n_layer=2, n_head=2, embed_dim=64, dropout=0.1,
        pos_embedding="learned", norm_first=False,
    )
    base.update(overrides)
    return GPTConfig(vocab_size=vocab_size, **base)


def gptlike_config(vocab_size: int, pos_embedding: str = "learned", **overrides) -> GPTConfig:
    """GPTLike preset (reference ``GPTLike_wikitext2_learned_pe.py`` defaults:
    6 layers, 8 heads, d_model 512, block 256, pre-LN, weight tying)."""
    base = dict(
        seq_len=256, n_layer=6, n_head=8, embed_dim=512, dropout=0.1,
        pos_embedding=pos_embedding, norm_first=True, tie_weights=True,
    )
    base.update(overrides)
    return GPTConfig(vocab_size=vocab_size, **base)

from llm_in_practise_tpu.models.deepseek import (
    DeepSeekConfig,
    DeepSeekLike,
    deepseeklike_config,
    moe_loss_fn,
)
from llm_in_practise_tpu.models.gpt import (
    GPT,
    GPTConfig,
    gptlike_config,
    minigpt_config,
    minigpt_v1_config,
)
from llm_in_practise_tpu.models.qwen3 import Qwen3, Qwen3Config, qwen3_config

__all__ = [
    "GPT",
    "GPTConfig",
    "DeepSeekConfig",
    "DeepSeekLike",
    "Qwen3",
    "Qwen3Config",
    "deepseeklike_config",
    "gptlike_config",
    "minigpt_config",
    "minigpt_v1_config",
    "moe_loss_fn",
    "qwen3_config",
]

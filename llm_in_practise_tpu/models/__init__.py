from llm_in_practise_tpu.models.deepseek import (
    DeepSeekConfig,
    DeepSeekLike,
    deepseeklike_config,
    moe_loss_fn,
)
from llm_in_practise_tpu.models.gpt import (
    GPT,
    GPTConfig,
    gptlike_config,
    minigpt_config,
    minigpt_v1_config,
)

__all__ = [
    "GPT",
    "GPTConfig",
    "DeepSeekConfig",
    "DeepSeekLike",
    "deepseeklike_config",
    "gptlike_config",
    "minigpt_config",
    "minigpt_v1_config",
    "moe_loss_fn",
]

"""Ring attention: sequence-parallel causal attention over the ``seq`` mesh axis.

The reference has **no** training-time sequence/context parallelism (SURVEY
§5.7 — max training seq is ``block_size=256``,
``DeepSeekLike_spare_MoE_wikitext2.py:426``; long context exists only through
vLLM's paged KV at inference). For TPU-scale capability parity this module
ships it as a first-class mesh axis: Q/K/V are sharded over ``seq``; each
device computes attention for its query block while the K/V shards rotate
around the ring via ``jax.lax.ppermute`` — the collective rides ICI and
overlaps with the per-block flash computation. Memory per device is
O(L/n · L/n) for logits and O(L/n) for the accumulators, so sequence length
scales linearly with the ring size.

Numerics: online (streaming) softmax in float32 — identical math to the
FlashAttention-2 forward in :mod:`llm_in_practise_tpu.ops.flash_attention`,
accumulated across ring steps instead of kernel grid steps. Causality is
enforced with absolute positions (query block ``i`` attends to KV block ``j``
fully when ``j < i``, triangularly when ``j == i``, not at all when ``j > i``),
so the result is bit-comparable to dense causal attention on the gathered
sequence (tests assert this on an 8-device CPU mesh).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from llm_in_practise_tpu.core import mesh as mesh_lib
from llm_in_practise_tpu.ops.attention import NEG_INF

try:  # jax>=0.4.35 stable location
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """GQA: repeat KV heads to match query heads."""
    if n_rep == 1:
        return k
    return jnp.repeat(k, n_rep, axis=2)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = mesh_lib.AXIS_SEQ,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Sequence-sharded attention; call inside ``shard_map`` over ``axis_name``.

    q/k/v: local shards ``(batch, local_len, heads, head_dim)`` — the global
    sequence is the concatenation of shards in ring order. Returns the local
    output shard, same shape/dtype as ``q``.
    """
    batch, q_len, n_head, head_dim = q.shape
    kv_len = k.shape[1]
    n_rep = n_head // k.shape[2]
    scale = scale if scale is not None else head_dim ** -0.5

    ring_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    q_pos = my_idx * q_len + jnp.arange(q_len)  # absolute query positions

    # Each step every device forwards its current KV shard to the next ring
    # neighbour, so after t rotations device i holds the shard that started
    # on device (i - t) mod n.
    perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]

    def step(t, carry):
        o, m, l, k_blk, v_blk = carry
        kv_idx = (my_idx - t) % ring_size
        kv_pos = kv_idx * kv_len + jnp.arange(kv_len)

        kf = _repeat_kv(k_blk, n_rep)
        vf = _repeat_kv(v_blk, n_rep)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kf,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            allowed = kv_pos[None, :] <= q_pos[:, None]  # (q_len, kv_len)
            s = jnp.where(allowed[None, None], s, NEG_INF)
            keep = allowed[None, None].astype(jnp.float32)
        else:
            keep = None

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))  # (B, H, Lq)
        # NEG_INF is finite, so exp(s - m_new) is 1.0 on fully-masked rows —
        # multiply by `keep` to zero those contributions exactly.
        p = jnp.exp(s - m_new[..., None])
        if keep is not None:
            p = p * keep
        corr = jnp.exp(m - m_new)  # (B, H, Lq)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vf.astype(jnp.float32))
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv

        k_next, v_next = jax.lax.ppermute((k_blk, v_blk), axis_name, perm)
        return o_new, m_new, l_new, k_next, v_next

    o0 = jnp.zeros((batch, q_len, n_head, head_dim), jnp.float32)
    m0 = jnp.full((batch, n_head, q_len), NEG_INF, jnp.float32)
    l0 = jnp.zeros((batch, n_head, q_len), jnp.float32)
    o, _, l, _, _ = jax.lax.fori_loop(
        0, ring_size, step, (o0, m0, l0, k, v)
    )
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: float | None = None,
    batch_axes: Sequence[str] = mesh_lib.BATCH_AXES,
    head_axis: str | None = mesh_lib.AXIS_TENSOR,
):
    """Wrap :func:`ring_attention` in shard_map over a concrete mesh.

    Returned fn takes *global* q/k/v ``(B, L, H, D)`` (sharded: batch over
    ``batch_axes``, sequence over ``seq``, heads over ``head_axis``) and
    returns the attention output with the same sharding. Composable with
    jit — shard_map nests inside a jitted train step.
    """
    spec = P(tuple(batch_axes), mesh_lib.AXIS_SEQ, head_axis, None)
    fn = _shard_map(
        functools.partial(ring_attention, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn


# --- Mesh context: lets models opt into SP via ``attn_impl="ring"`` ----------
#
# Models dispatch attention through a config string (mirroring how the
# reference picks attention by model file); the mesh is ambient state set by
# the training/serving entry point, not threaded through every module.

_ACTIVE_MESH: list[Mesh] = []


class sp_context:
    """``with sp_context(mesh):`` — route ``attn_impl='ring'`` over ``mesh``."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        _ACTIVE_MESH.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _ACTIVE_MESH.pop()
        return False


def active_sp_mesh() -> Mesh | None:
    if _ACTIVE_MESH and _ACTIVE_MESH[-1].shape.get(mesh_lib.AXIS_SEQ, 1) > 1:
        return _ACTIVE_MESH[-1]
    return None


@functools.lru_cache(maxsize=32)
def _cached_ring_fn(mesh: Mesh, causal: bool, scale: float | None):
    return make_ring_attention(mesh, causal=causal, scale=scale)


def context_ring_attention(q, k, v, *, causal: bool = True, scale=None):
    """Ring attention over the ambient SP mesh; caller checked it is set."""
    mesh = active_sp_mesh()
    if mesh is None:
        raise RuntimeError(
            "attn_impl='ring' needs an active sp_context(mesh) with seq>1"
        )
    return _cached_ring_fn(mesh, causal, scale)(q, k, v)

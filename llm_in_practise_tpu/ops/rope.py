"""Rotary position embeddings (RoPE), both reference formulations.

The reference implements RoPE twice: via complex ``freqs_cis``
(``DeepSeekLike_wikitext2.py:122-160``) and via interleaved cos/sin
(``DeepSeekLike_spare_MoE_wikitext2.py:131-174``). Both are the same rotation;
we implement the interleaved-pair form (even/odd lanes rotated together) as
the canonical one, precomputing cos/sin tables once per model.

Layout: q/k are ``(batch, length, heads, head_dim)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def precompute_cos_sin(
    head_dim: int, max_seq_len: int, theta: float = 10000.0
) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables of shape (max_seq_len, head_dim // 2), fp32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    positions = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(positions, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rotary_emb(
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    *,
    positions: jax.Array | None = None,
    interleaved: bool = True,
) -> jax.Array:
    """Rotate feature pairs of x: (B, L, H, D).

    ``interleaved=True`` pairs even/odd lanes (the reference's formulation);
    ``interleaved=False`` pairs lane ``i`` with ``i + D/2`` — the HF
    "rotate_half" layout used by Qwen/Llama checkpoints. Same rotation,
    different lane permutation; the cos/sin tables are shared.

    ``positions``: optional (B, L) absolute positions (for KV-cached decode);
    defaults to ``arange(L)``.
    """
    b, l, _, d = x.shape
    if positions is None:
        cos_l = cos[:l][None, :, None, :]  # (1, L, 1, D/2)
        sin_l = sin[:l][None, :, None, :]
    else:
        cos_l = cos[positions][:, :, None, :]  # (B, L, 1, D/2)
        sin_l = sin[positions][:, :, None, :]
    xf = x.astype(jnp.float32)
    if interleaved:
        x_pairs = xf.reshape(b, l, x.shape[2], d // 2, 2)
        x_even, x_odd = x_pairs[..., 0], x_pairs[..., 1]
        rot_even = x_even * cos_l - x_odd * sin_l
        rot_odd = x_even * sin_l + x_odd * cos_l
        out = jnp.stack([rot_even, rot_odd], axis=-1).reshape(x.shape)
    else:
        x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
        out = jnp.concatenate(
            [x1 * cos_l - x2 * sin_l, x2 * cos_l + x1 * sin_l], axis=-1
        )
    return out.astype(x.dtype)


def sinusoidal_embeddings(max_len: int, dim: int) -> jax.Array:
    """Classic fixed sinusoidal position table (max_len, dim).

    Parity with ``get_sinusoidal_embeddings`` —
    reference ``GPTLike_wikitext2_fixed_pe.py:178-190``.
    """
    position = jnp.arange(max_len, dtype=jnp.float32)[:, None]
    div_term = jnp.exp(
        jnp.arange(0, dim, 2, dtype=jnp.float32) * (-jnp.log(10000.0) / dim)
    )
    pe = jnp.zeros((max_len, dim), dtype=jnp.float32)
    pe = pe.at[:, 0::2].set(jnp.sin(position * div_term))
    pe = pe.at[:, 1::2].set(jnp.cos(position * div_term))
    return pe

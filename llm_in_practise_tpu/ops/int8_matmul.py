"""Pallas TPU fused W8A16 matmul — int8 weights streamed at memory speed.

The 4-bit kernels (:mod:`.nf4_matmul`, :mod:`.int4_matmul`) pay a
per-element VPU tax in the inner loop — nibble unpack plus codebook
select-tree (NF4) or affine rescale (int4) — which measured as the
decode bottleneck at 8B scale (``docs/perf.md`` Finding 9: ~4% of HBM
peak). Int8 removes the whole tax: the weight tile loads as int8,
converts to bf16 with ONE native cast (int8 magnitudes ≤ 127 are exact
in bf16), and feeds the MXU; the per-out-channel scale applies to the
f32 accumulator once per OUTPUT element after the K loop, because
column-wise scaling commutes with the contraction
(``x @ (q·s) == (x @ q)·s``). The backward folds the scale into ``dy``
outside the kernel (``dx = (dy·s) @ qᵀ``), so neither direction ever
expands scales in the inner loop and the bf16 weight never exists in
HBM.

Grid/pipeline mirror the sibling kernels: ``(M/bm, N/bn, K/bk)`` with K
innermost and an f32 VMEM accumulator. On non-TPU backends the kernel
runs in Pallas interpreter mode; shapes the tiling can't cover fall back
to dequant+matmul. The custom VJP propagates to ``x`` only (quantized
weights are frozen exports).

**Status: probe infrastructure, not a production path.** With dequant
reduced to one convert, XLA's own fusion schedules the thin decode
matmul BETTER than this hand tiling (77 vs 100 ms/token on the 8B
16-slot step; tile-size sweeps flat — ``INT8_TILE_PROBE.json``,
``docs/perf.md`` Finding 11), so ``peft/fused.py::fused_kernel_matmul``
deliberately routes Int8Tensor to the XLA dequant matmul even on the
kernels path. The kernel stays in-tree to keep that negative result
reproducible (``tools/tpu_int8_tile_probe.py``) and is smoke-tested on
real TPU by ``tests/test_int8.py::test_kernel_matmul_on_tpu`` (skipped
elsewhere).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llm_in_practise_tpu.ops.nf4_matmul import _interpret_default, _pick_block
from llm_in_practise_tpu.quant import int8
from llm_in_practise_tpu.quant.int8 import Int8Tensor


def _fwd_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref,
                *, block_m, block_n, block_k):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = q_ref[...].astype(jnp.bfloat16)          # exact for |q| <= 127
    acc_ref[...] += jax.lax.dot_general(
        x_ref[...].astype(jnp.bfloat16), w,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )

    @pl.when(ki == pl.num_programs(2) - 1)
    def _():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


def _bwd_kernel(dys_ref, q_ref, dx_ref, acc_ref,
                *, block_m, block_n, block_k):
    """dx[m, k] = Σ_n (dy·s)[m, n] · q[k, n]; grid (m, k, n), n innermost.
    The scale is already folded into ``dys`` by the caller."""
    ni = pl.program_id(2)

    @pl.when(ni == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        dys_ref[...].astype(jnp.bfloat16), q_ref[...].astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )

    @pl.when(ni == pl.num_programs(2) - 1)
    def _():
        dx_ref[...] = acc_ref[...].astype(dx_ref.dtype)


# Target tile sizes. Tunable at module level (the tile probe tool sweeps
# them): larger tiles cut the program count — the launch/fence overhead
# per grid step is what dominates THIN-activation (decode) matmuls, where
# each weight byte is read exactly once regardless of tiling.
_TGT_N = 512
_TGT_K = 512


def _plan(t: Int8Tensor, m: int):
    if len(t.shape) != 2:
        return None      # stacked 3-D leaves are sliced before use
    k, n = t.shape
    bn = _pick_block(n, _TGT_N)
    bk = _pick_block(k, _TGT_K)
    bm = 512 if m >= 512 else 256 if m >= 256 else 128
    if not bn or not bk:
        return None
    return bm, bn, bk


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def int8_matmul(x, t: Int8Tensor, out_dtype=None, interpret=None):
    """``x @ decode(t)`` with the weight streamed in int8 form.

    x: (..., K); t: Int8Tensor (K, N). Returns (..., N). VJP propagates
    to ``x`` only.
    """
    return _int8_matmul_fwd(x, t, out_dtype, interpret)[0]


def _int8_matmul_fwd(x, t, out_dtype, interpret):
    out_dtype = out_dtype or x.dtype
    interpret = _interpret_default() if interpret is None else interpret
    *lead, k = x.shape
    n = t.shape[1]
    m = int(np.prod(lead)) if lead else 1
    plan = _plan(t, m)
    if plan is None:
        out = x @ int8.decode(t, jnp.bfloat16).astype(x.dtype)
        return out.astype(out_dtype), (x.shape, jnp.zeros((0,), x.dtype), t, None)
    bm, bn, bk = plan
    x2 = x.reshape(m, k)
    pad_m = (-m) % bm
    if pad_m:
        x2 = jnp.pad(x2, ((0, pad_m), (0, 0)))
    grid = (x2.shape[0] // bm, n // bn, k // bk)
    kernel = functools.partial(
        _fwd_kernel, block_m=bm, block_n=bn, block_k=bk)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((x2.shape[0], n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x2, t.q, t.scale.astype(jnp.float32).reshape(1, n))
    return (out[:m].reshape(*lead, n),
            (x.shape, jnp.zeros((0,), x.dtype), t, plan))


def _int8_matmul_bwd(out_dtype, interpret, res, dy):
    x_shape, dtype_carrier, t, plan = res
    x_dtype = dtype_carrier.dtype
    interpret = _interpret_default() if interpret is None else interpret
    *lead, k = x_shape
    n = t.shape[1]
    if plan is None:
        dx = dy @ int8.decode(t, jnp.bfloat16).astype(dy.dtype).T
        return (dx.astype(x_dtype).reshape(x_shape), None)
    bm, bn, bk = plan
    m = int(np.prod(lead)) if lead else 1
    dys = (dy.reshape(m, n).astype(jnp.float32)
           * t.scale.astype(jnp.float32)[None, :])
    pad_m = (-m) % bm
    if pad_m:
        dys = jnp.pad(dys, ((0, pad_m), (0, 0)))
    grid = (dys.shape[0] // bm, k // bk, n // bn)
    kernel = functools.partial(
        _bwd_kernel, block_m=bm, block_n=bn, block_k=bk)
    dx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bn), lambda i, kk, j: (i, j)),
            pl.BlockSpec((bk, bn), lambda i, kk, j: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bk), lambda i, kk, j: (i, kk)),
        out_shape=jax.ShapeDtypeStruct((dys.shape[0], k), x_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bk), jnp.float32)],
        interpret=interpret,
    )(dys, t.q)
    return (dx[:m].reshape(x_shape), None)


int8_matmul.defvjp(_int8_matmul_fwd, _int8_matmul_bwd)

"""Scaled dot-product causal attention with a pluggable implementation.

This is the single dispatch point for attention in the framework. The
reference computes attention three ways (``nn.MultiheadAttention`` + triu mask
— ``GPTLike_wikitext2_learned_pe.py:118-130``; explicit matmul+mask in MLA —
``DeepSeekLike_spare_MoE_wikitext2.py:212-226``; torch SDPA inside
``nn.TransformerEncoder``). Here everything funnels through
:func:`dot_product_attention`, which picks:

- ``dense`` — pure-XLA einsum attention (works everywhere, incl. CPU tests)
- ``flash`` — Pallas TPU flash-attention kernel (O(L) memory, MXU-tiled)
- ``auto``  — flash on TPU when shapes allow, dense otherwise

Convention: q/k/v are ``(batch, length, heads, head_dim)`` (flax layout).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def causal_mask(
    q_len: int, kv_len: int, dtype=jnp.float32, q_offset: jax.Array | int | None = None
) -> jax.Array:
    """Additive causal mask of shape (1|B, 1, q_len, kv_len).

    ``q_offset`` is the absolute position of the first query. Default places
    the query block at the end of the kv sequence (plain decode); a KV-cached
    prefill passes the cache write index so queries mid-buffer mask both
    future prompt positions and unwritten cache slots. A ``(B,)`` vector
    offset gives per-sequence positions (continuous-batching decode, where
    every slot is at a different depth in its cache).
    """
    if q_offset is None:
        q_offset = kv_len - q_len
    q_offset = jnp.asarray(q_offset)
    if q_offset.ndim == 1:  # per-batch offsets -> (B, q_len) query positions
        q_pos = jnp.arange(q_len)[None, :] + q_offset[:, None]
        allowed = jnp.arange(kv_len)[None, None, :] <= q_pos[:, :, None]
        return jnp.where(allowed, 0.0, NEG_INF).astype(dtype)[:, None]
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    kv_pos = jnp.arange(kv_len)[None, :]
    allowed = kv_pos <= q_pos
    return jnp.where(allowed, 0.0, NEG_INF).astype(dtype)[None, None]


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    bias: jax.Array | None = None,
    kv_length: jax.Array | None = None,
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    scale: float | None = None,
    q_offset: jax.Array | int | None = None,
) -> jax.Array:
    """Reference XLA attention. q: (B, Lq, H, D), k/v: (B, Lk, H, D).

    ``kv_length``: optional (B,) valid kv lengths (for padded KV caches).
    ``q_offset``: absolute position of the first query (KV-cached prefill).
    """
    b, q_len, n_head, head_dim = q.shape
    kv_len, n_kv = k.shape[1], k.shape[2]
    scale = scale if scale is not None else head_dim ** -0.5
    if n_kv != n_head:
        # GQA: contract against the kv heads DIRECTLY — a jnp.repeat
        # broadcast before the einsum materializes groups x the KV bytes
        # in HBM, which measured as the cached-decode bottleneck at 8B
        # (~256 MB/layer/step — docs/perf.md Finding 14). bias is the
        # one caller-facing shape that would need regrouping; no GQA
        # caller passes one, so fail loudly rather than guess.
        if n_head % n_kv or bias is not None:
            raise ValueError(
                f"grouped attention needs n_head ({n_head}) divisible by "
                f"kv heads ({n_kv}) and no bias")
        g = n_head // n_kv
        q5 = q.reshape(b, q_len, n_kv, g, head_dim)
        # (B, Hkv, G, Lq, Lk) logits in f32 for numerical stability.
        logits = jnp.einsum(
            "bqhgd,bkhd->bhgqk", q5, k,
            preferred_element_type=jnp.float32) * scale
        if causal:
            logits = logits + causal_mask(
                q_len, kv_len, q_offset=q_offset)[:, :, None]
        if kv_length is not None:
            kv_pos = jnp.arange(kv_len)[None, None, None, None, :]
            valid = kv_pos < kv_length[:, None, None, None, None]
            logits = jnp.where(valid, logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        if dropout_rate > 0.0 and dropout_rng is not None:
            keep = jax.random.bernoulli(
                dropout_rng, 1.0 - dropout_rate, probs.shape)
            probs = probs * keep / (1.0 - dropout_rate)
        probs = probs.astype(v.dtype)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
        return out.reshape(b, q_len, n_head, head_dim)
    # (B, H, Lq, Lk) logits in f32 for numerical stability.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        logits = logits + causal_mask(q_len, kv_len, q_offset=q_offset)
    if kv_length is not None:
        kv_pos = jnp.arange(kv_len)[None, None, None, :]
        valid = kv_pos < kv_length[:, None, None, None]
        logits = jnp.where(valid, logits, NEG_INF)
    if bias is not None:
        logits = logits + bias
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    bias: jax.Array | None = None,
    kv_length: jax.Array | None = None,
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    scale: float | None = None,
    q_offset: jax.Array | int | None = None,
    impl: str = "auto",
) -> jax.Array:
    """Attention entry point used by every model in the framework."""
    if impl == "auto":
        impl = _pick_impl(q, k, bias, kv_length, dropout_rate, causal)
    if impl in ("ring", "ulysses"):
        # sequence-parallel schemes share one eligibility contract: full
        # (uncached) self-attention under an active sp_context mesh
        from llm_in_practise_tpu.ops import ring_attention as ra

        if (bias is None and kv_length is None and dropout_rate == 0.0
                and q_offset is None and k.shape[1] == q.shape[1]
                and ra.active_sp_mesh() is not None):
            if impl == "ring":
                return ra.context_ring_attention(
                    q, k, v, causal=causal, scale=scale)
            from llm_in_practise_tpu.ops import ulysses as ul

            return ul.context_ulysses_attention(
                q, k, v, causal=causal, scale=scale)
        impl = "dense"  # decode/cached paths fall back (KV not seq-sharded)
    if impl == "flash":
        from llm_in_practise_tpu.ops import flash_attention as fa

        if (causal and bias is None and kv_length is None
                and dropout_rate == 0.0 and q_offset is None
                and k.shape[:2] == q.shape[:2]
                and k.shape[3] == q.shape[3]
                and q.shape[2] % k.shape[2] == 0):
            if k.shape[2] != q.shape[2]:
                # the kernel wants equal heads; materializing the GQA
                # broadcast is fine HERE — flash only wins at training
                # lengths where the repeat is amortized over the whole
                # sequence (decode takes the grouped dense path)
                g = q.shape[2] // k.shape[2]
                k = jnp.repeat(k, g, axis=2)
                v = jnp.repeat(v, g, axis=2)
            return fa.flash_attention(q, k, v, causal=causal, scale=scale)
        impl = "dense"  # flash kernel doesn't cover these yet
    return dense_attention(
        q, k, v,
        causal=causal, bias=bias, kv_length=kv_length,
        dropout_rate=dropout_rate, dropout_rng=dropout_rng, scale=scale,
        q_offset=q_offset,
    )


@functools.cache
def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


@functools.cache
def _flash_available() -> bool:
    try:
        from llm_in_practise_tpu.ops import flash_attention  # noqa: F401
        return True
    except ImportError:
        return False


def _pick_impl(q, k, bias, kv_length, dropout_rate, causal=True) -> str:
    if (
        not _on_tpu()
        or not _flash_available()
        or not causal
        or bias is not None
        or kv_length is not None
        or dropout_rate
        or k.shape[:2] != q.shape[:2]      # same batch and length
        or k.shape[3] != q.shape[3]        # same head_dim
        or q.shape[2] % k.shape[2]         # heads = kv heads x groups
    ):
        return "dense"
    batch, q_len, n_head, head_dim = q.shape
    # Measured on one v5e chip (GPTLike 6L/512d training step): XLA's
    # fused dense attention beats the Pallas kernel on short sequences —
    # 357K vs 253K tok/s at L=256, +23% at L=512 — the kernel's tiling
    # overhead dominates small (L, L) score blocks. The flip side is the
    # dense path's f32 score materialization, B·H·L² bytes ×2 held for
    # the backward: at L=1024 training batches it no longer compiles.
    # Gate dense on BOTH the measured length crossover (the 512..1K
    # region is unmeasured — 512 is the last point dense provably wins)
    # and an absolute score-memory bound so wide-and-batchy shapes at
    # L<=512 don't trade the kernel's O(L) memory for an HBM blowup.
    score_bytes = 4 * batch * n_head * q_len * q_len
    # 2 GiB inclusive: the measured dense win at L=512/B=256/H=8 sits
    # exactly at the bound (and compiled + ran), so it stays admitted
    if q_len <= 512 and score_bytes <= (1 << 31):
        return "dense"
    if q_len % 128 == 0 and head_dim in (64, 128, 256):
        return "flash"
    return "dense"

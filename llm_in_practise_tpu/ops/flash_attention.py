"""Pallas TPU flash attention — causal, O(L) memory, MXU-tiled.

The reference computes attention as dense matmul + materialized triu mask
(``GPTLike_wikitext2_learned_pe.py:118-130``, MLA explicit matmul
``DeepSeekLike_spare_MoE_wikitext2.py:212-226``), which is O(L²) HBM. The
TPU idiom is blockwise online-softmax attention: K/V blocks are streamed
through VMEM by the Pallas pipeline (one ``(block, D)`` tile per grid step —
VMEM holds only the current tiles plus per-row accumulators, so sequence
length is bounded by HBM, not VMEM), and the (L, L) score matrix is never
materialized. Backward is the FlashAttention-2 split: recompute block scores
from the saved per-row logsumexp, one kernel for dK/dV (parallel over KV
blocks) and one for dQ (parallel over Q blocks).

Accumulators live in VMEM scratch and persist across the innermost grid
dimension (TPU grids execute sequentially, innermost fastest); causally dead
blocks are skipped with ``pl.when``.

Layout: kernels operate on ``(batch·heads, L, D)``; the public entry point
takes the framework-wide ``(B, L, H, D)`` and handles padding to the 128
tile. Causal-only (the only masking the models need — non-causal paths stay
on the dense XLA implementation in ``ops/attention.py``).

On non-TPU backends the kernels run in Pallas interpreter mode so the exact
kernel logic is unit-testable on the 8-device CPU mesh (SURVEY §4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANE = 128
_SUBLANE = 8  # lse/delta carry a replicated sublane dim to satisfy TPU tiling


def _interpret_default() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def _positions(block_q, block_k):
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return rows, cols


# --------------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, block_q, block_k):
    """Grid (bh, n_q, n_kv), kv innermost; acc/m/l scratch persists over kv."""
    qi, ki = pl.program_id(1), pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: kv block is live iff its first key position <= last query pos
    @pl.when(ki * block_k <= (qi + 1) * block_q - 1)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale                 # (bq, D)
        kb = k_ref[0].astype(jnp.float32)                        # (bk, D)
        vb = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                        # (bq, bk)
        rows, cols = _positions(block_q, block_k)
        s = jnp.where(ki * block_k + cols <= qi * block_q + rows, s, NEG_INF)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, 0:1] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, 0:1] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(
            p, vb, preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_kv - 1)
    def _():
        l = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse = (m_ref[:, 0:1] + jnp.log(l))[:, 0]
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (_SUBLANE, block_q))


def _flash_fwd_call(q, k, v, *, scale, block_q, block_k, interpret):
    bh, L, d = q.shape
    n_q, n_kv = L // block_q, L // block_k
    return pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k
        ),
        grid=(bh, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, _SUBLANE, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, L, d), q.dtype),
            jax.ShapeDtypeStruct((bh, _SUBLANE, L), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# -------------------------------------------------------------------- backward
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, block_q, block_k):
    """Grid (bh, n_kv, n_q), q innermost; dk/dv scratch persists over q."""
    ki, qj = pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qj == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # causal: this q block sees the kv block iff its last query >= first key
    @pl.when((qj + 1) * block_q - 1 >= ki * block_k)
    def _():
        kb = k_ref[0].astype(jnp.float32)                        # (bk, D)
        vb = v_ref[0].astype(jnp.float32)
        qb = q_ref[0].astype(jnp.float32)                        # (bq, D)
        dob = do_ref[0].astype(jnp.float32)
        lse_b = lse_ref[0, 0:1, :].T
        delta_b = delta_ref[0, 0:1, :].T
        s = scale * jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        rows, cols = _positions(block_q, block_k)
        s = jnp.where(ki * block_k + cols <= qj * block_q + rows, s, NEG_INF)
        p = jnp.exp(s - lse_b)                                   # (bq, bk)
        dv_acc[...] += jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_b) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qj == n_q - 1)
    def _():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, block_q, block_k):
    """Grid (bh, n_q, n_kv), kv innermost; dq scratch persists over kv."""
    qi, ki = pl.program_id(1), pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(ki * block_k <= (qi + 1) * block_q - 1)
    def _():
        qb = q_ref[0].astype(jnp.float32)
        dob = do_ref[0].astype(jnp.float32)
        lse_b = lse_ref[0, 0:1, :].T
        delta_b = delta_ref[0, 0:1, :].T
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        rows, cols = _positions(block_q, block_k)
        s = jnp.where(ki * block_k + cols <= qi * block_q + rows, s, NEG_INF)
        p = jnp.exp(s - lse_b)
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_b) * scale
        dq_acc[...] += jax.lax.dot(ds, kb, preferred_element_type=jnp.float32)

    @pl.when(ki == n_kv - 1)
    def _():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_call(q, k, v, out, lse, do, *, scale, block_q, block_k, interpret):
    bh, L, d = q.shape
    n_q, n_kv = L // block_q, L // block_k
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (bh, _SUBLANE, L))

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, block_q=block_q, block_k=block_k
        ),
        grid=(bh, n_kv, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, _SUBLANE, block_q), lambda b, i, j: (b, 0, j)),
            pl.BlockSpec((1, _SUBLANE, block_q), lambda b, i, j: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, L, d), q.dtype),
            jax.ShapeDtypeStruct((bh, L, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, block_q=block_q, block_k=block_k
        ),
        grid=(bh, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, _SUBLANE, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, _SUBLANE, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, L, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------------ custom vjp
@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_core(cfg, q, k, v):
    out, _ = _flash_core_fwd(cfg, q, k, v)
    return out


def _flash_core_fwd(cfg, q, k, v):
    scale, block_q, block_k, interpret = cfg
    out, lse = _flash_fwd_call(
        q, k, v, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _flash_core_bwd(cfg, res, do):
    scale, block_q, block_k, interpret = cfg
    q, k, v, out, lse = res
    return _flash_bwd_call(
        q, k, v, out, lse, do,
        scale=scale, block_q=block_q, block_k=block_k, interpret=interpret,
    )


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = _LANE,
    block_k: int = _LANE,
    interpret: bool | None = None,
) -> jax.Array:
    """Causal flash attention over ``(B, L, H, D)`` q/k/v.

    Sequence length is padded to the 128 tile internally; padded KV columns
    fall after every real query position so the causal mask excludes them,
    and padded query rows are sliced off on return. ``block_q``/``block_k``
    must divide the padded length.
    """
    if not causal:
        raise NotImplementedError("flash kernel is causal-only; use dense")
    b, L, h, d = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError("flash kernel requires identical q/k/v shapes")
    scale = scale if scale is not None else d ** -0.5
    if interpret is None:
        interpret = _interpret_default()

    L_pad = max(_LANE, -(-L // _LANE) * _LANE)
    block_q, block_k = min(block_q, L_pad), min(block_k, L_pad)
    if L_pad % block_q or L_pad % block_k:
        raise ValueError(
            f"block_q={block_q}/block_k={block_k} must divide padded length {L_pad}"
        )

    def to3(x):
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, L, d)
        if L_pad != L:
            x = jnp.pad(x, ((0, 0), (0, L_pad - L), (0, 0)))
        return x

    cfg = (float(scale), block_q, block_k, bool(interpret))
    out = _flash_core(cfg, to3(q), to3(k), to3(v))
    out = out[:, :L].reshape(b, h, L, d)
    return jnp.moveaxis(out, 1, 2)

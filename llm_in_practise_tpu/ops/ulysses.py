"""Ulysses-style all-to-all sequence parallelism over the ``seq`` mesh axis.

The second canonical long-context scheme next to ring attention
(:mod:`.ring_attention`), after DeepSpeed-Ulysses: activations stay
sequence-sharded through the whole network, and only around attention do
two ``all_to_all`` collectives re-partition — sequence-sharded
``(B, L/s, H, D)`` becomes head-sharded ``(B, L, H/s, D)``, every device
runs *ordinary dense/flash attention* over the full sequence for its head
group, and the second all-to-all restores sequence sharding.

Trade against the ring (why ship both — the reference ships neither,
SURVEY §5.7):

- **Ulysses**: 2 all-to-alls per attention, each moving the full
  activation block once; the attention itself is completely local, so any
  kernel (Pallas flash included) drops in unchanged. Requires
  ``n_kv_heads % seq == 0`` — the degree is capped by KV head count
  (GQA models cap hard).
- **Ring**: ppermute per step with compute overlap and no head-count
  constraint, but the attention inner loop must be ring-aware (online
  softmax across rotations).

Numerics: exactly dense attention — the collectives only permute data;
tests assert equality with the gathered-sequence reference on the
8-device CPU mesh, gradients included.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from llm_in_practise_tpu.core import mesh as mesh_lib
from llm_in_practise_tpu.ops.attention import dense_attention

try:  # jax>=0.4.35 stable location
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = mesh_lib.AXIS_SEQ,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """All-to-all attention; call inside ``shard_map`` over ``axis_name``.

    q/k/v: local shards ``(batch, local_len, heads, head_dim)``; the global
    sequence is the concatenation of shards in axis order. Heads must be
    divisible by the axis size. Returns the local output shard.
    """
    sp = jax.lax.psum(1, axis_name)
    if q.shape[2] % sp or k.shape[2] % sp:
        raise ValueError(
            f"ulysses needs heads divisible by the seq axis: "
            f"q heads {q.shape[2]}, kv heads {k.shape[2]}, axis {sp}"
        )

    def seq_to_heads(x):
        # (B, L/s, H, D) -> (B, L, H/s, D): split the head axis across the
        # devices, concatenate the sequence axis from them
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # GQA: broadcast the local KV head group AFTER the all-to-all, so the
    # collective only ever moves the compact kv heads
    groups = qh.shape[2] // kh.shape[2]
    if groups > 1:
        kh = jnp.repeat(kh, groups, axis=2)
        vh = jnp.repeat(vh, groups, axis=2)
    # full sequence, local head group: any attention body works unchanged
    out = dense_attention(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(out.astype(q.dtype))


def make_ulysses_attention(
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: float | None = None,
    batch_axes: Sequence[str] = mesh_lib.BATCH_AXES,
):
    """Wrap :func:`ulysses_attention` in shard_map over a concrete mesh.

    Returned fn takes *global* q/k/v ``(B, L, H, D)`` (batch over
    ``batch_axes``, sequence over ``seq``) and returns the output with the
    same sharding. Composable with jit. Note: unlike the ring wrapper,
    heads are NOT additionally sharded over ``model`` here — Ulysses
    already spends the head axis on the ``seq`` mesh dimension.
    """
    spec = P(tuple(batch_axes), mesh_lib.AXIS_SEQ, None, None)
    return _shard_map(
        functools.partial(ulysses_attention, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )


@functools.lru_cache(maxsize=32)
def _cached_ulysses_fn(mesh: Mesh, causal: bool, scale: float | None):
    return make_ulysses_attention(mesh, causal=causal, scale=scale)


def context_ulysses_attention(q, k, v, *, causal: bool = True, scale=None):
    """Ulysses attention over the ambient SP mesh (``attn_impl='ulysses'``
    under :class:`..ring_attention.sp_context` — same contract as ring)."""
    from llm_in_practise_tpu.ops.ring_attention import active_sp_mesh

    mesh = active_sp_mesh()
    if mesh is None:
        raise RuntimeError(
            "attn_impl='ulysses' needs an active sp_context(mesh) with seq>1"
        )
    return _cached_ulysses_fn(mesh, causal, scale)(q, k, v)

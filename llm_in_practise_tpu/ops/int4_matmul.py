"""Pallas TPU fused W4A16 matmul — the GPTQ/AWQ serving kernel.

The reference serves its GPTQ/AWQ exports through vLLM's W4A16 CUDA
kernels (Marlin — ``Quantization/LLM-Compressor/GPTQ/eval_qwen3_4b_gptq.py:
11-21`` loads ``quantization="compressed-tensors"``). This is the TPU
counterpart over the in-tree :class:`~llm_in_practise_tpu.quant.int4.
Int4Tensor` format (groups along K, packed ``(K//2, N)`` with adjacent-K
nibble pairs).

Mosaic won't lower the sublane interleave that unpacking adjacent-K pairs
wants, so the contraction splits instead: ``Σ_k x[k]·W[k] =
Σ_i x[2i]·W_hi[i] + Σ_i x[2i+1]·W_lo[i]`` — the activations are split
into even/odd K columns *outside* the kernel (cheap, activation-sized),
and each packed byte tile feeds two MXU dots, read once. Group scales and
zero-points expand along sublanes with the broadcast-reshape Mosaic does
support (both nibble halves of a byte row share a group when
``group_size`` is even, which every real group size is).

``int4_matmul`` is a drop-in for :func:`..quant.int4.dequant_matmul`:
same math, but the bf16 weight never materializes in HBM. The custom VJP
propagates to ``x`` only (quantized weights are frozen exports).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llm_in_practise_tpu.ops.nf4_matmul import _interpret_default, _pick_block
from llm_in_practise_tpu.quant import int4
from llm_in_practise_tpu.quant.int4 import Int4Tensor


def _expand_groups(v, rows, cols):
    """(rows//r, cols) per-group values → (rows, cols) row-repeated."""
    g = v.shape[0]
    rep = rows // g
    return jnp.broadcast_to(v[:, None, :], (g, rep, cols)).reshape(rows, cols)


def _dequant_halves(p, scales, zeros, block_kh, block_n):
    """packed (bkh, bn) + group params → (W_hi, W_lo) f32, each (bkh, bn).

    Row ``i`` of the packed tile holds codes for K rows ``2i`` (hi nibble)
    and ``2i+1`` (lo); both share the group of row ``i`` since the group
    size is even.
    """
    pi = p.astype(jnp.int32)
    s = _expand_groups(scales, block_kh, block_n)
    z = _expand_groups(zeros, block_kh, block_n)
    w_hi = (((pi >> 4) & 0xF).astype(jnp.float32) - z) * s
    w_lo = ((pi & 0xF).astype(jnp.float32) - z) * s
    return w_hi, w_lo


def _fwd_kernel(xe_ref, xo_ref, wp_ref, s_ref, z_ref, o_ref, acc_ref,
                *, block_m, block_n, block_kh):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w_hi, w_lo = _dequant_halves(
        wp_ref[...], s_ref[...], z_ref[...], block_kh, block_n)
    dot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[...] += dot(xe_ref[...].astype(jnp.bfloat16),
                        w_hi.astype(jnp.bfloat16))
    acc_ref[...] += dot(xo_ref[...].astype(jnp.bfloat16),
                        w_lo.astype(jnp.bfloat16))

    @pl.when(ki == pl.num_programs(2) - 1)
    def _():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _bwd_kernel(dy_ref, wp_ref, s_ref, z_ref, dxe_ref, dxo_ref,
                acc_e, acc_o, *, block_m, block_n, block_kh):
    ni = pl.program_id(2)

    @pl.when(ni == 0)
    def _():
        acc_e[...] = jnp.zeros_like(acc_e)
        acc_o[...] = jnp.zeros_like(acc_o)

    w_hi, w_lo = _dequant_halves(
        wp_ref[...], s_ref[...], z_ref[...], block_kh, block_n)
    dot_t = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dy = dy_ref[...].astype(jnp.bfloat16)
    acc_e[...] += dot_t(dy, w_hi.astype(jnp.bfloat16))
    acc_o[...] += dot_t(dy, w_lo.astype(jnp.bfloat16))

    @pl.when(ni == pl.num_programs(2) - 1)
    def _():
        dxe_ref[...] = acc_e[...].astype(dxe_ref.dtype)
        dxo_ref[...] = acc_o[...].astype(dxo_ref.dtype)


def _plan(t: Int4Tensor, m: int):
    k, n = t.shape
    gs = t.group_size
    if k % 2 or gs % 2 or k % gs:
        return None
    kh, gh = k // 2, gs // 2
    bn = _pick_block(n, 512)
    bkh = _pick_block(kh, 512)
    bm = 512 if m >= 512 else 256 if m >= 256 else 128
    if not bn or not bkh or bkh % gh:
        return None
    return bm, bn, bkh, gh


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def int4_matmul(x, t: Int4Tensor, out_dtype=None, interpret=None):
    """``x @ decode(t)`` streaming the weight in packed int4 form.

    x: (..., K); t: Int4Tensor (K, N). Falls back to dequant+matmul for
    shapes the tiling can't cover. VJP propagates to ``x`` only.
    """
    return _int4_matmul_fwd(x, t, out_dtype, interpret)[0]


def _int4_matmul_fwd(x, t, out_dtype, interpret):
    out_dtype = out_dtype or x.dtype
    interpret = _interpret_default() if interpret is None else interpret
    *lead, k = x.shape
    n = t.shape[1]
    m = int(np.prod(lead)) if lead else 1
    plan = _plan(t, m)
    if plan is None:
        out = x @ int4.decode(t, jnp.bfloat16).astype(x.dtype)
        return out.astype(out_dtype), (x.shape, jnp.zeros((0,), x.dtype), t, None)
    bm, bn, bkh, gh = plan
    kh = k // 2
    x2 = x.reshape(m, k)
    pad_m = (-m) % bm
    if pad_m:
        x2 = jnp.pad(x2, ((0, pad_m), (0, 0)))
    x3 = x2.reshape(-1, kh, 2)
    xe, xo = x3[:, :, 0], x3[:, :, 1]
    grid = (x2.shape[0] // bm, n // bn, kh // bkh)
    kernel = functools.partial(
        _fwd_kernel, block_m=bm, block_n=bn, block_kh=bkh)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bkh), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bm, bkh), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bkh, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((bkh // gh, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((bkh // gh, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((x2.shape[0], n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(xe, xo, t.packed, t.scales.astype(jnp.float32),
      t.zeros.astype(jnp.float32))
    return (out[:m].reshape(*lead, n),
            (x.shape, jnp.zeros((0,), x.dtype), t, plan))


def _int4_matmul_bwd(out_dtype, interpret, res, dy):
    x_shape, dtype_carrier, t, plan = res
    x_dtype = dtype_carrier.dtype
    interpret = _interpret_default() if interpret is None else interpret
    *lead, k = x_shape
    n = t.shape[1]
    if plan is None:
        dx = dy @ int4.decode(t, jnp.bfloat16).astype(dy.dtype).T
        return (dx.astype(x_dtype).reshape(x_shape), None)
    bm, bn, bkh, gh = plan
    kh = k // 2
    m = int(np.prod(lead)) if lead else 1
    dy2 = dy.reshape(m, n)
    pad_m = (-m) % bm
    if pad_m:
        dy2 = jnp.pad(dy2, ((0, pad_m), (0, 0)))
    grid = (dy2.shape[0] // bm, kh // bkh, n // bn)
    kernel = functools.partial(
        _bwd_kernel, block_m=bm, block_n=bn, block_kh=bkh)
    dxe, dxo = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bn), lambda i, kk, j: (i, j)),
            pl.BlockSpec((bkh, bn), lambda i, kk, j: (kk, j)),
            pl.BlockSpec((bkh // gh, bn), lambda i, kk, j: (kk, j)),
            pl.BlockSpec((bkh // gh, bn), lambda i, kk, j: (kk, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bkh), lambda i, kk, j: (i, kk)),
            pl.BlockSpec((bm, bkh), lambda i, kk, j: (i, kk)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((dy2.shape[0], kh), x_dtype),
            jax.ShapeDtypeStruct((dy2.shape[0], kh), x_dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bm, bkh), jnp.float32),
                        pltpu.VMEM((bm, bkh), jnp.float32)],
        interpret=interpret,
    )(dy2, t.packed, t.scales.astype(jnp.float32),
      t.zeros.astype(jnp.float32))
    dx = jnp.stack([dxe, dxo], axis=-1).reshape(dy2.shape[0], k)
    return (dx[:m].astype(x_dtype).reshape(x_shape), None)


int4_matmul.defvjp(_int4_matmul_fwd, _int4_matmul_bwd)

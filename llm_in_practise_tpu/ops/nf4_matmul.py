"""Pallas TPU fused NF4 dequant-matmul — the bitsandbytes kernel, TPU-shaped.

The reference's QLoRA forward runs bitsandbytes CUDA kernels that
dequantize the NF4 base on the fly inside the matmul
(``Fine-Tuning/qwen3-14b-qlora-dist-deepspeed.py:101-107``). The pure-JAX
path (:func:`llm_in_practise_tpu.quant.nf4.dequantize`) materializes the
bf16 weight in HBM first — 4x the weight traffic of the 4-bit stream. This
kernel keeps the weight packed all the way into VMEM and dequantizes tiles
right before the MXU dot, shaped by what Mosaic actually lowers:

- **Layout** (``NF4Tensor`` ``"kblock"``): absmax blocks along K (bnb
  parity — its 64-blocks run along torch's ``in`` dim), absmax ``(K//64,
  N)``; nibbles pair column ``i`` with column ``N//2 + i`` (split-half), so
  hi/lo unpack yields two *contiguous column halves* — no lane interleave,
  which Mosaic won't lower. The kernel computes the two halves as two MXU
  dots into a ``(bm, 2, bnh)`` output block; ``reshape(M, N)`` outside is
  the identity column order.
- **Scales**: the ``(bk//64, bnh)`` absmax tile expands to ``(bk, bnh)``
  with a broadcast-reshape along sublanes (supported), never a gather.
- **Codebook**: the 16-entry NF4 table is a 4-level binary select tree on
  the code bits (15 vectorized selects) — TPU-friendly where a 16-entry
  gather is not.
- **Pipeline**: grid ``(M/bm, NH/bnh, K/bk)``, K innermost; f32
  accumulators persist in VMEM scratch across K steps.
- **Backward** (QLoRA: base frozen, gradient flows to x only):
  ``dx = dy @ dequant(W)^T`` streams the same packed tiles, so the bf16
  weight never exists in HBM in either direction.

On non-TPU backends the kernels run in Pallas interpreter mode (same
logic, CPU-testable); :func:`nf4_matmul` falls back to dequant+matmul for
flat-layout tensors and shapes the tiling can't cover.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llm_in_practise_tpu.quant import nf4
from llm_in_practise_tpu.quant.nf4 import NF4Tensor

_NF4_VALS = tuple(float(v) for v in np.asarray(nf4.NF4_CODE))


def _interpret_default() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def _codes_to_vals(codes):
    """16-entry NF4 codebook lookup as a binary select tree (int32 → f32)."""
    vals = [jnp.full(codes.shape, v, jnp.float32) for v in _NF4_VALS]
    for bit in range(4):
        b = ((codes >> bit) & 1) == 1
        vals = [jnp.where(b, vals[2 * j + 1], vals[2 * j])
                for j in range(len(vals) // 2)]
    return vals[0]


def _expand_scale(am, block_k, block_nh):
    """(bk//64, bnh) absmax → (bk, bnh) by repeating each row BLOCK times
    (broadcast + leading-dim merge — the Mosaic-supported expansion)."""
    g = block_k // nf4.BLOCK
    return jnp.broadcast_to(
        am[:, None, :], (g, nf4.BLOCK, block_nh)
    ).reshape(block_k, block_nh)


def _dequant_halves(p, am_hi, am_lo, block_k, block_nh):
    """packed (bk, bnh) + absmax halves → (W_hi, W_lo), each (bk, bnh)."""
    pi = p.astype(jnp.int32)
    w_hi = _codes_to_vals((pi >> 4) & 0xF) * _expand_scale(am_hi, block_k, block_nh)
    w_lo = _codes_to_vals(pi & 0xF) * _expand_scale(am_lo, block_k, block_nh)
    return w_hi, w_lo


def _fwd_kernel(x_ref, wp_ref, am_ref, o_ref, acc_hi, acc_lo,
                *, block_m, block_nh, block_k):
    """o[m, {hi,lo}, nh] = Σ_k x[m, k]·W[k, ·]; grid (m, nh, k), k innermost."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc_hi[...] = jnp.zeros_like(acc_hi)
        acc_lo[...] = jnp.zeros_like(acc_lo)

    w_hi, w_lo = _dequant_halves(
        wp_ref[...], am_ref[:, 0, :], am_ref[:, 1, :], block_k, block_nh)
    x = x_ref[...].astype(jnp.bfloat16)
    # one wide MXU dot over the lane-concatenated halves
    w = jnp.concatenate([w_hi, w_lo], axis=1).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    acc_hi[...] += acc[:, :block_nh]
    acc_lo[...] += acc[:, block_nh:]

    @pl.when(ki == pl.num_programs(2) - 1)
    def _():
        o_ref[:, 0, :] = acc_hi[...].astype(o_ref.dtype)
        o_ref[:, 1, :] = acc_lo[...].astype(o_ref.dtype)


def _bwd_kernel(dy_ref, wp_ref, am_ref, dx_ref, acc_ref,
                *, block_m, block_nh, block_k):
    """dx[m, k] = Σ_n dy[m, n]·W[k, n]; grid (m, k, nh), nh innermost."""
    ni = pl.program_id(2)

    @pl.when(ni == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w_hi, w_lo = _dequant_halves(
        wp_ref[...], am_ref[:, 0, :], am_ref[:, 1, :], block_k, block_nh)
    dot_t = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[...] += dot_t(dy_ref[:, 0, :].astype(jnp.bfloat16),
                          w_hi.astype(jnp.bfloat16))
    acc_ref[...] += dot_t(dy_ref[:, 1, :].astype(jnp.bfloat16),
                          w_lo.astype(jnp.bfloat16))

    @pl.when(ni == pl.num_programs(2) - 1)
    def _():
        dx_ref[...] = acc_ref[...].astype(dx_ref.dtype)


def _pick_block(dim: int, target: int) -> int:
    """Largest divisor of ``dim`` ≤ target that is a multiple of 128."""
    for cand in range(min(target, dim) // 128 * 128, 127, -128):
        if dim % cand == 0:
            return cand
    return 0


def _plan(t: NF4Tensor, blocks, m: int = 128):
    """Resolve (bm, bnh, bk) tile sizes; None → caller falls back."""
    if t.layout != "kblock":
        return None
    k, n = t.shape
    if blocks is not None:
        bm, bnh, bk = blocks
    else:
        bnh = _pick_block(n // 2, 512)
        bk = _pick_block(k, 512)
        bm = 512 if m >= 512 else 256 if m >= 256 else 128
        if not bnh or not bk or bk % nf4.BLOCK:
            return None
    if (n // 2) % bnh or k % bk or bk % nf4.BLOCK:
        return None
    return bm, bnh, bk


def _call_fwd(x2, packed, absmax3, *, bm, bnh, bk, out_dtype, interpret):
    m, k = x2.shape
    nh = packed.shape[1]
    grid = (m // bm, nh // bnh, k // bk)
    kernel = functools.partial(
        _fwd_kernel, block_m=bm, block_nh=bnh, block_k=bk)
    out3 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bnh), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((bk // nf4.BLOCK, 2, bnh),
                         lambda i, j, kk: (kk, 0, j)),
        ],
        out_specs=pl.BlockSpec((bm, 2, bnh), lambda i, j, kk: (i, 0, j)),
        out_shape=jax.ShapeDtypeStruct((m, 2, nh), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bnh), jnp.float32),
                        pltpu.VMEM((bm, bnh), jnp.float32)],
        interpret=interpret,
    )(x2, packed, absmax3)
    # (M, 2, NH) row-major == [cols 0..NH) then [NH..N) — identity order
    return out3.reshape(m, 2 * nh)


def _call_bwd(dy2, packed, absmax3, *, bm, bnh, bk, out_dtype, interpret):
    m, n = dy2.shape
    k, nh = packed.shape
    grid = (m // bm, k // bk, nh // bnh)
    kernel = functools.partial(
        _bwd_kernel, block_m=bm, block_nh=bnh, block_k=bk)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, 2, bnh), lambda i, kk, j: (i, 0, j)),
            pl.BlockSpec((bk, bnh), lambda i, kk, j: (kk, j)),
            pl.BlockSpec((bk // nf4.BLOCK, 2, bnh),
                         lambda i, kk, j: (kk, 0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bk), lambda i, kk, j: (i, kk)),
        out_shape=jax.ShapeDtypeStruct((m, k), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bk), jnp.float32)],
        interpret=interpret,
    )(dy2.reshape(m, 2, n // 2), packed, absmax3)


def _layout_arrays(t: NF4Tensor):
    packed, absmax = nf4.kblock_arrays(t)       # (K, NH) u8, (K//64, N) f32
    n = t.shape[1]
    absmax3 = absmax.reshape(-1, 2, n // 2)     # [:, 0]=hi half, [:, 1]=lo
    return packed, absmax3


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def nf4_matmul(x, t: NF4Tensor, out_dtype=None, blocks=None, interpret=None):
    """``x @ dequant(t)`` with the weight streamed in 4-bit form.

    x: (..., K); t: NF4Tensor of shape (K, N). Returns (..., N). The base is
    a frozen constant (QLoRA): the VJP propagates to ``x`` only.
    """
    return _nf4_matmul_fwd(x, t, out_dtype, blocks, interpret)[0]


def _nf4_matmul_fwd(x, t, out_dtype, blocks, interpret):
    out_dtype = out_dtype or x.dtype
    interpret = _interpret_default() if interpret is None else interpret
    *lead, k = x.shape
    n = t.shape[1]
    m = int(np.prod(lead)) if lead else 1
    plan = _plan(t, blocks, m)
    if plan is None:
        out = x @ nf4.dequantize(t, jnp.bfloat16).astype(x.dtype)
        return out.astype(out_dtype), (x.shape, jnp.zeros((0,), x.dtype), t, None)
    bm, bnh, bk = plan
    x2 = x.reshape(m, k)
    pad_m = (-m) % bm
    if pad_m:
        x2 = jnp.pad(x2, ((0, pad_m), (0, 0)))
    packed, absmax3 = _layout_arrays(t)
    out = _call_fwd(x2, packed, absmax3, bm=bm, bnh=bnh, bk=bk,
                    out_dtype=out_dtype, interpret=interpret)
    return out[:m].reshape(*lead, n), (x.shape, jnp.zeros((0,), x.dtype), t, plan)


def _nf4_matmul_bwd(out_dtype, blocks, interpret, res, dy):
    x_shape, dtype_carrier, t, plan = res
    x_dtype = dtype_carrier.dtype
    interpret = _interpret_default() if interpret is None else interpret
    *lead, k = x_shape
    n = t.shape[1]
    if plan is None:
        dx = dy @ nf4.dequantize(t, jnp.bfloat16).astype(dy.dtype).T
        return (dx.astype(x_dtype).reshape(x_shape), None)
    bm, bnh, bk = plan
    m = int(np.prod(lead)) if lead else 1
    dy2 = dy.reshape(m, n)
    pad_m = (-m) % bm
    if pad_m:
        dy2 = jnp.pad(dy2, ((0, pad_m), (0, 0)))
    packed, absmax3 = _layout_arrays(t)
    dx = _call_bwd(dy2, packed, absmax3, bm=bm, bnh=bnh, bk=bk,
                   out_dtype=x_dtype, interpret=interpret)
    return (dx[:m].reshape(x_shape), None)


nf4_matmul.defvjp(_nf4_matmul_fwd, _nf4_matmul_bwd)

"""llm_in_practise_tpu — a TPU-native LLM framework (JAX/XLA/pjit/Pallas).

Brand-new implementation of the capabilities of the iKubernetes/llm-in-practise
curriculum (see /root/repo/SURVEY.md): from-scratch GPT / DeepSeek-style model
training, distributed pre-training (DP / ZeRO-1/2/3 / FSDP equivalents over a
`jax.sharding.Mesh`), LoRA/QLoRA fine-tuning with Pallas NF4 kernels, GPTQ/AWQ
post-training quantization, and a KV-cached OpenAI-compatible serving stack.

Design is TPU-first: parallelism is expressed as NamedSharding over mesh axes
(`data`, `fsdp`, `model`, `expert`, `seq`) with XLA emitting the collectives,
replacing the reference's NCCL/DDP/DeepSpeed engines.
"""

__version__ = "0.1.0"

from llm_in_practise_tpu.core.mesh import MeshSpec, build_mesh  # noqa: F401

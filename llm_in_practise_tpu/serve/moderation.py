"""Content moderation service — the Llama-Guard wrapper analog.

The reference wraps a vLLM-served Llama-Guard-3 behind a FastAPI
``/v1/moderations`` endpoint translating guard verdicts into the OpenAI
moderation schema, with an ``X-API-KEY`` middleware
(``Deployment/litellm-proxy/llama-guard-wrapper/{app.py:22-66,
model_client.py, openai_moderation_map.py, schemas.py}``). Here:

- the category taxonomy and OpenAI-schema mapping are ported behavior
  (S1..S13 hazard codes → ``hate``/``violence``/… flags),
- the *classifier* is pluggable: default is a transparent keyword/rule
  scorer (runs anywhere, no model download); pass ``classifier=`` any
  callable ``text -> list[str]`` of hazard codes — e.g. one that prompts a
  guard LLM served by :mod:`llm_in_practise_tpu.serve.api` the way the
  reference prompts Llama-Guard through vLLM,
- :func:`gateway_hook` adapts a service into the Gateway's pre-call check.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from http.server import ThreadingHTTPServer

from llm_in_practise_tpu.obs.registry import Registry
from llm_in_practise_tpu.serve.http_util import (
    JsonHandler,
    serve_obs_get,
    serve_obs_post,
)

# Llama-Guard-3 hazard taxonomy → OpenAI moderation categories
# (openai_moderation_map.py behavior).
HAZARD_TO_OPENAI = {
    "S1": "violence",                 # violent crimes
    "S2": "illicit",                  # non-violent crimes
    "S3": "sexual",                   # sex crimes
    "S4": "sexual/minors",
    "S5": "harassment",               # defamation
    "S6": "illicit",                  # specialized advice
    "S7": "privacy",
    "S8": "illicit",                  # intellectual property
    "S9": "illicit/violent",          # indiscriminate weapons
    "S10": "hate",
    "S11": "self-harm",
    "S12": "sexual",                  # adult content
    "S13": "illicit",                 # elections
}

OPENAI_CATEGORIES = sorted(set(HAZARD_TO_OPENAI.values()))

_DEFAULT_RULES: dict[str, tuple[str, ...]] = {
    "S1": ("kill", "murder", "attack someone", "hurt someone"),
    "S7": ("social security number", "home address of", "dox"),
    "S9": ("build a bomb", "make a weapon", "explosive device"),
    "S10": ("hate speech",),
    "S11": ("kill myself", "suicide", "self-harm", "hurt myself"),
}


def rule_classifier(rules: dict[str, tuple[str, ...]] | None = None):
    """Keyword classifier: ``text -> [hazard codes]``. The default stand-in
    for the guard model; deliberately conservative and transparent."""
    rules = rules or _DEFAULT_RULES
    compiled = {
        code: re.compile("|".join(re.escape(p) for p in pats), re.IGNORECASE)
        for code, pats in rules.items()
    }

    def classify(text: str) -> list[str]:
        return [code for code, rx in compiled.items() if rx.search(text)]

    return classify


@dataclass
class ModerationService:
    """``/v1/moderations`` with the OpenAI response schema."""

    classifier: object = field(default_factory=rule_classifier)
    api_key: str | None = None     # X-API-KEY middleware parity
    model_name: str = "guard-rules"
    requests_total: int = 0
    flagged_total: int = 0
    _httpd: ThreadingHTTPServer | None = None
    _registry: Registry | None = None

    def metrics_text(self) -> str:
        if self._registry is None:
            from llm_in_practise_tpu.obs.buildinfo import (
                register_build_info,
            )

            reg = Registry()
            # build identity (obs/buildinfo.py): same family on every
            # server in the stack
            register_build_info(reg, {
                "server": "moderation",
                "model": self.model_name,
                "api_key": bool(self.api_key),
            })
            reg.counter_func("moderation_requests_total",
                             lambda: self.requests_total,
                             help="inputs scored by the classifier")
            reg.counter_func("moderation_flagged_total",
                             lambda: self.flagged_total,
                             help="inputs flagged in any category")
            self._registry = reg
        return self._registry.render()

    def moderate(self, text: str) -> dict:
        """One input → OpenAI moderation result dict."""
        self.requests_total += 1
        hazards = list(self.classifier(text))
        categories = {c: False for c in OPENAI_CATEGORIES}
        scores = {c: 0.0 for c in OPENAI_CATEGORIES}
        for code in hazards:
            cat = HAZARD_TO_OPENAI.get(code)
            if cat:
                categories[cat] = True
                scores[cat] = 1.0
        flagged = any(categories.values())
        if flagged:
            self.flagged_total += 1
        return {
            "flagged": flagged,
            "categories": categories,
            "category_scores": scores,
        }

    def handle(self, body: dict) -> tuple[int, dict]:
        raw = body.get("input", "")
        inputs = raw if isinstance(raw, list) else [raw]
        results = [self.moderate(str(t)) for t in inputs]
        return 200, {
            "id": "modr-llm-in-practise-tpu",
            "model": body.get("model", self.model_name),
            "results": results,
        }

    # --- HTTP ----------------------------------------------------------------

    def make_handler(self):
        svc = self

        class Handler(JsonHandler):
            def do_GET(self):
                if serve_obs_get(self, svc.metrics_text):
                    return
                return self._json(404, {"error": {"message": "not found"}})

            def do_POST(self):
                if svc.api_key and self.headers.get("X-API-KEY") != svc.api_key:
                    return self._json(401, {"error": {"message": "invalid API key"}})
                if self.path != "/v1/moderations":
                    body, err = self._read_json()
                    if err:
                        return self._json(400, err)
                    if serve_obs_post(self, body):
                        return None
                    return self._json(404, {"error": {"message": "not found"}})
                body, err = self._read_json()
                if err:
                    return self._json(400, err)
                try:
                    status, resp = svc.handle(body)
                except Exception as e:  # noqa: BLE001 — a pluggable
                    # classifier's fault must answer the caller (the
                    # gateway fails open on moderation errors), never
                    # drop the connection
                    status, resp = 500, {"error": {
                        "message": f"{type(e).__name__}: {e}",
                        "type": "internal_error"}}
                return self._json(status, resp)

        return Handler

    def serve(self, host: str = "0.0.0.0", port: int = 8001, *,
              background: bool = False) -> int:
        self._httpd = ThreadingHTTPServer((host, port), self.make_handler())
        bound = self._httpd.server_address[1]
        if background:
            threading.Thread(
                target=self._httpd.serve_forever, daemon=True).start()
        else:
            self._httpd.serve_forever()
        return bound

    def shutdown(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None


def gateway_hook(service: ModerationService):
    """Adapt a ModerationService into the Gateway's pre-call moderation
    callable ``text -> (flagged, [categories])``."""

    def hook(text: str):
        result = service.moderate(text)
        cats = [c for c, v in result["categories"].items() if v]
        return result["flagged"], cats

    return hook

"""Shared stdlib-HTTP plumbing for the serving stack's three servers
(:mod:`.api`, :mod:`.gateway`, :mod:`.moderation`)."""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler


class JsonHandler(BaseHTTPRequestHandler):
    """Base handler: JSON responses, body parsing, quiet logging."""

    protocol_version = "HTTP/1.1"
    _responded = False

    def log_message(self, *args):  # quiet; obs handles logging
        pass

    def _json(self, status: int, payload: dict):
        self._responded = True
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, status: int, body: bytes, content_type: str):
        self._responded = True
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self):
        """Parse the request body; returns (dict, None) or (None, error)."""
        try:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length) or b"{}"), None
        except (ValueError, json.JSONDecodeError):
            return None, {"error": {"message": "invalid JSON body"}}

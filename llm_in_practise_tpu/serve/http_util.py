"""Shared stdlib-HTTP plumbing for the serving stack's servers
(:mod:`.api`, :mod:`.gateway`, :mod:`.moderation`, and the kv-pool's
metrics sidecar)."""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler


class JsonHandler(BaseHTTPRequestHandler):
    """Base handler: JSON responses, body parsing, quiet logging."""

    protocol_version = "HTTP/1.1"
    _responded = False

    def log_message(self, *args):  # quiet; obs handles logging
        pass

    def _json(self, status: int, payload: dict):
        self._responded = True
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, status: int, body: bytes, content_type: str):
        self._responded = True
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self):
        """Parse the request body; returns (dict, None) or (None, error)."""
        try:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length) or b"{}"), None
        except (ValueError, json.JSONDecodeError):
            return None, {"error": {"message": "invalid JSON body"}}


def serve_obs_get(handler: JsonHandler, metrics_text, tracer=None) -> bool:
    """Serve the observability GET triplet every server in the stack
    exposes (docs/observability.md) — ``/health``, ``/metrics``
    (Prometheus text exposition), ``/debug/traces`` (bounded span ring
    grouped by trace id). Returns True when the path was handled.

    ``metrics_text`` is a zero-arg callable; ``tracer`` defaults to the
    process tracer (servers constructed with their own pass it in).

    Fail-contained by contract (graftlint's ``handler-fail-open``
    safe-call list relies on it): a scrape callback that raises — a
    registry ``*_func`` over an object in a bad state — answers a 500
    JSON body instead of unwinding into socketserver, which would drop
    the connection and log a traceback nobody scrapes."""
    if handler.path == "/health":
        handler._json(200, {"status": "ok"})
        return True
    if handler.path == "/metrics":
        try:
            body = metrics_text().encode()
        except Exception as e:  # noqa: BLE001 — a broken scrape callback
            # must answer the scraper, never kill the handler thread
            handler._json(500, {"error": {
                "message": f"metrics render failed: "
                           f"{type(e).__name__}: {e}",
                "type": "internal_error"}})
            return True
        handler._text(200, body, "text/plain; version=0.0.4")
        return True
    if handler.path == "/debug/traces":
        try:
            if tracer is None:
                from llm_in_practise_tpu.obs.trace import get_tracer

                tracer = get_tracer()
            payload = tracer.debug_payload()
        except Exception as e:  # noqa: BLE001 — same contract as /metrics
            handler._json(500, {"error": {
                "message": f"trace snapshot failed: "
                           f"{type(e).__name__}: {e}",
                "type": "internal_error"}})
            return True
        handler._json(200, payload)
        return True
    return False


def obs_profile_response(body: dict | None) -> tuple[int, dict]:
    """Handle a ``POST /debug/profile`` body → ``(status, payload)``.

    Body: ``{"duration_s": <float, default 2, clamped to the capture's
    bound>}``. One capture at a time process-wide — a concurrent
    request gets a 409. Success payload carries the capture directory
    and the Perfetto-loadable files (see obs/prof.py); failures are
    contained to this response (a broken profiler must never take a
    server down). Shared by the JsonHandler servers (via
    :func:`serve_obs_post`) and the cache service's tuple-returning
    ``handle`` dispatch."""
    from llm_in_practise_tpu.obs.prof import ProfilerBusyError, get_profiler

    body = body or {}
    if not isinstance(body, dict):
        # a JSON list/string parses fine upstream; .get() on it would
        # be an AttributeError that kills the handler thread instead of
        # this 422 (the "failures contained to this response" contract)
        return 422, {"error": {"message": "body must be a JSON object",
                               "type": "invalid_request_error"}}
    try:
        duration = float(body.get("duration_s", 2.0))
    except (TypeError, ValueError):
        return 422, {"error": {"message": "duration_s must be a number",
                               "type": "invalid_request_error"}}
    try:
        result = get_profiler().capture(duration)
    except ProfilerBusyError as e:
        return 409, {"error": {"message": str(e),
                               "type": "conflict_error",
                               "code": "profile_busy"}}
    except Exception as e:  # noqa: BLE001 — profiler faults (unsupported
        # backend, full disk) answer the curl, never crash the server
        return 500, {"error": {"message": f"{type(e).__name__}: {e}",
                               "type": "internal_error",
                               "code": "profile_failed"}}
    return 200, result


def serve_obs_post(handler: JsonHandler, body: dict | None) -> bool:
    """Serve the observability POST route every server exposes —
    ``POST /debug/profile`` (bounded on-demand ``jax.profiler``
    capture; docs/observability.md "Device plane"). Returns True when
    the path was handled."""
    if handler.path != "/debug/profile":
        return False
    status, payload = obs_profile_response(body)
    handler._json(status, payload)
    return True

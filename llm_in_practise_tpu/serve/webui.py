"""Standalone chat web UI — the deployable Open-WebUI stage.

The reference deploys Open-WebUI on K8s as the user-facing chat front-end
over its serving stack (``LLM_on_Kubernetes/Open-WebUI/``) and compose
stacks for Ollama/AnythingLLM. The in-server page
(:func:`~.api.webui_html`) covers single-server use, but is not a
deployable unit: it lives inside one model server and cannot front the
gateway. This module is the deployable analog, stdlib-only:

- serves the same streaming chat page at ``/``;
- reverse-proxies ``POST /v1/chat/completions`` to the gateway (SSE bytes
  relayed chunk-by-chunk), so the browser talks same-origin — no CORS,
  and the gateway/service mesh stays internal;
- ``GET /health`` for probes.

Deployment: ``deploy/k8s/10-webui/`` runs this as a Deployment + Service +
Ingress pointing at the gateway Service.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from llm_in_practise_tpu.serve.api import webui_html


class WebUI:
    def __init__(self, gateway_url: str, *, model_name: str = "chat",
                 timeout_s: float = 300.0):
        self.gateway_url = gateway_url.rstrip("/")
        self.model_name = model_name
        self.timeout_s = timeout_s
        self._httpd: ThreadingHTTPServer | None = None

    def serve(self, host: str = "0.0.0.0", port: int = 3000,
              *, background: bool = False):
        ui = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, status: int, data: bytes, ctype: str):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    if self.path in ("/", "/index.html"):
                        page = webui_html(ui.model_name).encode()
                        return self._send(200, page,
                                          "text/html; charset=utf-8")
                    if self.path == "/health":
                        return self._send(200, b'{"status": "ok"}',
                                          "application/json")
                except Exception as e:  # noqa: BLE001 — answer the
                    # browser, never drop the connection on a GET fault
                    return self._send(500, json.dumps({"error": {
                        "message": f"{type(e).__name__}: {e}"}}).encode(),
                        "application/json")
                self._send(404, b'{"error": "not found"}',
                           "application/json")

            def do_POST(self):
                if self.path != "/v1/chat/completions":
                    return self._send(404, b'{"error": "not found"}',
                                      "application/json")
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n) if n else b"{}"
                    req = urllib.request.Request(
                        ui.gateway_url + "/v1/chat/completions", data=body,
                        headers={"Content-Type": "application/json"},
                    )
                except Exception as e:  # noqa: BLE001 — truncated body /
                    # bad gateway URL: a 400 the browser can show
                    return self._send(400, json.dumps({"error": {
                        "message": f"{type(e).__name__}: {e}"}}).encode(),
                        "application/json")
                try:
                    resp = urllib.request.urlopen(req, timeout=ui.timeout_s)
                except urllib.error.HTTPError as e:
                    try:
                        detail = e.read() or b"{}"
                    except Exception:  # noqa: BLE001 — error body gone
                        # (peer closed mid-read); the status code stands
                        detail = b"{}"
                    return self._send(e.code, detail, "application/json")
                except Exception as e:  # noqa: BLE001 — unreachable,
                    # timeout, bad scheme: a 502 the browser can show,
                    # never a dropped connection
                    return self._send(502, json.dumps({"error": {
                        "message": f"gateway unreachable: {e}"}}).encode(),
                        "application/json")
                with resp:
                    ctype = resp.headers.get("Content-Type",
                                             "application/json")
                    if "text/event-stream" in ctype:
                        # SSE relay: forward bytes as they arrive
                        self.send_response(resp.status)
                        self.send_header("Content-Type", ctype)
                        self.send_header("Cache-Control", "no-store")
                        self.send_header("Connection", "close")
                        self.end_headers()
                        try:
                            while True:
                                chunk = resp.read(4096)
                                if not chunk:
                                    break
                                self.wfile.write(chunk)
                                self.wfile.flush()
                        except (BrokenPipeError, ConnectionResetError):
                            pass  # browser went away mid-stream
                        except Exception:  # noqa: BLE001 — upstream died
                            # mid-relay; headers are out, just stop
                            pass
                        return
                    try:
                        payload = resp.read()
                    except Exception as e:  # noqa: BLE001 — gateway died
                        # mid-body: a 502 the browser can show
                        return self._send(502, json.dumps({"error": {
                            "message": f"gateway read failed: "
                                       f"{e}"}}).encode(),
                            "application/json")
                    self._send(resp.status, payload, ctype)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        bound = self._httpd.server_address
        if background:
            threading.Thread(target=self._httpd.serve_forever,
                             daemon=True).start()
        else:
            print(f"web ui on {bound[0]}:{bound[1]} -> {self.gateway_url}")
            self._httpd.serve_forever()
        return bound

    def shutdown(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


def main() -> None:
    """Run the chat UI (``deploy/k8s/10-webui/``)."""
    import argparse

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=3000)
    p.add_argument("--gateway-url", required=True,
                   help="base URL of the gateway (e.g. http://gateway:4000)")
    p.add_argument("--model", default="chat",
                   help="model/group name sent with chat requests")
    args = p.parse_args()
    WebUI(args.gateway_url, model_name=args.model).serve(args.host, args.port)


if __name__ == "__main__":
    main()

"""Standalone exact+semantic response cache service — the deployable L2/L3
cache stage.

The reference builds this as its own platform stage: a cache gateway with a
Redis/Valkey exact tier, a semantic tier keyed by embeddings from a separate
embedding service, and K8s manifests wiring multiple LiteLLM replicas to the
shared store (``LLM_on_Kubernetes/Inference_Platfrom/README.md:2845-3488``).
In-process caching inside each gateway replica (``gateway.ResponseCache``)
cannot give that: two replicas answering the same question still compute it
twice.

This module is that stage, stdlib-only:

- :class:`CacheService` — an HTTP service holding ONE
  :class:`~.gateway.ResponseCache` shared by every gateway replica.
  ``POST /cache/get`` (the chat request body) → ``{"found": bool,
  "response": ...}``; ``POST /cache/put`` (``{"request", "response"}``);
  ``GET /metrics`` (Prometheus text), ``GET /health``. Optionally takes
  ``embed_url`` pointing at a ``/v1/embeddings`` endpoint (the model
  server's — :mod:`.api` serves it) so the semantic tier matches on real
  model embeddings instead of hashed bag-of-words, exactly the reference's
  cache→embedding-service call graph.

- :class:`RemoteResponseCache` — the client a gateway replica holds in
  place of its in-process cache (duck-typed ``get``/``put``). Fail-open:
  a dead or slow cache service degrades to a miss (with a cooldown so the
  serving path doesn't pay a connect timeout per request), never an error.

Deployment: ``deploy/k8s/09-semantic-cache/`` runs this as a Deployment +
ClusterIP Service and points the gateway replicas at it (``--cache-url``).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from llm_in_practise_tpu.obs.registry import Registry
from llm_in_practise_tpu.serve.gateway import ResponseCache


def embeddings_client(embed_url: str, *, timeout_s: float = 10.0,
                      model: str = ""):
    """``embed_fn(text) -> list[float]`` backed by a ``/v1/embeddings``
    endpoint; raises on transport errors (the caller decides the fallback)."""

    def embed(text: str) -> list[float]:
        req = urllib.request.Request(
            embed_url.rstrip("/") + "/v1/embeddings",
            data=json.dumps({"input": text, "model": model}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            payload = json.loads(r.read())
        return payload["data"][0]["embedding"]

    return embed


class CacheService:
    """One shared cache, HTTP-fronted. See module docstring."""

    def __init__(self, *, ttl_s: float = 300.0, max_entries: int = 4096,
                 semantic_threshold: float | None = 0.97,
                 embed_url: str | None = None):
        embed_fn = None
        if embed_url:
            remote = embeddings_client(embed_url)
            fallback_failures = {"n": 0}

            def embed_fn(text: str) -> list[float]:
                # embedding-service outage must not take the cache down:
                # fall back to the hashed-BoW embedding (entries made under
                # different encoders won't cross-match above threshold —
                # self-consistent within each encoder's entries)
                from llm_in_practise_tpu.serve.gateway import _token_embed
                try:
                    return remote(text)
                except (urllib.error.URLError, TimeoutError, OSError,
                        KeyError, json.JSONDecodeError):
                    fallback_failures["n"] += 1
                    return _token_embed(text)

            self._embed_failures = fallback_failures
        else:
            self._embed_failures = {"n": 0}
        self.cache = ResponseCache(
            ttl_s=ttl_s, max_entries=max_entries,
            semantic_threshold=semantic_threshold, embed_fn=embed_fn)
        self._httpd: ThreadingHTTPServer | None = None
        self.registry = self._build_registry()

    # -- request handling -----------------------------------------------------

    def handle(self, method: str, path: str, body: dict | None):
        """(status, response-dict). Transport-agnostic for tests."""
        if method == "GET" and path == "/health":
            return 200, {"status": "ok"}
        if method == "GET" and path == "/metrics":
            return 200, {"text": self.metrics_text()}
        if method == "GET" and path == "/debug/traces":
            # every server in the stack serves the process trace ring
            # (docs/observability.md) — populated here whenever any
            # span-recording component is colocated in this process
            from llm_in_practise_tpu.obs.trace import get_tracer

            return 200, get_tracer().debug_payload()
        if method == "POST" and path == "/debug/profile":
            # the observability POST every server exposes: bounded
            # on-demand jax.profiler capture (obs/prof.py; one at a
            # time process-wide)
            from llm_in_practise_tpu.serve.http_util import (
                obs_profile_response,
            )

            return obs_profile_response(body)
        if method == "POST" and path == "/cache/get":
            if not isinstance(body, dict):
                return 422, {"error": "body must be the chat request"}
            hit = self.cache.get(body)
            return 200, ({"found": True, "response": hit}
                         if hit is not None else {"found": False})
        if method == "POST" and path == "/cache/put":
            if (not isinstance(body, dict)
                    or not isinstance(body.get("request"), dict)
                    or "response" not in body):
                return 422, {"error": "body must be {request, response}"}
            self.cache.put(body["request"], body["response"])
            return 200, {"ok": True}
        return 404, {"error": f"no route {method} {path}"}

    def _build_registry(self) -> Registry:
        """Unified-registry exposition (obs/registry.py). Every family
        now gets a ``# TYPE`` header — the hand-rolled block emitted
        bare samples, which strict Prometheus parsers reject (the bug
        the migration subsumes; pinned by the exposition tests)."""
        c = self.cache
        reg = Registry()
        # build identity (obs/buildinfo.py): same family on every server
        from llm_in_practise_tpu.obs.buildinfo import register_build_info

        register_build_info(reg, {
            "server": "cache_service",
            "ttl_s": c.ttl_s,
            "max_entries": c.max_entries,
            "semantic_threshold": c.semantic_threshold,
        })
        reg.counter_func("llm_cache_exact_hits_total", lambda: c.hits)
        reg.counter_func("llm_cache_semantic_hits_total",
                         lambda: c.semantic_hits)
        reg.counter_func("llm_cache_misses_total", lambda: c.misses)
        reg.gauge_func("llm_cache_entries", lambda: len(c._exact))
        reg.gauge_func("llm_cache_semantic_entries",
                       lambda: len(c._semantic))
        reg.counter_func("llm_cache_embed_fallbacks_total",
                         lambda: self._embed_failures["n"],
                         "semantic lookups that fell back to hashed-BoW "
                         "after an embedding-service fault")
        return reg

    def metrics_text(self) -> str:
        return self.registry.render()

    # -- HTTP plumbing --------------------------------------------------------

    def serve(self, host: str = "0.0.0.0", port: int = 8200,
              *, background: bool = False):
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _reply(self, status: int, payload: dict):
                if "text" in payload and len(payload) == 1:
                    data = payload["text"].encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    data = json.dumps(payload).encode()
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    status, payload = service.handle("GET", self.path, None)
                except Exception as e:  # noqa: BLE001 — a handler fault
                    # (scrape callback, cache state) answers 500, never
                    # drops the scraper's connection
                    status, payload = 500, {
                        "error": f"{type(e).__name__}: {e}"}
                self._reply(status, payload)

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n)) if n else None
                except (ValueError, json.JSONDecodeError):
                    return self._reply(422, {"error": "invalid JSON"})
                except Exception as e:  # noqa: BLE001 — truncated body /
                    # transport fault mid-read: answer, don't unwind
                    return self._reply(400, {
                        "error": f"{type(e).__name__}: {e}"})
                try:
                    status, payload = service.handle("POST", self.path,
                                                     body)
                except Exception as e:  # noqa: BLE001 — e.g. a remote
                    # embed_fn fault path nobody anticipated: the cache
                    # is an optimization, its faults must be 500s the
                    # gateway's fail-open client can count and skip
                    status, payload = 500, {
                        "error": f"{type(e).__name__}: {e}"}
                self._reply(status, payload)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        bound = self._httpd.server_address
        if background:
            threading.Thread(target=self._httpd.serve_forever,
                             daemon=True).start()
        else:
            print(f"cache service on {bound[0]}:{bound[1]}")
            self._httpd.serve_forever()
        return bound

    def shutdown(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


class RemoteResponseCache:
    """Gateway-side client for a shared :class:`CacheService`.

    Duck-types ``gateway.ResponseCache``'s ``get``/``put`` so
    ``Gateway(cache=RemoteResponseCache(url))`` is a drop-in swap. Fail-open
    with a cooldown: an unreachable cache service costs one failed call,
    then sits out ``cooldown_s`` — the serving path never blocks on a dead
    cache longer than ``timeout_s`` once per cooldown window.
    """

    def __init__(self, base_url: str, *, timeout_s: float = 2.0,
                 cooldown_s: float = 30.0, clock=time.monotonic):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.cooldown_s = cooldown_s
        self.errors = 0
        # local counters mirroring ResponseCache's surface — the gateway's
        # /metrics reads cache.hits/semantic_hits/misses whenever a cache
        # is configured (gateway.metrics_text). The service does not say
        # whether a hit was exact or semantic, so hits counts both here
        # and semantic_hits stays 0; the split lives in the service's own
        # /metrics.
        self.hits = 0
        self.semantic_hits = 0
        self.misses = 0
        # get() calls that never reached the service (cooldown window or
        # transport error). Kept out of `misses` so the gateway's hit-rate
        # metric doesn't conflate outage time with genuine cache misses.
        self.skipped = 0
        self._down_until = 0.0
        self._clock = clock

    def _post(self, path: str, payload: dict) -> dict | None:
        if self._clock() < self._down_until:
            return None
        req = urllib.request.Request(
            self.base_url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, TimeoutError, OSError,
                json.JSONDecodeError):
            self.errors += 1
            self._down_until = self._clock() + self.cooldown_s
            return None

    def get(self, body: dict) -> dict | None:
        if body.get("stream"):
            return None
        reply = self._post("/cache/get", body)
        if reply is None:
            # Cooldown short-circuit or transport failure — the service
            # never answered, so this is not a cache miss.
            self.skipped += 1
            return None
        if reply.get("found"):
            self.hits += 1
            return reply["response"]
        self.misses += 1
        return None

    def put(self, body: dict, response: dict) -> None:
        if body.get("stream"):
            return
        self._post("/cache/put", {"request": body, "response": response})


def main() -> None:
    """Run the shared cache service (``deploy/k8s/09-semantic-cache/``)."""
    import argparse

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8200)
    p.add_argument("--ttl", type=float, default=300.0)
    p.add_argument("--max-entries", type=int, default=4096)
    p.add_argument("--semantic-threshold", type=float, default=0.97,
                   help="<=0 disables the semantic tier")
    p.add_argument("--embed-url", default=None,
                   help="base URL of a /v1/embeddings service for real "
                        "semantic matching (default: hashed bag-of-words)")
    args = p.parse_args()
    thr = args.semantic_threshold if args.semantic_threshold > 0 else None
    CacheService(ttl_s=args.ttl, max_entries=args.max_entries,
                 semantic_threshold=thr, embed_url=args.embed_url,
                 ).serve(args.host, args.port)


if __name__ == "__main__":
    main()

"""Constrained decoding: grammar-compiled logit masks (ISSUE 12 tentpole).

The reference platform's serving track is OpenAI-surface-first, and the
agent/tool-calling workload class it implies needs *structured output*:
``response_format={"type": "json_schema"}`` must make every sampled
completion parse AND validate. vLLM/outlines/llguidance do this with a
grammar compiled against the tokenizer; this module is the TPU-native
equivalent, shaped so the engine's pinned 1-dispatch-per-step invariant
survives:

- **A small EBNF core** (:class:`Lit` / :class:`Chars` / :class:`Seq` /
  :class:`Alt` / :class:`Rep` / :class:`Ref`) interpreted as a
  character-level NFA with a pushdown continuation stack — ``Ref``
  recursion is what lets generic JSON nest, and the continuation tuples
  ARE the stack, so automaton states stay hashable and memoizable.
- **Two front-ends**: :func:`compile_regex` (anchored subset: literals,
  classes, ``. | * + ? {m,n}``, groups) and :func:`compile_schema`
  (the JSON-Schema subset in docs/structured-output.md — unsupported
  keywords raise :class:`ConstraintError`, they are never silently
  ignored, so "validates against the schema" stays a theorem).
- **A token-level automaton** (:class:`TokenAutomaton`): per automaton
  state, a vocab-width additive logit mask (0 = allowed, ``NEG_INF`` =
  forbidden) plus a token→next-state table, compiled LAZILY on first
  visit by simulating each vocab piece through the char NFA. The masks
  are what the engine adds to logits INSIDE its existing jitted
  programs (serve/engine.py "grammar" sections); the lazy compile is
  the dominant cost and books under the ``grammar_compile`` host
  activity so PR 11's step-timeline coverage gate stays honest.
- **Per-request cursors** (:class:`ConstraintState`): mutable current
  state + done flag, carried on the engine Request so
  preempt-by-recompute resume and slot churn keep byte-identical
  streams without replaying the grammar.

Generation is *canonical*: no inter-token whitespace, object properties
are exactly the schema's ``required`` list in declaration order, and
free-form strings draw from escaped-free printable ASCII. Canonical
output is a strict subset of conforming output — everything emitted
still validates (:func:`validate_instance`, fuzz-pinned by
``tests/test_structured_output.py``).

Thread model: a compiled :class:`TokenAutomaton` is shared across
requests and may be driven by several engine threads (base + adapter
engines), so its lazy state caches are lock-guarded; cursors belong to
one request and are engine-thread-only.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np

NEG_INF = np.float32(-1e30)  # matches infer/sampling.NEG_INF

# free-form string content: printable ASCII minus the two chars that
# would need escaping ('"' and '\\') — escape-free strings keep the char
# NFA tiny and every emitted string is still valid JSON
_STR_CHARS = frozenset(chr(c) for c in range(0x20, 0x7F)) - {'"', "\\"}
_DIGITS = frozenset("0123456789")
_DIGITS19 = frozenset("123456789")


class ConstraintError(ValueError):
    """Invalid or unsupported constraint spec — the API layer maps this
    to HTTP 422 (an unsupported schema must fail fast, not generate
    output that silently ignores a keyword)."""


# --- EBNF core ------------------------------------------------------------
#
# Nodes are plain objects compared by identity; grammars are DAGs (with
# Ref-cycles for recursion) built once per compiled constraint.


class Lit:
    __slots__ = ("text",)

    def __init__(self, text: str):
        self.text = text


class Chars:
    """One character drawn from ``allowed``."""

    __slots__ = ("allowed",)

    def __init__(self, allowed):
        self.allowed = frozenset(allowed)
        if not self.allowed:
            raise ConstraintError("empty character class")


class Seq:
    __slots__ = ("parts",)

    def __init__(self, parts):
        self.parts = tuple(parts)


class Alt:
    __slots__ = ("options",)

    def __init__(self, options):
        self.options = tuple(options)
        if not self.options:
            raise ConstraintError("empty alternation")


class Rep:
    """``item (sep item)*`` with count bounds: at least ``lo`` items,
    at most ``hi`` (None = unbounded). ``lo=0`` admits the empty
    production. The separator shape is exactly JSON's comma-joined
    arrays/objects; ``sep=None`` gives plain regex repetition."""

    __slots__ = ("item", "sep", "lo", "hi")

    def __init__(self, item, sep=None, lo=0, hi=None):
        if hi is not None and hi < lo:
            raise ConstraintError(f"repetition bounds {lo}..{hi} empty")
        self.item, self.sep, self.lo, self.hi = item, sep, lo, hi


class Ref:
    """Lazy indirection — the knot that lets generic JSON values nest.
    The target is assigned after construction (two-phase tying)."""

    __slots__ = ("target",)

    def __init__(self, target=None):
        self.target = target


_END = "<end>"  # accepting marker inside a state frozenset


def _expand(node, cont, out, guard):
    """Epsilon-closure of ``node`` then ``cont`` into consuming
    positions (``("lit", node, i, cont)`` / ``("chr", node, cont)``)
    plus the ``_END`` marker. ``guard`` breaks epsilon cycles (a
    malformed grammar like ``Rep(Seq([]))``)."""
    key = (id(node), cont)
    if key in guard:
        return
    guard.add(key)
    if isinstance(node, Lit):
        if node.text:
            out.add(("lit", node, 0, cont))
        else:
            _expand_cont(cont, out, guard)
    elif isinstance(node, Chars):
        out.add(("chr", node, cont))
    elif isinstance(node, Seq):
        if not node.parts:
            _expand_cont(cont, out, guard)
            return
        c = cont
        for p in reversed(node.parts[1:]):
            c = ("n", p, c)
        _expand(node.parts[0], c, out, guard)
    elif isinstance(node, Alt):
        for opt in node.options:
            _expand(opt, cont, out, guard)
    elif isinstance(node, Rep):
        if node.lo <= 0:
            _expand_cont(cont, out, guard)
        if node.hi is None or node.hi > 0:
            _expand(node.item, ("rep", node, 1, cont), out, guard)
    elif isinstance(node, Ref):
        if node.target is None:
            raise ConstraintError("unresolved grammar reference")
        _expand(node.target, cont, out, guard)
    else:  # pragma: no cover — construction-time type error
        raise ConstraintError(f"unknown grammar node {type(node).__name__}")


def _expand_cont(cont, out, guard):
    """Continue past a finished node: pop the continuation stack."""
    if cont is None:
        out.add(_END)
        return
    tag = cont[0]
    if tag == "n":
        _expand(cont[1], cont[2], out, guard)
    elif tag == "rep":
        rep, k, rest = cont[1], cont[2], cont[3]
        if k >= rep.lo:
            _expand_cont(rest, out, guard)
        if rep.hi is None or k < rep.hi:
            if rep.sep is not None:
                _expand(rep.sep, ("repsep", rep, k, rest), out, guard)
            else:
                _expand(rep.item, ("rep", rep, k + 1, rest), out, guard)
    elif tag == "repsep":
        rep, k, rest = cont[1], cont[2], cont[3]
        _expand(rep.item, ("rep", rep, k + 1, rest), out, guard)
    else:  # pragma: no cover
        raise ConstraintError(f"unknown continuation tag {tag!r}")


def start_state(root) -> frozenset:
    out: set = set()
    _expand(root, None, out, set())
    return frozenset(out)


def char_transitions(state: frozenset) -> dict:
    """``{char: next_state}`` for every char consumable from ``state``."""
    trans: dict[str, set] = {}
    for pos in state:
        if pos == _END:
            continue
        if pos[0] == "lit":
            _, node, i, cont = pos
            tgt = trans.setdefault(node.text[i], set())
            if i + 1 < len(node.text):
                tgt.add(("lit", node, i + 1, cont))
            else:
                _expand_cont(cont, tgt, set())
        else:  # "chr"
            _, node, cont = pos
            after: set = set()
            _expand_cont(cont, after, set())
            for ch in node.allowed:
                trans.setdefault(ch, set()).update(after)
    return {ch: frozenset(s) for ch, s in trans.items()}


def is_accepting(state: frozenset) -> bool:
    return _END in state


# --- regex front-end ------------------------------------------------------

_CLASS_SHORTHAND = {
    "d": _DIGITS,
    "w": _DIGITS | frozenset("abcdefghijklmnopqrstuvwxyz"
                             "ABCDEFGHIJKLMNOPQRSTUVWXYZ_"),
    "s": frozenset(" \t"),
}


def compile_regex(pattern: str, *, charset=_STR_CHARS):
    """Anchored-full-match regex subset → grammar node. Supports
    literals, ``\\d \\w \\s`` + escaped metachars, ``.``, ``[...]``
    classes (ranges, negation), groups, ``|``, and ``* + ? {m} {m,}
    {m,n}``. Everything is intersected with ``charset`` so a schema
    string ``pattern`` can never generate JSON-breaking characters.
    Unsupported syntax raises :class:`ConstraintError`."""
    pos = 0
    n = len(pattern)

    def peek():
        return pattern[pos] if pos < n else None

    def take():
        nonlocal pos
        ch = pattern[pos]
        pos += 1
        return ch

    def parse_alt():
        opts = [parse_concat()]
        while peek() == "|":
            take()
            opts.append(parse_concat())
        return opts[0] if len(opts) == 1 else Alt(opts)

    def parse_concat():
        parts = []
        while peek() is not None and peek() not in "|)":
            parts.append(parse_repeat())
        return Seq(parts)

    def parse_repeat():
        atom = parse_atom()
        ch = peek()
        if ch == "*":
            take()
            return Rep(atom, lo=0, hi=None)
        if ch == "+":
            take()
            return Rep(atom, lo=1, hi=None)
        if ch == "?":
            take()
            return Rep(atom, lo=0, hi=1)
        if ch == "{":
            take()
            spec = ""
            while peek() is not None and peek() != "}":
                spec += take()
            if peek() != "}":
                raise ConstraintError(f"unterminated {{…}} in {pattern!r}")
            take()
            try:
                if "," in spec:
                    lo_s, hi_s = spec.split(",", 1)
                    lo = int(lo_s)
                    hi = int(hi_s) if hi_s.strip() else None
                else:
                    lo = hi = int(spec)
            except ValueError:
                raise ConstraintError(
                    f"bad repetition {{{spec}}} in {pattern!r}") from None
            return Rep(atom, lo=lo, hi=hi)
        return atom

    def class_chars(inner: str):
        chars: set = set()
        i = 0
        negate = inner.startswith("^")
        if negate:
            i = 1
        while i < len(inner):
            c = inner[i]
            if c == "\\" and i + 1 < len(inner):
                esc = inner[i + 1]
                chars |= _CLASS_SHORTHAND.get(esc, frozenset(esc))
                i += 2
                continue
            if i + 2 < len(inner) and inner[i + 1] == "-":
                chars |= {chr(x) for x in
                          range(ord(c), ord(inner[i + 2]) + 1)}
                i += 3
                continue
            chars.add(c)
            i += 1
        return (charset - chars) if negate else (chars & charset)

    def parse_atom():
        ch = take()
        if ch == "(":
            if peek() == "?":  # (?: …) non-capturing — groups don't
                take()         # capture here anyway
                if peek() != ":":
                    raise ConstraintError(
                        f"unsupported group modifier in {pattern!r}")
                take()
            inner = parse_alt()
            if peek() != ")":
                raise ConstraintError(f"unbalanced group in {pattern!r}")
            take()
            return inner
        if ch == "[":
            inner = ""
            while peek() is not None and peek() != "]":
                if peek() == "\\":
                    inner += take()
                inner += take()
            if peek() != "]":
                raise ConstraintError(f"unterminated class in {pattern!r}")
            take()
            allowed = class_chars(inner)
            if not allowed:
                raise ConstraintError(
                    f"class [{inner}] has no generatable chars")
            return Chars(allowed)
        if ch == ".":
            return Chars(charset)
        if ch == "\\":
            if peek() is None:
                raise ConstraintError(f"dangling escape in {pattern!r}")
            esc = take()
            if esc in _CLASS_SHORTHAND:
                return Chars(_CLASS_SHORTHAND[esc] & charset)
            if esc.isalnum():
                # \n, \t, \b, \1 … — either a control char no JSON
                # string can carry raw, or regex syntax this engine
                # doesn't implement. Generating the literal LETTER
                # instead would emit output that fails the very
                # pattern it must enforce — fail fast (→ 422).
                raise ConstraintError(
                    f"unsupported escape \\{esc} in {pattern!r}")
            return Lit(esc)            # escaped metachar: \. \[ \\ …
        if ch in "^$":
            # patterns are anchored by construction; an explicit anchor
            # is a no-op at its own end of the pattern and an error
            # anywhere else (a mid-pattern anchor can never match the
            # single string this grammar generates)
            if (ch == "^" and pos != 1) or (ch == "$" and pos != n):
                raise ConstraintError(
                    f"mid-pattern anchor {ch!r} in {pattern!r}")
            return Seq([])
        if ch in "*+?{":
            raise ConstraintError(f"dangling quantifier in {pattern!r}")
        return Lit(ch)

    node = parse_alt()
    if pos != n:
        raise ConstraintError(f"trailing regex syntax in {pattern!r}")
    return node


# --- JSON Schema front-end ------------------------------------------------

# Canonical generation bounds (docs/structured-output.md): unbounded
# schema productions get finite caps so constrained generation always
# TERMINATES structurally — without them a model that argmaxes digits
# (or padding chars) forever can only ever finish with a truncated,
# INVALID stream (finish_reason "length"), defeating the conformance
# guarantee. Caps only shrink the generatable set — everything emitted
# still validates. Explicit schema bounds (maxLength/maxItems) override.
_MAX_DIGITS = 16          # digits per integer part / fraction
_MAX_STRING = 256         # free-form string chars without maxLength
_FREE_STRING = 64         # string chars inside json_object mode
_MAX_ITEMS = 64           # array items without maxItems
_FREE_ITEMS = 16          # container members in json_object mode
_FREE_DEPTH = 6           # nesting depth in json_object mode

_COMMON_KEYS = {"type", "title", "description", "default", "examples",
                "$schema"}
_ALLOWED_KEYS = {
    "object": {"properties", "required", "additionalProperties"},
    "string": {"enum", "const", "minLength", "maxLength", "pattern"},
    "integer": {"enum", "const"},
    "number": {"enum", "const"},
    "boolean": {"enum", "const"},
    "null": set(),
    "array": {"items", "minItems", "maxItems"},
}


def _json_lit(value) -> Lit:
    return Lit(json.dumps(value, separators=(",", ":")))


def _integer_node():
    body = Alt([Lit("0"),
                Seq([Chars(_DIGITS19),
                     Rep(Chars(_DIGITS), hi=_MAX_DIGITS - 1)])])
    return Seq([Rep(Lit("-"), lo=0, hi=1), body])


def _number_node():
    frac = Rep(Seq([Lit("."), Rep(Chars(_DIGITS), lo=1,
                                  hi=_MAX_DIGITS)]), lo=0, hi=1)
    return Seq([_integer_node(), frac])


def _string_node(schema: dict):
    pattern = schema.get("pattern")
    if pattern is not None:
        if not isinstance(pattern, str):
            raise ConstraintError("'pattern' must be a string")
        return Seq([Lit('"'), compile_regex(pattern), Lit('"')])
    lo = int(schema.get("minLength", 0))
    hi = schema.get("maxLength")
    hi = int(hi) if hi is not None else max(_MAX_STRING, lo)
    return Seq([Lit('"'), Rep(Chars(_STR_CHARS), lo=lo, hi=hi), Lit('"')])


def _free_value_node(depth: int = _FREE_DEPTH):
    """Generic JSON value — the ``json_object`` mode grammar, built
    depth-indexed (scalars only at the bottom) so generation is
    structurally bounded: canonical caps on nesting, member count, and
    string length (docs/structured-output.md)."""
    string = Seq([Lit('"'), Rep(Chars(_STR_CHARS), hi=_FREE_STRING),
                  Lit('"')])
    scalars = [string, _number_node(), Lit("true"), Lit("false"),
               Lit("null")]
    value = Alt(scalars)
    obj = None
    for _ in range(max(1, depth)):     # ONE obj construction site
        member = Seq([string, Lit(":"), value])
        obj = Seq([Lit("{"), Rep(member, sep=Lit(","), hi=_FREE_ITEMS),
                   Lit("}")])
        arr = Seq([Lit("["), Rep(value, sep=Lit(","), hi=_FREE_ITEMS),
                   Lit("]")])
        value = Alt(scalars + [obj, arr])
    return obj  # OpenAI json_object mode: the root is an object


def compile_schema(schema) -> object:
    """JSON Schema (subset) → grammar node. Unsupported keywords raise
    :class:`ConstraintError` — silently ignoring ``minimum`` (say)
    would emit output that fails validation, the one thing this
    subsystem exists to prevent. The subset and the canonicalization
    rules are documented in docs/structured-output.md."""
    if schema is True or schema == {}:
        return _free_value_node()
    if not isinstance(schema, dict):
        raise ConstraintError(
            f"schema must be an object, got {type(schema).__name__}")
    if "anyOf" in schema:
        extra = set(schema) - _COMMON_KEYS - {"anyOf"}
        if extra:
            raise ConstraintError(
                f"keywords {sorted(extra)} unsupported next to 'anyOf'")
        opts = schema["anyOf"]
        if not isinstance(opts, list) or not opts:
            raise ConstraintError("'anyOf' must be a non-empty array")
        return Alt([compile_schema(s) for s in opts])
    if "const" in schema:
        return _json_lit(schema["const"])
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, list) or not vals:
            raise ConstraintError("'enum' must be a non-empty array")
        return Alt([_json_lit(v) for v in vals])
    t = schema.get("type")
    if isinstance(t, list):
        return Alt([compile_schema(dict(schema, type=one)) for one in t])
    if t not in _ALLOWED_KEYS:
        raise ConstraintError(
            f"unsupported schema type {t!r} (supported: "
            f"{sorted(_ALLOWED_KEYS)}, plus enum/const/anyOf)")
    extra = set(schema) - _COMMON_KEYS - _ALLOWED_KEYS[t]
    if extra:
        raise ConstraintError(
            f"unsupported keyword(s) {sorted(extra)} for type {t!r} — "
            "constrained decoding enforces the whole schema or none of "
            "it (docs/structured-output.md lists the subset)")
    if t == "string":
        return _string_node(schema)
    if t == "integer":
        return _integer_node()
    if t == "number":
        return _number_node()
    if t == "boolean":
        return Alt([Lit("true"), Lit("false")])
    if t == "null":
        return Lit("null")
    if t == "array":
        items = schema.get("items", {})
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        hi = int(hi) if hi is not None else max(_MAX_ITEMS, lo)
        item = (compile_schema(items) if items not in ({}, True)
                else _free_value_node())
        return Seq([Lit("["), Rep(item, sep=Lit(","), lo=lo, hi=hi),
                    Lit("]")])
    # object: canonical form — exactly the required properties, in
    # declaration order (a strict subset of conforming instances; see
    # module docstring)
    props = schema.get("properties", {})
    required = schema.get("required", [])
    if not isinstance(props, dict) or not isinstance(required, list):
        raise ConstraintError(
            "'properties' must be an object and 'required' an array")
    missing = [k for k in required if k not in props]
    if missing:
        raise ConstraintError(
            f"required properties {missing} have no schema in "
            "'properties'")
    ordered = [k for k in props if k in set(required)]
    parts = [Lit("{")]
    for i, key in enumerate(ordered):
        if i:
            parts.append(Lit(","))
        parts.append(Lit(json.dumps(key) + ":"))
        parts.append(compile_schema(props[key]))
    parts.append(Lit("}"))
    return Seq(parts)


def validate_instance(value, schema) -> bool:
    """Does ``value`` conform to ``schema`` (the supported subset)?
    Used by the conformance fuzz tests and the structured bench — an
    independent check of what the masks enforced, deliberately NOT
    derived from the grammar."""
    if schema is True or schema == {}:
        return True
    if "anyOf" in schema:
        return any(validate_instance(value, s) for s in schema["anyOf"])
    if "const" in schema:
        return value == schema["const"]
    if "enum" in schema:
        return value in schema["enum"]
    t = schema.get("type")
    if isinstance(t, list):
        return any(validate_instance(value, dict(schema, type=one))
                   for one in t)
    if t == "object":
        if not isinstance(value, dict):
            return False
        for key in schema.get("required", []):
            if key not in value:
                return False
        props = schema.get("properties", {})
        return all(validate_instance(v, props[k])
                   for k, v in value.items() if k in props)
    if t == "array":
        if not isinstance(value, list):
            return False
        if len(value) < int(schema.get("minItems", 0)):
            return False
        if ("maxItems" in schema
                and len(value) > int(schema["maxItems"])):
            return False
        items = schema.get("items", {})
        return all(validate_instance(v, items) for v in value)
    if t == "string":
        if not isinstance(value, str):
            return False
        if len(value) < int(schema.get("minLength", 0)):
            return False
        if ("maxLength" in schema
                and len(value) > int(schema["maxLength"])):
            return False
        if "pattern" in schema:
            import re

            return re.fullmatch(schema["pattern"], value) is not None
        return True
    if t == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if t == "number":
        return (isinstance(value, (int, float))
                and not isinstance(value, bool))
    if t == "boolean":
        return isinstance(value, bool)
    if t == "null":
        return value is None
    return False


# --- token-level automaton ------------------------------------------------


class TokenAutomaton:
    """Vocab-compiled grammar: per automaton state, an additive logit
    mask row plus a token→next-state table, built lazily on first visit
    (``ensure``). States are the char-NFA frozensets; a generation of
    L tokens visits ≤ L+1 states, so the caches grow with observed
    traffic, not with the grammar's reachable-state count.

    Shared across requests and engines — the lazy caches are guarded.
    """

    def __init__(self, root, vocab: list[str], *, eos_id: int | None,
                 kind: str = "json_schema"):
        self.root = root
        self.vocab = list(vocab)
        self.vocab_size = len(self.vocab)
        self.eos_id = eos_id
        self.kind = kind
        self.start = start_state(root)
        self._lock = threading.Lock()
        self._masks: dict = {}       # guarded-by: _lock
        self._trans: dict = {}       # guarded-by: _lock
        self._chars: dict = {}       # guarded-by: _lock
        # lifetime compile telemetry (torn float/int reads are fine for
        # monotone scrape counters — the spec_* counter convention)
        self.states_compiled = 0
        self.compile_seconds = 0.0

    # -- char-level steps (cached) --
    #
    # Read discipline: the three caches are INSERT-ONLY dicts whose
    # values are immutable once published; writers hold _lock, readers
    # use GIL-atomic lookups (a stale miss just recomputes the same
    # value). Holding the lock on the per-step mask reads would
    # serialize every engine thread against every compile.

    def _char_trans(self, state):
        trans = self._chars.get(state)  # graftlint: disable=guarded-by — insert-only cache, GIL-atomic read; miss recomputes idempotently
        if trans is None:
            trans = char_transitions(state)
            with self._lock:
                self._chars[state] = trans
        return trans

    def compiled(self, state) -> bool:
        return state in self._masks  # graftlint: disable=guarded-by — insert-only cache, GIL-atomic membership probe

    def ensure(self, state) -> None:
        """Compile ``state``'s mask row + token transitions (idempotent;
        the engine brackets cache misses with the ``grammar_compile``
        steptrace activity)."""
        if state in self._masks:  # graftlint: disable=guarded-by — benign double-check; the publish below re-checks under _lock
            return
        t0 = time.monotonic()
        mask = np.full((self.vocab_size,), NEG_INF, np.float32)
        trans: dict[int, object] = {}
        for tid, piece in enumerate(self.vocab):
            if not piece:
                continue  # unmapped/empty pieces can never advance
            st = state
            ok = True
            for ch in piece:
                st = self._char_trans(st).get(ch)
                if st is None:
                    ok = False
                    break
            if ok:
                mask[tid] = 0.0
                trans[tid] = st
        if self.eos_id is not None and is_accepting(state):
            mask[self.eos_id] = 0.0
        with self._lock:
            if state not in self._masks:
                self._masks[state] = mask
                self._trans[state] = trans
                self.states_compiled += 1
                self.compile_seconds += time.monotonic() - t0

    def mask(self, state) -> np.ndarray:
        self.ensure(state)
        return self._masks[state]  # graftlint: disable=guarded-by — published (immutable ndarray) before ensure() returns

    def step(self, state, token_id: int):
        """Next state after ``token_id``, or None (grammar-forbidden)."""
        self.ensure(state)
        return self._trans[state].get(int(token_id))  # graftlint: disable=guarded-by — published (never mutated after) before ensure() returns

    def exhausted(self, state) -> bool:
        """No character can follow: the value is complete (the engine
        finishes the stream with ``finish_reason="stop"``)."""
        return not self._char_trans(state)

    def cursor(self) -> "ConstraintState":
        return ConstraintState(self)


class ConstraintState:
    """One request's live grammar cursor. Engine-thread-only once the
    request is slotted; it rides the Request object through
    preempt-by-recompute requeues, so a resumed stream continues from
    the exact grammar position (nothing is replayed)."""

    __slots__ = ("auto", "cur", "done", "violations")

    def __init__(self, auto: TokenAutomaton):
        self.auto = auto
        self.cur = auto.start
        self.done = False
        self.violations = 0

    @property
    def vocab_size(self) -> int:
        return self.auto.vocab_size

    def needs_compile(self) -> bool:
        return not self.auto.compiled(self.cur)

    def mask_row(self) -> np.ndarray:
        return self.auto.mask(self.cur)

    def advance(self, token_id: int) -> bool:
        """Consume one emitted token; returns True when the value is
        complete (or the token was out-of-grammar — defensively treated
        as completion so the stream ends instead of derailing; the mask
        makes this unreachable on the engine's own sampling paths)."""
        if self.done:
            return True
        nxt = self.auto.step(self.cur, token_id)
        if nxt is None:
            self.violations += 1
            self.done = True
            return True
        self.cur = nxt
        if self.auto.exhausted(nxt):
            self.done = True
        return self.done


# --- request-surface compilation -----------------------------------------


def vocab_strings(tokenizer, vocab_size: int) -> list[str]:
    """Per-id decoded pieces for the token automaton. Pieces that don't
    round-trip to clean text (byte-fragment ids in byte-level BPEs
    decode to U+FFFD) become '' — never maskable-in, which is correct:
    a grammar over characters cannot vouch for half a codepoint."""
    out = []
    for tid in range(vocab_size):
        try:
            piece = tokenizer.decode([tid])
        except Exception:  # noqa: BLE001 — unmapped id in a toy vocab
            piece = ""
        if not isinstance(piece, str) or "�" in piece:
            piece = ""
        out.append(piece)
    return out


def _tool_schema(tools, tool_choice):
    """The grammar schema for a forced tool call: the OpenAI tool-call
    value ``{"name": <fn>, "arguments": {…}}`` with arguments from the
    function's declared parameters. ``tool_choice="required"`` admits
    any declared tool (alternation)."""
    by_name = {}
    for t in tools or []:
        fn = (t or {}).get("function") or {}
        name = fn.get("name")
        if not isinstance(name, str) or not name:
            raise ConstraintError("every tool needs function.name")
        by_name[name] = fn.get("parameters") or {"type": "object"}

    def call_schema(name):
        return {"type": "object",
                "properties": {"name": {"const": name},
                               "arguments": by_name[name]},
                "required": ["name", "arguments"]}

    if isinstance(tool_choice, dict):
        name = ((tool_choice.get("function") or {}).get("name"))
        if name not in by_name:
            raise ConstraintError(
                f"tool_choice names unknown function {name!r}")
        return call_schema(name)
    if not by_name:
        raise ConstraintError("tool_choice='required' with no tools")
    if len(by_name) == 1:
        return call_schema(next(iter(by_name)))
    return {"anyOf": [call_schema(n) for n in by_name]}


def compile_request_constraint(*, response_format=None, tools=None,
                               tool_choice=None, vocab: list[str],
                               eos_id: int | None) -> TokenAutomaton | None:
    """The API-layer entry: OpenAI structured-output request fields →
    a compiled :class:`TokenAutomaton` (or None when the request is
    unconstrained). Raises :class:`ConstraintError` on invalid or
    unsupported specs (HTTP 422)."""
    kind = None
    schema = None
    if tool_choice not in (None, "auto", "none"):
        kind = "tool_call"
        schema = _tool_schema(tools, tool_choice)
    elif isinstance(response_format, dict):
        rf_type = response_format.get("type")
        if rf_type == "json_object":
            kind = "json_object"
        elif rf_type == "json_schema":
            kind = "json_schema"
            wrapper = response_format.get("json_schema")
            if not isinstance(wrapper, dict):
                raise ConstraintError(
                    "response_format.json_schema must be an object")
            schema = wrapper.get("schema")
            if not isinstance(schema, dict):
                raise ConstraintError(
                    "response_format.json_schema.schema must be an "
                    "object")
        elif rf_type not in (None, "text"):
            raise ConstraintError(
                f"unsupported response_format.type {rf_type!r}")
    if kind is None:
        return None
    root = compile_schema(schema) if schema is not None else (
        _free_value_node())
    return TokenAutomaton(root, vocab, eos_id=eos_id, kind=kind)


class ConstraintCompiler:
    """Per-server compile cache: (engine vocab, canonical spec) →
    shared :class:`TokenAutomaton`. HTTP handler threads compile
    concurrently; the cache keeps repeat structured requests (the
    agent-loop shape: same schema, every turn) at dict-lookup cost.

    LRU-BOUNDED: keys are raw client-supplied schema JSON, so an
    adversarial (or merely varied — a changing ``const`` per request)
    client would otherwise grow the cache, and every automaton's
    vocab-width mask rows, without limit. Eviction only drops the
    SHARED cache entry — automatons still referenced by in-flight
    request cursors stay alive until those requests finish."""

    def __init__(self, max_entries: int = 256):
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._cache: dict = {}       # guarded-by: _lock (insertion-ordered: LRU)
        self.compiles = 0            # guarded-by: _lock
        self.compile_seconds = 0.0   # guarded-by: _lock

    def get(self, *, response_format=None, tools=None, tool_choice=None,
            vocab, vocab_key, eos_id):
        key = (vocab_key, eos_id, json.dumps(
            {"rf": response_format, "tools": tools, "tc": tool_choice},
            sort_keys=True, default=str))
        with self._lock:
            if key in self._cache:
                auto = self._cache.pop(key)   # re-insert = mark recent
                self._cache[key] = auto
                return auto
        t0 = time.monotonic()
        auto = compile_request_constraint(
            response_format=response_format, tools=tools,
            tool_choice=tool_choice, vocab=vocab, eos_id=eos_id)
        dt = time.monotonic() - t0
        with self._lock:
            self.compiles += 1
            self.compile_seconds += dt
            self._cache[key] = auto
            while len(self._cache) > self.max_entries:
                self._cache.pop(next(iter(self._cache)))
        return auto

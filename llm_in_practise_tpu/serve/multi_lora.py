"""Batched multi-LoRA serving — one base model, thousands of tenants
(ROADMAP item 5 / ISSUE 15).

``serve/adapters.py`` served each adapter as a whole merged-weight
engine: N adapters paid N full copies of the base model in HBM plus N
jit caches, and slots could never batch across tenants. This module is
the punica-style answer (gathered BGMV — arxiv 2310.18547's batched
``y += x @ A[idx] @ B[idx]`` idiom): the low-rank factors of every
loaded adapter live in shared, rank-bucketed HBM banks, a per-slot
``adapter_index`` array rides the dispatch plan, and twin "adapted"
engine programs (the ISSUE 12 masked-twin idiom) gather each slot's
A/B factors inside the jitted step and add the delta on the LoRA
target matmuls. Slots running DIFFERENT adapters — and adapter-none
slots, whose index selects the all-zeros row 0 — share one dispatch at
the pinned 1 dispatch/step on both KV layouts.

Three pieces:

- :func:`lora_context` / :func:`current_lora` — a thread-local stack
  carrying the gathered-BGMV dispatch pytree. The engine's adapter
  twin programs push it INSIDE the jitted function (the factors enter
  as traced jit arguments, never baked constants), and the facade's
  interceptor reads it per Dense call.
- :class:`LoRAServingModel` — the model facade
  (:class:`~llm_in_practise_tpu.parallel.collectives.TPQuantizedCollectives`
  idiom): ``apply`` delegates untouched when no context is set (base
  programs stay byte-identical executables) and runs under the
  gathered-BGMV method interceptor when one is.
- :class:`AdapterRegistry` — hot-load/evict lifecycle over the banks:
  rank-bucketed capacity with power-of-two growth (bounded retraces),
  refcounted rows with LRU evict-under-pressure against a byte budget
  (the kv-pool ``max_bytes`` convention), per-adapter namespace
  generations for prefix-cache isolation, and swap/eviction/tenant
  counters for /metrics.

``AdapterHandle`` at the bottom keeps the old engine-per-adapter
surface (``serve/api.py``'s ``adapters=`` dict) working over ONE
shared engine.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import re
import threading
import time
from contextlib import contextmanager

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from llm_in_practise_tpu.obs.hbm import get_ledger
from llm_in_practise_tpu.obs.logging import get_logger
from llm_in_practise_tpu.peft.lora import LoRAConfig, stack_lora_tree

_BLOCK_RE = re.compile(r"block_(\d+)/(.*)")

# ---------------------------------------------------------------------------
# thread-local lora context
# ---------------------------------------------------------------------------

_TLS = threading.local()


@contextmanager
def lora_context(lora):
    """Push a gathered-BGMV dispatch pytree for the current thread.

    The engine's adapter twin programs enter this INSIDE the jitted
    wrapper, so while the program traces, ``current_lora()`` returns
    TRACERS of the bank arrays — the compiled executable takes them as
    arguments and one program serves every adapter population."""
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append(lora)
    try:
        yield
    finally:
        stack.pop()


def current_lora():
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


def lora_wrap(fn):
    """Twin-program wrapper: same body, plus a KW-ONLY ``lora`` pytree
    argument pushed as the thread-local context inside the traced
    function. Keyword-only keeps every positional ``donate_argnums``
    index of the wrapped program valid, and jit's laziness means a twin
    that never runs never compiles (the masked-twin economics)."""

    def wrapped(*args, lora, **kwargs):
        with lora_context(lora):
            return fn(*args, **kwargs)

    return wrapped


# ---------------------------------------------------------------------------
# the gathered-BGMV interceptor + model facade
# ---------------------------------------------------------------------------


def _gathered_delta(lora, key, x):
    """Summed low-rank delta for Dense ``key`` over the batch:
    ``((x @ A[idx]) @ B[idx]) * scale[idx]`` per rank bucket, f32
    compute (the two rank-r einsums are tiny next to the base matmul).
    Returns None when no loaded bucket carries this target."""
    m = _BLOCK_RE.match(key)
    delta = None
    for rb, bank in lora["banks"].items():
        idx = lora["idx"][rb]
        fac = layer = None
        if m is not None:
            fac = bank["stacked"].get("blocks/block/" + m.group(2))
            layer = int(m.group(1))
        if fac is None:
            fac = bank["flat"].get(key)
            layer = None
        if fac is None:
            continue
        if layer is not None:
            ga = fac["a"][idx, layer]     # (B, d_in, rb)
            gb = fac["b"][idx, layer]     # (B, rb, d_out)
        else:
            ga = fac["a"][idx]
            gb = fac["b"][idx]
        t = jnp.einsum("b...d,bdr->b...r", x.astype(jnp.float32), ga)
        d = jnp.einsum("b...r,bro->b...o", t, gb)
        scale = bank["scale"][idx].reshape((-1,) + (1,) * (d.ndim - 1))
        d = d * scale
        delta = d if delta is None else delta + d
    return delta


def _lora_interceptor(next_fn, call_args, call_kwargs, context):
    """Flax method interceptor adding the gathered low-rank delta AFTER
    the unmodified base Dense call (the base math — including any
    packed-quantized or TP-collective interception stacked beneath —
    is untouched; adapter-none rows gather the all-zeros row 0, so
    their delta is exactly 0.0 and the output bit-identical)."""
    lora = current_lora()
    mod = context.module
    if (lora is None or not isinstance(mod, nn.Dense)
            or context.method_name != "__call__"):
        return next_fn(*call_args, **call_kwargs)
    y = next_fn(*call_args, **call_kwargs)
    key = "/".join(mod.path) + "/kernel"
    delta = _gathered_delta(lora, key, call_args[0])
    if delta is None:
        return y
    return y + delta.reshape(y.shape).astype(y.dtype)


class LoRAServingModel:
    """Model facade (the ``TPQuantizedCollectives`` idiom) routing every
    engine program through the gathered-BGMV interceptor WHEN a lora
    context is set — and delegating untouched when none is, so the base
    (non-twin) programs trace the exact pre-LoRA computation.

    Wraps any serving model object, including an already-wrapped
    ``TPQuantizedCollectives`` (the interceptors nest; the base matmul
    path beneath stays whatever it was). ``inner`` exposes the wrapped
    model for identity checks (the engine's quantized-collective
    isinstance probe must see through this facade)."""

    def __init__(self, model):
        self.inner = model

    @property
    def config(self):
        return self.inner.config

    @property
    def cache_slot_axis(self) -> int:
        return getattr(self.inner, "cache_slot_axis", 0)

    def init_cache(self, *args, **kwargs):
        return self.inner.init_cache(*args, **kwargs)

    def apply(self, variables, *args, **kwargs):
        if current_lora() is None:
            return self.inner.apply(variables, *args, **kwargs)
        with nn.intercept_methods(_lora_interceptor):
            return self.inner.apply(variables, *args, **kwargs)

    def __getattr__(self, item):
        # dataclass-style passthrough for everything else the serving
        # stack duck-types off the model (paged_kv geometry, cost-model
        # config reads, draft compat checks, ...)
        return getattr(self.inner, item)


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _AdapterRec:
    name: str
    rb: int                     # rank bucket
    row: int                    # bank row
    ns: int                     # prefix-namespace generation (monotone)
    n_bytes: int                # f32 payload bytes at padded rank
    refcount: int = 0
    last_used: float = 0.0
    source: str | None = None


class _RankBucket:
    """One rank bucket's stacked banks. Row 0 is RESERVED all-zeros —
    the "no adapter" row every idle/base slot's index selects, making
    the adapted programs' base rows bit-identical by construction."""

    def __init__(self, rb: int):
        self.rb = rb
        self.cap = 2                       # row 0 (zeros) + 1
        self.free: list[int] = [1]
        self.stacked: dict[str, dict] = {}   # key -> {"a","b"} jnp banks
        self.flat: dict[str, dict] = {}
        self.scale = jnp.zeros((self.cap,), jnp.float32)

    def banks(self) -> dict:
        return {"stacked": self.stacked, "flat": self.flat,
                "scale": self.scale}

    def grow(self) -> None:
        """Double capacity (power-of-two ladder → bounded retraces of
        the adapter twins, the prefill-bucket compile policy)."""
        new_cap = self.cap * 2
        pad = new_cap - self.cap

        def wide(bank):
            return {k: jnp.concatenate(
                [v, jnp.zeros((pad,) + v.shape[1:], v.dtype)], axis=0)
                for k, v in bank.items()}

        self.stacked = {k: wide(v) for k, v in self.stacked.items()}
        self.flat = {k: wide(v) for k, v in self.flat.items()}
        self.scale = jnp.concatenate(
            [self.scale, jnp.zeros((pad,), jnp.float32)])
        self.free.extend(range(self.cap, new_cap))
        self.cap = new_cap

    def ensure_target(self, key: str, a_shape, b_shape,
                      stacked: bool) -> None:
        """Union-of-targets banks: an adapter bringing a target key the
        bucket hasn't seen allocates zero rows for every existing
        adapter (their delta through it stays exactly 0). One bounded
        retrace per new key — the pytree structure changed."""
        table = self.stacked if stacked else self.flat
        if key in table:
            return
        table[key] = {
            "a": jnp.zeros((self.cap,) + tuple(a_shape), jnp.float32),
            "b": jnp.zeros((self.cap,) + tuple(b_shape), jnp.float32),
        }

    def zero_row(self, row: int) -> None:
        for table in (self.stacked, self.flat):
            for key, fac in table.items():
                table[key] = {
                    "a": fac["a"].at[row].set(0.0),
                    "b": fac["b"].at[row].set(0.0),
                }
        self.scale = self.scale.at[row].set(0.0)


def load_adapter_tree(adapter_path: str):
    """Restore one ``adapter.msgpack`` + sidecar checkpoint
    (``ckpt.save_named`` layout, same path handling as
    ``serve.adapters.load_adapter``) WITHOUT merging: returns
    ``(lora_params, LoRAConfig)`` for bank stacking."""
    from llm_in_practise_tpu.ckpt import checkpoint as ckpt_lib

    if os.path.isdir(adapter_path):
        adapter_path = os.path.join(adapter_path, "adapter.msgpack")
    lora_params, meta = ckpt_lib.restore_checkpoint(adapter_path)
    if "lora_config" not in meta:
        raise ValueError(
            f"{adapter_path} has no lora_config metadata sidecar")
    return lora_params, LoRAConfig.from_dict(meta["lora_config"])


class AdapterRegistry:
    """Rank-bucketed stacked A/B factor banks + adapter lifecycle.

    Loading stacks an adapter's per-layer factors
    (:func:`~llm_in_practise_tpu.peft.lora.stack_lora_tree`) into one
    bank row per rank bucket — rank padded with zero columns to the
    bucket's power-of-two rank, which leaves the delta bit-unchanged.
    Requests ``acquire``/``release`` refcounts; eviction under the byte
    budget (``max_bytes``, the kv-pool convention — adapter payload
    bytes count against the same operator HBM ledger the tiered pool
    budgets) only ever takes refcount-0 rows, LRU first.

    Every (re-)register mints a fresh ``ns`` generation from a global
    monotone counter: the engine keys its prefix caches by
    ``token + (ns << 32)`` (length-preserving, injective), so tenants
    never hit each other's KV and a hot-swapped adapter name never hits
    its own stale KV. ``ns`` 0 is the base model's identity namespace.

    Thread-safe: HTTP threads register/acquire while the engine thread
    gathers dispatch args.
    """

    def __init__(self, base_params, *, max_bytes: int | None = None,
                 mesh=None, axis: str = "model"):
        blocks = [int(m.group(1)) for k in (base_params or {})
                  for m in (re.fullmatch(r"block_(\d+)", str(k)),) if m]
        self.n_layer = max(blocks) + 1 if blocks else 0
        self.max_bytes = max_bytes
        self.mesh = mesh
        self.axis = axis
        self._lock = threading.Lock()
        self._adapters: dict[str, _AdapterRec] = {}  # guarded-by: _lock
        self._buckets: dict[int, _RankBucket] = {}   # guarded-by: _lock
        self.bytes_loaded = 0                        # guarded-by: _lock
        # lifetime counters for /metrics (scrape threads read these as
        # monotone floats/ints; all writes under the lock)
        self.loads_total = 0                         # guarded-by: _lock
        self.evictions_total = 0                     # guarded-by: _lock
        self.swap_seconds_total = 0.0                # guarded-by: _lock
        self.tenant_tokens: dict[str, int] = {}      # guarded-by: _lock
        self._ns = itertools.count(1)
        self._log = get_logger("serve.multi_lora")

    # -- loading / eviction ------------------------------------------------

    def register(self, name: str, adapter_path: str) -> None:
        """Hot-load one adapter checkpoint under ``name``."""
        lora_params, cfg = load_adapter_tree(adapter_path)
        self.register_tree(name, lora_params, cfg, source=adapter_path)

    def register_tree(self, name: str, lora_params: dict,
                      cfg: LoRAConfig, source: str | None = None) -> None:
        """Stack a restored LoRA tree into the banks (tests and benches
        hand trees directly; :meth:`register` is the checkpoint path)."""
        t0 = time.monotonic()
        tree = (stack_lora_tree(lora_params, self.n_layer)
                if self.n_layer else dict(lora_params))
        rb = 1 << max(int(cfg.r) - 1, 0).bit_length()
        # f32 payload at the PADDED rank — what the bank row really costs
        n_bytes = 4 * sum(
            int(np.prod(ab["a"].shape)) // ab["a"].shape[-1] * rb
            + int(np.prod(ab["b"].shape)) // ab["b"].shape[-2] * rb
            for ab in tree.values())
        with self._lock:
            old = self._adapters.get(name)
            if old is not None:
                if old.refcount > 0:
                    raise RuntimeError(
                        f"adapter {name!r} is busy ({old.refcount} "
                        "in-flight requests); drain before hot-swapping")
                self._evict_locked(old)
            self._reserve_bytes_locked(name, n_bytes)
            bucket = self._buckets.get(rb)
            if bucket is None:
                bucket = self._buckets[rb] = _RankBucket(rb)
            row = self._take_row_locked(bucket)
            for key, ab in tree.items():
                # control-plane load path (register/hot-swap), not the
                # engine step: blocking on the checkpoint's arrays here
                # is the designed swap cost (llm_adapter_swap_seconds)
                a = np.asarray(ab["a"], np.float32)  # graftlint: disable=host-sync
                b = np.asarray(ab["b"], np.float32)  # graftlint: disable=host-sync
                r = a.shape[-1]
                if r > rb:                   # cannot happen (rb = ceil pow2)
                    raise ValueError(f"rank {r} exceeds bucket {rb}")
                a = np.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, rb - r)])
                b = np.pad(b, [(0, 0)] * (b.ndim - 2)
                           + [(0, rb - r), (0, 0)])
                stacked = key.startswith("blocks/block/")
                bucket.ensure_target(key, a.shape, b.shape, stacked)
                table = bucket.stacked if stacked else bucket.flat
                fac = table[key]
                table[key] = {
                    "a": self._place(fac["a"].at[row].set(a), key,
                                     part="a"),
                    "b": self._place(fac["b"].at[row].set(b), key,
                                     part="b"),
                }
            bucket.scale = bucket.scale.at[row].set(float(cfg.scaling))
            self._adapters[name] = _AdapterRec(
                name=name, rb=rb, row=row, ns=next(self._ns),
                n_bytes=n_bytes, last_used=time.monotonic(),
                source=source)
            self.bytes_loaded += n_bytes
            self.loads_total += 1
            self.swap_seconds_total += time.monotonic() - t0
            # HBM ledger: payload bytes under the rank bucket's account
            # (adapters/r<b>); the pow2 bank-capacity padding beyond
            # the payload shows up in the reconciliation residual, not
            # here — docs/observability.md "Memory plane"
            get_ledger().book(f"adapters/r{rb}", n_bytes)

    def _place(self, arr, key: str, *, part: str):
        """TP placement: factor banks shard with the BASE weight's rule
        (docs/serving-tp.md). Row-parallel targets shard the contraction
        dim — A's ``d_in`` — over the model axis; column-parallel
        targets shard the output dim — B's ``d_out``. Replicated
        whenever the mesh is absent or the dim doesn't divide (always
        correct; sharding is a memory/bandwidth choice)."""
        if self.mesh is None:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P

        from llm_in_practise_tpu.parallel.collectives import (
            ROW_PARALLEL_TARGETS,
        )

        tp = int(self.mesh.shape.get(self.axis, 1))
        row_parallel = any(t in key for t in ROW_PARALLEL_TARGETS)
        spec = [None] * arr.ndim
        if tp > 1:
            if part == "a" and row_parallel and arr.shape[-2] % tp == 0:
                spec[-2] = self.axis            # d_in
            elif (part == "b" and not row_parallel
                  and arr.shape[-1] % tp == 0):
                spec[-1] = self.axis            # d_out
        return jax.device_put(arr, NamedSharding(self.mesh, P(*spec)))

    def _take_row_locked(self, bucket: _RankBucket) -> int:
        if not bucket.free:
            bucket.grow()
        row = bucket.free.pop()
        # recycled rows hold the previous tenant's factors until the new
        # writes land — zero EVERY target so an adapter that doesn't
        # carry some bank key can't inherit stale deltas through it
        bucket.zero_row(row)
        return row

    def _reserve_bytes_locked(self, name: str, n_bytes: int) -> None:
        if self.max_bytes is None:
            return
        while self.bytes_loaded + n_bytes > self.max_bytes:
            victim = min(
                (r for r in self._adapters.values() if r.refcount == 0),
                key=lambda r: r.last_used, default=None)
            if victim is None:
                raise RuntimeError(
                    f"adapter byte budget exhausted loading {name!r}: "
                    f"{self.bytes_loaded + n_bytes} > {self.max_bytes} "
                    "and every loaded adapter has in-flight requests")
            self._log.info("evicting adapter %s under byte pressure "
                           "(%d bytes)", victim.name, victim.n_bytes)
            self._evict_locked(victim)
            self.evictions_total += 1
            get_ledger().note_reclaim(f"adapters/r{victim.rb}", "budget")

    def _evict_locked(self, rec: _AdapterRec) -> None:
        """Free ``rec``'s bank row (zeroed on reuse, not here — the
        engine thread may still hold last step's bank arrays, which are
        immutable snapshots) and drop its bytes from the ledger."""
        self._adapters.pop(rec.name, None)
        self._buckets[rec.rb].free.append(rec.row)
        self.bytes_loaded -= rec.n_bytes
        get_ledger().book(f"adapters/r{rec.rb}", -rec.n_bytes)

    def evict(self, name: str) -> bool:
        """Explicit unload; refuses while requests are in flight."""
        with self._lock:
            rec = self._adapters.get(name)
            if rec is None:
                return False
            if rec.refcount > 0:
                raise RuntimeError(
                    f"adapter {name!r} has {rec.refcount} in-flight "
                    "requests")
            self._evict_locked(rec)
            self.evictions_total += 1
            return True

    # -- request lifecycle -------------------------------------------------

    def acquire(self, name: str) -> None:
        with self._lock:
            rec = self._adapters.get(name)
            if rec is None:
                raise KeyError(name)
            rec.refcount += 1
            rec.last_used = time.monotonic()

    def release(self, name: str) -> None:
        with self._lock:
            rec = self._adapters.get(name)
            if rec is not None and rec.refcount > 0:
                rec.refcount -= 1

    def note_tokens(self, name: str, n: int) -> None:
        """Book ``n`` generated tokens to tenant ``name``
        (llm_tenant_tokens_total{adapter=…})."""
        if n <= 0:
            return
        with self._lock:
            self.tenant_tokens[name] = self.tenant_tokens.get(name, 0) + n

    def ns_of(self, name: str | None) -> int:
        """Prefix-namespace generation for ``name`` (0 = base)."""
        if name is None:
            return 0
        with self._lock:
            rec = self._adapters.get(name)
            return rec.ns if rec is not None else 0

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._adapters)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._adapters

    # -- dispatch ----------------------------------------------------------

    def dispatch_args(self, adapters: list[str | None]):
        """The gathered-BGMV jit-argument pytree for one dispatch whose
        batch rows run ``adapters`` (None = base → row 0), or None when
        every row is base — the caller then runs the base program and
        the twin never traces. Banks are IMMUTABLE snapshots (functional
        ``.at`` updates), so the engine thread may keep using a returned
        pytree across a concurrent register/evict."""
        with self._lock:
            recs = [self._adapters.get(a) if a is not None else None
                    for a in adapters]
            if all(r is None for r in recs):
                return None
            idx = {}
            banks = {}
            for rb, bucket in sorted(self._buckets.items()):
                rows = np.zeros((len(adapters),), np.int32)
                for i, rec in enumerate(recs):
                    if rec is not None and rec.rb == rb:
                        rows[i] = rec.row
                idx[rb] = jnp.asarray(rows)
                banks[rb] = bucket.banks()
            return {"idx": idx, "banks": banks}

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        """Point-in-time snapshot for /metrics and /debug views."""
        with self._lock:
            return {
                "loaded": len(self._adapters),
                "bytes_loaded": self.bytes_loaded,
                "max_bytes": self.max_bytes,
                "loads_total": self.loads_total,
                "evictions_total": self.evictions_total,
                "swap_seconds_total": self.swap_seconds_total,
                "tenant_tokens": dict(self.tenant_tokens),
                "refcounts": {n: r.refcount
                              for n, r in self._adapters.items()},
                "buckets": {rb: {"cap": b.cap, "free": len(b.free)}
                            for rb, b in self._buckets.items()},
            }


# ---------------------------------------------------------------------------
# the engine-per-adapter compatibility surface
# ---------------------------------------------------------------------------


class AdapterHandle:
    """Engine-shaped view of ONE adapter on a SHARED engine — what
    ``serve/api.py``'s ``adapters=`` dict holds now that
    ``build_adapter_engines`` stopped building engines. ``submit``
    injects the adapter name; everything else proxies to the shared
    engine (stats, debug views, model/params reads, lifecycle)."""

    def __init__(self, engine, name: str):
        self._engine = engine
        self.adapter_name = name

    def submit(self, prompt_ids, params=None, **kw):
        kw.setdefault("adapter", self.adapter_name)
        return self._engine.submit(prompt_ids, params, **kw)

    def start(self):
        # the shared engine's loop may already run (engine.start is NOT
        # idempotent — two loops would race the slot tables)
        eng = self._engine
        if eng._thread is None or not eng._thread.is_alive():
            eng.start()

    def __getattr__(self, item):
        return getattr(self._engine, item)

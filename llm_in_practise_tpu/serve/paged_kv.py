"""Paged KV cache — block-table page pool with refcounted sharing.

The slot engine's original KV layout gives every slot a contiguous
``cache_len``-row region of one ``(max_slots, cache_len, …)`` buffer per
layer, so concurrency is capped by WORST-CASE context reservation: a
16-token prompt generating 32 tokens pins the same HBM as an 8K-context
request. vLLM's PagedAttention (the reference platform's serving core)
breaks that bond: KV lives in fixed-size **pages** carved from one
preallocated pool, and each request maps logical positions to physical
pages through a **block table** — admission reserves the pages a request
actually needs, decode allocates one page at a time as the context
grows, and a shared prompt prefix is the SAME physical pages refcounted
across requests (copy-on-write: a would-be write to a shared page forks
it first).

TPU twist — XLA-static shapes, no custom kernel: the jitted engine
programs cannot take a different shape per step, and the in-tree model
families all consume a contiguous ``(slots, width, …)`` cache. So the
paged programs keep the pool as ONE flat token-major buffer per layer
(``(num_pages * page_size, heads, dim)``), take host-computed
**gather/scatter index arrays as ordinary inputs** (same shapes every
step → no retrace), and inside one dispatch:

1. gather each slot's pages into a transient contiguous view whose
   width is bucketed (power-of-two up to ``cache_len`` — one compile
   per bucket, same trick as prefill buckets);
2. run the UNCHANGED engine program body (``_decode_fn``,
   ``decode_scan``, ``batched_chunk``, the fused mixed step) against
   that view — the math is literally the contiguous code path, which is
   how golden-token parity with ``kv_layout="contiguous"`` is pinned;
3. scatter only the freshly written rows back to their pages; discarded
   writes (idle rows' dead windows, padding) are routed to a reserved
   **trash page** (physical page 0) by the host-built scatter indices,
   replacing the contiguous path's clamp-and-overwrite gymnastics.

The transient view is freed by XLA between dispatches; its width tracks
the longest LIVE context (not ``cache_len``), so the persistent KV
footprint is the pool — sized to expected live tokens, not
``max_slots × cache_len``. That is where the concurrency headroom comes
from (see docs/paged-kv.md for the admission math and the workspace
caveat; a fused paged-attention Pallas kernel that reads pages in place
is the follow-up that removes the gather entirely).

Sharing/refcount protocol (one invariant the churn test pins): a
physical page's refcount equals the number of slot block tables mapping
it, plus one if the :class:`~.prefix_cache.PagedPrefixIndex` holds it.
Pages are freed when the count returns to zero — never while any reader
remains.
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

from llm_in_practise_tpu.obs.hbm import get_ledger

#: physical page 0 is never allocated: host-built scatter indices route
#: every discarded write (idle rows, padding beyond a row's valid
#: window) into it, and unmapped logical pages gather from it (those
#: positions sit beyond the row's cache index, so the causal mask keeps
#: them unattended).
TRASH_PAGE = 0


def pages_for(n_tokens: int, page_size: int) -> int:
    """Pages needed to hold ``n_tokens`` KV rows (0 tokens -> 0 pages)."""
    return -(-int(n_tokens) // int(page_size))


def kv_row_bytes(model, dtype) -> int:
    """HBM bytes one KV-cache ROW (one token position, all layers)
    costs for ``model`` — the exchange rate the engine uses to express
    a draft model's contiguous cache in page-pool tokens, so a paged
    engine with a draft can't over-admit against bytes the draft
    already spent (ISSUE 9 satellite; docs/paged-kv.md)."""
    probe = 16
    tpl = model.init_cache(1, probe, dtype=dtype)
    total = 0
    for layer in tpl:
        for key, buf in layer.items():
            if key == "index":
                continue
            total += (buf.size // probe) * buf.dtype.itemsize
    return total


class PagePoolExhausted(RuntimeError):
    """Allocation failed with no reclaimable pages left."""


class PagePool:
    """Host-side accountant of the physical page pool: free list,
    per-page refcounts, and the alloc/share/release protocol.

    Purely bookkeeping — the actual KV bytes live in
    :class:`PagedKV`'s device buffers; this class decides which pages a
    request may write. Engine-thread writes, scrape-thread reads: the
    mutating ops and the stats properties share ``_lock``.

    ``reclaim`` (optional callable ``(n_pages) -> int``) is asked to
    free at least ``n_pages`` when the free list runs dry — the engine
    wires the shared-prefix index's LRU eviction here, so cold shared
    prefixes are reclaimed before admission fails.
    """

    def __init__(self, num_pages: int, page_size: int, *, reclaim=None):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved trash "
                f"page), got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        self.reclaim = reclaim
        self._lock = threading.Lock()
        # refcount per physical page; page 0 pinned forever as trash
        self._refs = np.zeros((num_pages,), np.int32)  # guarded-by: _lock
        self._refs[TRASH_PAGE] = 1
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # guarded-by: _lock
        self.allocs = 0          # guarded-by: _lock
        self.frees = 0           # guarded-by: _lock
        self.alloc_failures = 0  # guarded-by: _lock

    # -- capacity / stats -----------------------------------------------------

    @property
    def capacity(self) -> int:
        """Allocatable pages (the pool minus the trash page)."""
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.capacity - self.free_pages

    @property
    def shared_pages(self) -> int:
        """Pages mapped by more than one reader (refcount > 1)."""
        with self._lock:
            return int(np.sum(self._refs[1:] > 1))

    def refcount(self, page: int) -> int:
        with self._lock:
            return int(self._refs[page])

    def refcount_histogram(self) -> dict[int, int]:
        """{refcount: page count} over allocated pages (trash excluded)."""
        with self._lock:
            refs = self._refs[1:]
            live = refs[refs > 0]
            counts: dict[int, int] = {}
            for r in live:
                counts[int(r)] = counts.get(int(r), 0) + 1
            return counts

    def snapshot(self) -> dict:
        """Every occupancy/sharing/churn figure under ONE lock hold.

        The per-field properties above each take the lock separately —
        fine for a single gauge, but a multi-field report stitched from
        them can tear (a release between ``used_pages`` and
        ``shared_pages`` makes the sums disagree). ``/debug/kv`` and
        the ledger cross-check read through here so their page math is
        internally consistent by construction."""
        with self._lock:
            refs = self._refs[1:]
            live = refs[refs > 0]
            hist: dict[int, int] = {}
            for r in live:
                hist[int(r)] = hist.get(int(r), 0) + 1
            free = len(self._free)
            return {
                "capacity": self.num_pages - 1,
                "free_pages": free,
                "used_pages": self.num_pages - 1 - free,
                "shared_pages": int(np.sum(refs > 1)),
                "refcount_histogram": hist,
                "allocs": self.allocs,
                "frees": self.frees,
                "alloc_failures": self.alloc_failures,
            }

    # -- alloc / share / release ----------------------------------------------

    def try_alloc(self, n: int) -> list[int] | None:
        """``n`` fresh pages (refcount 1 each), or ``None`` when even the
        ``reclaim`` hook cannot free enough. Never raises — admission
        turns ``None`` into preemption/shed policy."""
        if n <= 0:
            return []
        with self._lock:
            short = n - len(self._free)
        if short > 0 and self.reclaim is not None:
            # outside the lock: reclaim re-enters through free()
            self.reclaim(short)
        with self._lock:
            if len(self._free) < n:
                self.alloc_failures += 1
                return None
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._refs[p] = 1
            self.allocs += n
            return pages

    def alloc(self, n: int) -> list[int]:
        """Like :meth:`try_alloc` but raises :class:`PagePoolExhausted`."""
        pages = self.try_alloc(n)
        if pages is None:
            raise PagePoolExhausted(
                f"page pool exhausted: need {n} pages, "
                f"{self.free_pages} free of {self.capacity}")
        return pages

    def share(self, pages) -> None:
        """One more reader for each page (prefix sharing / index pin)."""
        with self._lock:
            for p in pages:
                if p == TRASH_PAGE:
                    continue
                if self._refs[p] <= 0:
                    raise ValueError(f"share of unallocated page {p}")
                self._refs[p] += 1

    def release(self, pages) -> None:
        """One fewer reader; pages hitting refcount 0 return to the
        free list."""
        with self._lock:
            for p in pages:
                if p == TRASH_PAGE:
                    continue
                r = int(self._refs[p]) - 1
                if r < 0:
                    raise ValueError(f"release of free page {p}")
                self._refs[p] = r
                if r == 0:
                    self._free.append(p)
                    self.frees += 1

    def check_leaks(self, expected_held: int = 0) -> None:
        """Assert the pool accounting is consistent: the total of all
        outstanding refs (trash page excluded) equals ``expected_held``,
        and with zero holders every page is back on the free list.
        The churn test calls this after N admit/finish/shed cycles."""
        with self._lock:
            held = int(np.sum(self._refs[1:]))
            free = len(self._free)
        if held != expected_held:
            raise AssertionError(
                f"page refcount leak: {held} refs outstanding, "
                f"expected {expected_held}")
        if expected_held == 0 and free != self.capacity:
            raise AssertionError(
                f"page leak: {self.capacity - free} pages neither free "
                "nor referenced")


@dataclasses.dataclass
class PagedHit:
    """A paged-admission prefix hit.

    ``pages`` — physical pages already holding the prefix KV (share
    refs were taken by the index lookup; the engine maps them into the
    slot's block table). ``entry`` — a row-based entry instead (kv-pool
    tier or a claimed handoff), to be page-scattered at admission.
    Exactly one of the two is set. ``last_logits`` rides along for
    full-length entries (the direct-insert path samples from it)."""

    length: int
    pages: list[int] | None = None
    entry: object | None = None
    last_logits: object | None = None
    # True for a consume-once handoff claim (``Request.kv_entry``): a
    # dry-pool requeue must stash it BACK on the request — tier hits
    # are re-lookup-able, a dropped claim is a guaranteed local prefill
    external: bool = False


class PagedKV:
    """Device-side paged KV state for one engine: per-layer flat pools
    + per-slot block tables + the host-side index-array builders the
    jitted paged programs consume.

    Only the unrolled cache layout (slot axis 0) is supported — the
    stacked scan layout keeps ``kv_layout="contiguous"`` (see
    docs/paged-kv.md, "Limitations").
    """

    def __init__(self, model, *, max_slots: int, cache_len: int,
                 page_size: int, pool_tokens: int, dtype,
                 mesh=None):
        import jax
        import jax.numpy as jnp

        if int(getattr(model, "cache_slot_axis", 0)) != 0:
            raise ValueError(
                "kv_layout='paged' supports the unrolled cache layout "
                "only (cache_slot_axis == 0); scan-layers engines must "
                "use kv_layout='contiguous'")
        self.page_size = int(page_size)
        self.cache_len = int(cache_len)
        self.max_slots = int(max_slots)
        # logical pages a single slot can ever map
        self.pages_per_slot = pages_for(cache_len, page_size)
        num_pages = pages_for(pool_tokens, page_size) + 1  # + trash page
        self.pool = PagePool(num_pages, page_size)
        # block tables: logical page -> physical page, 0 = unmapped
        self.block_tables = np.zeros(
            (max_slots, self.pages_per_slot), np.int32)
        # pages currently mapped per slot (bt[s, :n] are live)
        self.slot_pages_n = np.zeros((max_slots,), np.int32)
        # flat token-major pools, one dict per layer, index key dropped
        # (the per-dispatch view carries its own pinned index vector)
        tpl = model.init_cache(1, self.page_size, dtype=dtype)
        self.n_layers = len(tpl)
        pool_rows = num_pages * self.page_size
        kv = []
        for layer in tpl:
            bufs = {}
            for key, buf in layer.items():
                if key == "index":
                    continue
                tail = tuple(buf.shape[2:])   # (1, P, *tail)
                bufs[key] = jnp.zeros((pool_rows,) + tail, buf.dtype)
            kv.append(bufs)
        if mesh is not None:
            kv = jax.device_put(kv, self._pool_shardings(kv, mesh))
        self.kv = kv
        # ledger account kv_pool.pages: the flat pools are the one real
        # device allocation here — page/row rates derive from it so
        # every page-count figure converts to bytes the same way
        # everywhere (/debug/kv, /debug/hbm, session pins).
        self.pool_bytes = sum(int(buf.nbytes) for layer in kv
                              for buf in layer.values())
        self.row_bytes = self.pool_bytes // pool_rows if pool_rows else 0
        self.page_bytes = self.row_bytes * self.page_size
        self._ledger_open = True
        get_ledger().book("kv_pool.pages", self.pool_bytes)

    def close(self) -> None:
        """Release the pool's ledger claim (engine stop). Idempotent —
        a double stop must not double-free the account."""
        if self._ledger_open:
            self._ledger_open = False
            get_ledger().book("kv_pool.pages", -self.pool_bytes)

    def view_bytes(self, width: int, n_slots: int | None = None) -> int:
        """Device bytes of one transient gather view: ``n_slots`` rows
        of ``width`` tokens at the pool's per-row rate — what a paged
        dispatch materializes NEXT TO the pool (the coexistence bytes
        ROADMAP item 1 reclaims)."""
        s = self.max_slots if n_slots is None else int(n_slots)
        return int(width) * s * self.row_bytes

    @staticmethod
    def _pool_shardings(kv, mesh):
        """KV heads (second-to-last dim of 'k'/'v' pools) shard over the
        mesh's ``model`` axis; everything else replicates — the paged
        mirror of the contiguous engine's ``_cache_shardings``."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        tp = mesh.shape.get("model", 1)
        out = []
        for layer in kv:
            specs = {}
            for key, buf in layer.items():
                if (key in ("k", "v") and tp > 1 and buf.ndim >= 2
                        and buf.shape[-2] % tp == 0):
                    spec = [None] * buf.ndim
                    spec[-2] = "model"
                    specs[key] = NamedSharding(mesh, P(*spec))
                else:
                    specs[key] = NamedSharding(mesh, P())
            out.append(specs)
        return out

    # -- capacity -------------------------------------------------------------

    def fits_ever(self, n_tokens: int) -> bool:
        """Whether a request needing ``n_tokens`` KV rows can EVER be
        admitted (pool capacity, ignoring current occupancy) — the
        api-layer 422 check."""
        return pages_for(n_tokens, self.page_size) <= self.pool.capacity

    def slot_tokens_capacity(self, slot: int) -> int:
        return int(self.slot_pages_n[slot]) * self.page_size

    # -- block-table mutation (engine thread only) ----------------------------

    def map_shared(self, slot: int, pages: list[int]) -> None:
        """Start ``slot``'s table with already-incref'd shared pages."""
        n = len(pages)
        self.block_tables[slot, :n] = pages
        self.slot_pages_n[slot] = n

    def extend(self, slot: int, need_tokens: int) -> bool:
        """Grow ``slot``'s table to cover ``need_tokens`` positions;
        False when the pool (after reclaim) cannot supply the pages —
        the engine then preempts or sheds."""
        target = min(pages_for(need_tokens, self.page_size),
                     self.pages_per_slot)
        cur = int(self.slot_pages_n[slot])
        if target <= cur:
            return True
        pages = self.pool.try_alloc(target - cur)
        if pages is None:
            return False
        self.block_tables[slot, cur:target] = pages
        self.slot_pages_n[slot] = target
        return True

    def release_slot(self, slot: int) -> list[int]:
        """Drop every page mapping of ``slot`` (refcounts decremented;
        exclusively-owned pages return to the free list). Returns the
        released physical pages (tests assert on them)."""
        n = int(self.slot_pages_n[slot])
        pages = [int(p) for p in self.block_tables[slot, :n]]
        self.pool.release(pages)
        self.block_tables[slot, :n] = TRASH_PAGE
        self.slot_pages_n[slot] = 0
        return pages

    def slot_pages(self, slot: int) -> list[int]:
        n = int(self.slot_pages_n[slot])
        return [int(p) for p in self.block_tables[slot, :n]]

    # -- host-side index builders --------------------------------------------

    def gather_idx(self, width: int) -> np.ndarray:
        """(max_slots, width) flat pool-row indices for the contiguous
        view gather: position ``t`` of slot ``s`` reads
        ``bt[s, t // P] * P + t % P`` (unmapped pages -> trash)."""
        P = self.page_size
        t = np.arange(width)
        lp = t // P
        return (self.block_tables[:, lp] * P
                + (t % P)[None, :]).astype(np.int32)

    def row_gather_idx(self, slot: int, width: int) -> np.ndarray:
        """(1, width) flat indices over one slot (handoff/offload rows)."""
        P = self.page_size
        t = np.arange(width)
        lp = np.minimum(t // P, self.pages_per_slot - 1)
        return (self.block_tables[slot, lp] * P
                + (t % P)).astype(np.int32)[None, :]

    def scatter_idx(self, starts: np.ndarray, valid: np.ndarray,
                    width: int) -> np.ndarray:
        """(max_slots, width) flat pool-row targets for the write-back
        of each row's window ``[starts[s], starts[s] + valid[s])``;
        positions at ``j >= valid[s]`` (and any unmapped page) are
        routed to the trash page."""
        P = self.page_size
        j = np.arange(width)
        pos = starts.astype(np.int64)[:, None] + j[None, :]
        lp = np.minimum(pos // P, self.pages_per_slot - 1)
        phys = np.take_along_axis(
            self.block_tables, lp.astype(np.int64), axis=1)
        keep = j[None, :] < valid[:, None]
        phys = np.where(keep, phys, TRASH_PAGE)
        return (phys * P + pos % P).astype(np.int32)

    def rows_scatter_idx(self, slots: list[int], lengths: list[int],
                         width: int) -> np.ndarray:
        """(B, width) flat targets for scattering B bucket-width row
        sets (one-shot prefill / direct insert): row b's positions
        ``[0, lengths[b])`` land in ``slots[b]``'s pages, padding goes
        to trash."""
        P = self.page_size
        j = np.arange(width)
        out = np.zeros((len(slots), width), np.int64)
        for b, (s, ln) in enumerate(zip(slots, lengths)):
            lp = np.minimum(j // P, self.pages_per_slot - 1)
            phys = self.block_tables[s, lp]
            phys = np.where(j < ln, phys, TRASH_PAGE)
            out[b] = phys * P + j % P
        return out.astype(np.int32)

    # -- snapshots ------------------------------------------------------------

    def debug_snapshot(self) -> dict:
        """The ``GET /debug/kv`` payload: pool occupancy, sharing,
        fragmentation, and per-slot block-table sizes.

        Pool state comes from ONE :meth:`PagePool.snapshot` (a report
        stitched from the per-field properties could tear between lock
        acquisitions), and every page figure is cross-linked to ledger
        account ``kv_pool.pages`` at the pool's own byte rate — so
        ``/debug/kv`` and ``/debug/hbm`` cannot disagree on what a page
        costs."""
        pool = self.pool.snapshot()
        # internal fragmentation: allocated-but-unfilled token slack of
        # the slot-mapped pages (tail of each slot's last page)
        mapped = int(np.sum(self.slot_pages_n))
        return {
            "layout": "paged",
            "page_size": self.page_size,
            "pages_total": pool["capacity"],
            "pages_free": pool["free_pages"],
            "pages_used": pool["used_pages"],
            "pages_shared": pool["shared_pages"],
            "pages_slot_mapped": mapped,
            "refcount_histogram": {
                str(k): v for k, v in
                sorted(pool["refcount_histogram"].items())},
            "alloc_failures": pool["alloc_failures"],
            "block_table_pages_per_slot": [
                int(n) for n in self.slot_pages_n],
            "ledger_account": "kv_pool.pages",
            "page_bytes": self.page_bytes,
            "pool_bytes": self.pool_bytes,
            "slot_mapped_bytes": mapped * self.page_bytes,
        }

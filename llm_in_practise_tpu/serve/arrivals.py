"""Trace-replay arrival schedules for the closed-loop serve benches.

ROADMAP item 2(b), first slice: the uniform closed-loop ladders
(N workers, back-to-back requests) measure steady-state throughput but
never exercise the shapes real traffic has — bursts, idle gaps, and
mixed prompt/output lengths arriving TOGETHER. This module synthesizes
a seeded, replayable arrival trace:

- **Bursty inter-arrivals**: Gamma-distributed gaps with a chosen
  coefficient of variation (``cv = 1`` is Poisson; ``cv > 1`` is
  burstier than Poisson — the canonical open-loop burst model). The
  Gamma shape is ``1/cv²`` and the scale ``mean·cv²``, so the mean
  inter-arrival time is exact whatever the burstiness.
- **Mixed lengths**: per-request prompt/output token counts drawn
  log-uniformly from configured ranges — the short-chat-next-to-long-
  document mix arxiv 2311.03687's runtime dissection shows dominating
  mixed-load latency.
- **Replayability**: everything derives from one ``numpy`` Generator
  seed; the schedule (and its parameters) embed in the BENCH artifact,
  so a regression run replays the identical trace.

Used by ``tools/structured_bench.py`` (the BENCH_STRUCTURED artifact)
and pluggable into the other serve benches; :func:`replay` drives any
``submit(request) -> handle`` callable at the scheduled offsets from a
pool of worker threads (open-loop: a late engine does NOT slow the
arrival clock — queueing shows up as queueing, not as a lighter load).
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled request: offset seconds from trace start, prompt
    length and output budget in tokens."""

    at_s: float
    prompt_tokens: int
    max_tokens: int


def synthesize(*, seed: int, n_requests: int, mean_iat_s: float,
               cv: float = 2.0, prompt_tokens: tuple[int, int] = (8, 64),
               max_tokens: tuple[int, int] = (8, 64)) -> list[Arrival]:
    """Seeded bursty trace: Gamma(1/cv², mean·cv²) inter-arrivals plus
    log-uniform prompt/output lengths. ``cv=1`` degenerates to Poisson;
    ``cv=0`` to a uniform (closed-ladder-like) clock."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if mean_iat_s < 0:
        raise ValueError(f"mean_iat_s must be >= 0, got {mean_iat_s}")
    rng = np.random.default_rng(seed)
    if cv <= 0 or mean_iat_s == 0:
        gaps = np.full((n_requests,), mean_iat_s)
    else:
        shape = 1.0 / (cv * cv)
        gaps = rng.gamma(shape, mean_iat_s / shape, size=n_requests)
    at = np.cumsum(gaps)
    at -= at[0]  # first request arrives at t=0

    def log_uniform(lo: int, hi: int, size: int) -> np.ndarray:
        lo, hi = max(1, int(lo)), max(1, int(hi))
        if hi <= lo:
            return np.full((size,), lo)
        return np.exp(rng.uniform(np.log(lo), np.log(hi + 1), size=size)
                      ).astype(np.int64).clip(lo, hi)

    plens = log_uniform(*prompt_tokens, n_requests)
    olens = log_uniform(*max_tokens, n_requests)
    return [Arrival(float(at[i]), int(plens[i]), int(olens[i]))
            for i in range(n_requests)]


def describe(schedule: list[Arrival]) -> dict:
    """Artifact block: the schedule's realized statistics (the seeded
    parameters reproduce it; the realized numbers make drift visible)."""
    gaps = np.diff([a.at_s for a in schedule]) if len(schedule) > 1 else (
        np.zeros((1,)))
    return {
        "n_requests": len(schedule),
        "span_s": round(schedule[-1].at_s, 4) if schedule else 0.0,
        "iat_mean_s": round(float(np.mean(gaps)), 5),
        "iat_cv": round(float(np.std(gaps) / np.mean(gaps)), 3)
        if float(np.mean(gaps)) > 0 else 0.0,
        "prompt_tokens_mean": round(float(np.mean(
            [a.prompt_tokens for a in schedule])), 1),
        "max_tokens_mean": round(float(np.mean(
            [a.max_tokens for a in schedule])), 1),
    }


@dataclasses.dataclass(frozen=True)
class SessionArrival:
    """One turn of one conversation in a multi-turn trace: the session
    identity and turn index ride with the usual offset/length fields so
    a bench can key routing, build the cumulative prompt, and tell a
    cold first turn from warm follow-ups."""

    at_s: float
    session_id: str
    turn: int                # 0-based within the session
    n_turns: int             # this session's total turns
    prompt_tokens: int       # NEW tokens this turn appends
    max_tokens: int
    adapter: str | None = None   # tenant (--lora-modules name), if mixed


def synthesize_sessions(*, seed: int, n_sessions: int,
                        turns: tuple[int, int] = (2, 5),
                        mean_iat_s: float = 0.05, cv: float = 2.0,
                        think_time_s: tuple[float, float] = (0.05, 0.3),
                        prompt_tokens: tuple[int, int] = (8, 48),
                        max_tokens: tuple[int, int] = (8, 32),
                        adapters: list[str] | None = None,
                        ) -> list[SessionArrival]:
    """Seeded multi-turn session trace (ROADMAP item 5's next slice,
    the driver for ``tools/session_bench.py``).

    Sessions OPEN with the bursty Gamma inter-arrival clock of
    :func:`synthesize`; each session then runs ``turns`` follow-ups
    separated by log-uniform think-time gaps — so turns of different
    sessions interleave and a replica's cache sees unrelated traffic
    between one conversation's turns (the case session pinning exists
    for). ``adapters`` assigns each session a tenant round-robin
    (mixed multi-LoRA traffic); the per-turn ``prompt_tokens`` is the
    NEW suffix — the caller accumulates the shared prefix, which is
    what makes follow-ups warm-hittable at all. Returned sorted by
    ``at_s``: the global arrival order :func:`replay` fires in.
    """
    if n_sessions < 1:
        raise ValueError(f"n_sessions must be >= 1, got {n_sessions}")
    rng = np.random.default_rng(seed)
    if cv <= 0 or mean_iat_s == 0:
        gaps = np.full((n_sessions,), mean_iat_s)
    else:
        shape = 1.0 / (cv * cv)
        gaps = rng.gamma(shape, mean_iat_s / shape, size=n_sessions)
    opens = np.cumsum(gaps)
    opens -= opens[0]
    out: list[SessionArrival] = []
    lo_t, hi_t = max(1, int(turns[0])), max(1, int(turns[1]))
    for s in range(n_sessions):
        n_turns = int(rng.integers(lo_t, hi_t + 1))
        adapter = (adapters[s % len(adapters)]
                   if adapters else None)
        at = float(opens[s])
        for t in range(n_turns):
            if t > 0:
                lo, hi = think_time_s
                at += float(np.exp(rng.uniform(
                    np.log(max(lo, 1e-4)), np.log(max(hi, 1e-4)))))
            out.append(SessionArrival(
                at_s=at,
                session_id=f"sess-{seed}-{s}",
                turn=t, n_turns=n_turns,
                prompt_tokens=int(rng.integers(
                    max(1, prompt_tokens[0]),
                    max(1, prompt_tokens[1]) + 1)),
                max_tokens=int(rng.integers(
                    max(1, max_tokens[0]),
                    max(1, max_tokens[1]) + 1)),
                adapter=adapter))
    out.sort(key=lambda a: (a.at_s, a.session_id, a.turn))
    return out


def describe_sessions(schedule: list[SessionArrival]) -> dict:
    """Artifact block for a session trace (mirrors :func:`describe`)."""
    sessions = {a.session_id for a in schedule}
    warm = [a for a in schedule if a.turn > 0]
    return {
        "n_sessions": len(sessions),
        "n_turns": len(schedule),
        "warm_turns": len(warm),
        "span_s": round(schedule[-1].at_s, 4) if schedule else 0.0,
        "turns_per_session_mean": round(
            len(schedule) / max(1, len(sessions)), 2),
        "prompt_tokens_mean": round(float(np.mean(
            [a.prompt_tokens for a in schedule])), 1) if schedule else 0.0,
        "adapters": sorted({a.adapter for a in schedule
                            if a.adapter is not None}),
    }


def replay(schedule: list[Arrival], submit, *, workers: int = 8,
           time_scale: float = 1.0, lateness: list | None = None) -> list:
    """Open-loop replay: fire ``submit(arrival)`` at each arrival's
    scheduled offset (scaled by ``time_scale``) from a worker pool, and
    return the submit results in schedule order.

    Open-loop holds only while in-flight requests fit the pool: callers
    that BLOCK inside ``submit`` (drain the stream) bound concurrency
    at ``workers``, and arrivals past that fire LATE — a degradation
    toward closed-loop that must be visible, not assumed away. Pass a
    ``lateness`` list to receive each arrival's realized (start − due)
    seconds in schedule order; the benches embed its p99/max so an
    artifact states the load actually applied, not just the schedule.
    """
    results: list = [None] * len(schedule)
    late: list = [0.0] * len(schedule)
    idx_lock = threading.Lock()
    next_idx = [0]
    t0 = time.monotonic()

    def worker():
        while True:
            with idx_lock:
                i = next_idx[0]
                if i >= len(schedule):
                    return
                next_idx[0] += 1
            due = t0 + schedule[i].at_s * time_scale
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            late[i] = max(0.0, time.monotonic() - due)
            results[i] = submit(schedule[i])

    threads = [threading.Thread(target=worker)
               for _ in range(max(1, workers))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if lateness is not None:
        lateness.extend(late)
    return results


def lateness_stats(lateness: list) -> dict:
    """Artifact block for a replay's realized arrival lateness."""
    arr = np.asarray(lateness if lateness else [0.0])
    return {
        "arrival_lateness_p99_s": round(float(np.percentile(arr, 99)), 4),
        "arrival_lateness_max_s": round(float(arr.max()), 4),
    }

"""OpenAI-compatible HTTP server over the continuous-batching engine.

Parity with the reference's FastAPI server
(``Scripts/inference/07-deepseek1.5b-api-infr.py``):

- ``POST /v1/chat/completions`` — non-streaming (``:105-161``) **and** SSE
  streaming, which the reference stubs out with a 501 (``:110-112``); here it
  is implemented (chunked ``data:`` events + ``[DONE]``), closing that gap
  the reference defers to vLLM.
- prompt build from OpenAI messages (``:37-57``) — ChatML via
  :func:`llm_in_practise_tpu.data.sft.render_chatml` plus the generation
  prompt suffix.
- usage accounting (``:118-152``), ``GET /v1/models``, ``GET /health``.
- ``GET /metrics`` — Prometheus text exposition with the platform's canonical
  serving metrics (queue depth, running requests, TTFT/TPOT quantiles —
  mirroring the PromQL table ``LLM_on_Kubernetes/Inference_Platfrom/
  README.md:1676-1692``).

Built on the stdlib ``ThreadingHTTPServer`` — the serving runtime carries no
web-framework dependency; each connection gets an OS thread, generation
throughput is owned by the engine's single background loop.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from llm_in_practise_tpu.data.sft import IM_START, render_chatml
from llm_in_practise_tpu.serve import schemas
from llm_in_practise_tpu.serve.engine import InferenceEngine, SamplingParams


def build_prompt(messages) -> str:
    """OpenAI messages -> ChatML generation prompt (reference ``:37-57``)."""
    rendered = render_chatml([{"role": m.role, "content": m.content} for m in messages])
    return rendered + f"\n{IM_START}assistant\n"


def _quantile(values, q):
    if not values:
        return 0.0
    return float(np.quantile(np.asarray(values), q))


class OpenAIServer:
    """Wires engine + tokenizer + HTTP. ``tokenizer`` needs ``encode``/``decode``."""

    def __init__(
        self,
        engine: InferenceEngine,
        tokenizer,
        *,
        model_name: str = "llm-in-practise-tpu",
        prompt_builder=build_prompt,
    ):
        self.engine = engine
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.prompt_builder = prompt_builder
        self._httpd: ThreadingHTTPServer | None = None

    # --- request handling ----------------------------------------------------

    def handle_chat(self, body: dict, send_json, send_stream):
        try:
            req = schemas.ChatCompletionRequest.from_dict(body)
        except schemas.ValidationError as e:
            return send_json(422, {"error": {"message": str(e), "type": "invalid_request_error"}})

        prompt = self.prompt_builder(req.messages)
        prompt_ids = self.tokenizer.encode(prompt)
        params = SamplingParams(
            temperature=req.temperature,
            top_k=req.top_k,
            top_p=req.top_p,
            greedy=req.temperature == 0.0,
            max_tokens=req.max_tokens,
        )
        handle = self.engine.submit(prompt_ids, params)
        req_id = schemas.completion_id()

        if req.stream:
            def chunks():
                yield schemas.chat_completion_chunk(
                    req_id=req_id, model=req.model, delta=None
                )
                tokens, prev_text = [], ""
                for tok in handle:
                    tokens.append(tok)
                    text = self.tokenizer.decode(tokens)
                    delta, prev_text = text[len(prev_text):], text
                    if delta:
                        yield schemas.chat_completion_chunk(
                            req_id=req_id, model=req.model, delta=delta
                        )
                yield schemas.chat_completion_chunk(
                    req_id=req_id, model=req.model, delta=None,
                    finish_reason=handle.finish_reason or "stop",
                )
            return send_stream(chunks())

        out_ids = handle.result()
        text = self.tokenizer.decode(out_ids)
        usage = schemas.Usage(len(prompt_ids), len(out_ids))
        return send_json(200, schemas.chat_completion_response(
            req_id=req_id, model=req.model, text=text,
            finish_reason=handle.finish_reason or "stop", usage=usage,
        ))

    def metrics_text(self) -> str:
        s = self.engine.stats
        with s.lock:
            ttft, tpot = list(s.ttft_s), list(s.tpot_s)
            lines = [
                "# TYPE llm_requests_total counter",
                f"llm_requests_total {s.requests_total}",
                "# TYPE llm_tokens_generated_total counter",
                f"llm_tokens_generated_total {s.tokens_generated_total}",
                "# TYPE llm_num_requests_waiting gauge",
                f"llm_num_requests_waiting {s.queue_depth}",
                "# TYPE llm_num_requests_running gauge",
                f"llm_num_requests_running {s.active_slots}",
            ]
        for name, vals in (("llm_ttft_seconds", ttft), ("llm_tpot_seconds", tpot)):
            lines += [
                f"# TYPE {name} summary",
                f'{name}{{quantile="0.5"}} {_quantile(vals, 0.5):.6f}',
                f'{name}{{quantile="0.99"}} {_quantile(vals, 0.99):.6f}',
                f"{name}_count {len(vals)}",
                f"{name}_sum {sum(vals):.6f}",
            ]
        return "\n".join(lines) + "\n"

    # --- HTTP plumbing -------------------------------------------------------

    def make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet; obs handles logging
                pass

            _responded = False

            def _json(self, status: int, payload: dict):
                self._responded = True
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _sse(self, events):
                self._responded = True
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                try:
                    try:
                        for event in events:
                            payload = f"data: {json.dumps(event)}\n\n".encode()
                            self.wfile.write(payload)
                            self.wfile.flush()
                    except Exception as e:  # noqa: BLE001 — headers are out;
                        # surface the fault as an SSE error event, then DONE.
                        err = {"error": {"message": f"{type(e).__name__}: {e}",
                                         "type": "internal_error"}}
                        self.wfile.write(f"data: {json.dumps(err)}\n\n".encode())
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-stream

            def do_GET(self):
                if self.path == "/health":
                    return self._json(200, {"status": "ok"})
                if self.path == "/v1/models":
                    return self._json(200, {
                        "object": "list",
                        "data": [{
                            "id": server.model_name,
                            "object": "model",
                            "owned_by": "llm-in-practise-tpu",
                        }],
                    })
                if self.path == "/metrics":
                    body = server.metrics_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                return self._json(404, {"error": {"message": "not found"}})

            def do_POST(self):
                if self.path not in ("/v1/chat/completions",):
                    return self._json(404, {"error": {"message": "not found"}})
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    return self._json(400, {"error": {"message": "invalid JSON body"}})
                try:
                    return server.handle_chat(body, self._json, self._sse)
                except Exception as e:  # noqa: BLE001 — a handler fault must
                    # still answer the client, not drop the connection. If a
                    # response already went out (SSE underway), sending a
                    # second status line would corrupt the stream — _sse has
                    # its own in-band error path; just stop.
                    if self._responded:
                        return None
                    return self._json(500, {"error": {
                        "message": f"{type(e).__name__}: {e}",
                        "type": "internal_error",
                    }})

        return Handler

    def serve(self, host: str = "0.0.0.0", port: int = 8000, *, background: bool = False):
        """Start engine loop + HTTP server. Returns the bound port."""
        if self.engine._thread is None:
            self.engine.start()
        self._httpd = ThreadingHTTPServer((host, port), self.make_handler())
        bound = self._httpd.server_address[1]
        if background:
            threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        else:
            self._httpd.serve_forever()
        return bound

    def shutdown(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        self.engine.stop()

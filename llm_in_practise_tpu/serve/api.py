"""OpenAI-compatible HTTP server over the continuous-batching engine.

Parity with the reference's FastAPI server
(``Scripts/inference/07-deepseek1.5b-api-infr.py``):

- ``POST /v1/chat/completions`` — non-streaming (``:105-161``) **and** SSE
  streaming, which the reference stubs out with a 501 (``:110-112``); here it
  is implemented (chunked ``data:`` events + ``[DONE]``), closing that gap
  the reference defers to vLLM.
- prompt build from OpenAI messages (``:37-57``) — ChatML via
  :func:`llm_in_practise_tpu.data.sft.render_chatml` plus the generation
  prompt suffix.
- usage accounting (``:118-152``), ``GET /v1/models``, ``GET /health``.
- ``POST /v1/embeddings`` — mean-pooled hidden states (the embedding
  service the reference's semantic cache / RAG stack call out to).
- ``GET /metrics`` — Prometheus text exposition rendered by the unified
  registry (:mod:`llm_in_practise_tpu.obs.registry`): queue depth, running
  requests, bucketed TTFT/TPOT histograms — mirroring the PromQL table
  ``LLM_on_Kubernetes/Inference_Platfrom/README.md:1676-1692``; see
  docs/observability.md for the catalog.
- ``GET /debug/traces`` — the request-span ring
  (:mod:`llm_in_practise_tpu.obs.trace`): per-request spans for queue
  wait, admission, prefill chunks, decode, handoff publish/claim, and
  stream flush, correlated across the gateway and the disaggregated
  replicas by a ``traceparent``-propagated trace id.

Built on the stdlib ``ThreadingHTTPServer`` — the serving runtime carries no
web-framework dependency; each connection gets an OS thread, generation
throughput is owned by the engine's single background loop.
"""

from __future__ import annotations

import dataclasses
import html
import json
import sys
import threading
import time
from http.server import ThreadingHTTPServer

import numpy as np

from llm_in_practise_tpu.data.sft import IM_START, render_chatml
from llm_in_practise_tpu.obs.hbm import (
    get_ledger,
    host_entry_bytes,
    register_hbm_ledger,
)
from llm_in_practise_tpu.obs.registry import Registry
from llm_in_practise_tpu.obs.trace import get_tracer, parse_traceparent
from llm_in_practise_tpu.serve import constrain, schemas
from llm_in_practise_tpu.serve.engine import InferenceEngine, SamplingParams
from llm_in_practise_tpu.serve.http_util import (
    JsonHandler,
    serve_obs_get,
    serve_obs_post,
)


def build_prompt(messages) -> str:
    """OpenAI messages -> ChatML generation prompt (reference ``:37-57``)."""
    rendered = render_chatml([{"role": m.role, "content": m.content} for m in messages])
    return rendered + f"\n{IM_START}assistant\n"


class OpenAIServer:
    """Wires engine + tokenizer + HTTP. ``tokenizer`` needs ``encode``/``decode``."""

    def __init__(
        self,
        engine: InferenceEngine,
        tokenizer,
        *,
        model_name: str = "llm-in-practise-tpu",
        prompt_builder=build_prompt,
        adapters: dict[str, InferenceEngine] | None = None,
        role: str = "both",
        handoff=None,
        tracer=None,
    ):
        from llm_in_practise_tpu.obs.meter import HandoffMeter
        from llm_in_practise_tpu.serve.disagg import validate_roles

        self.engine = engine
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.prompt_builder = prompt_builder
        # Disaggregated serving (serve/disagg.py): ``role`` gates the
        # internal handoff endpoint and labels the per-role latency
        # metrics; ``handoff`` is the store prefill publishes into and
        # decode claims from (shared pool server, or LocalHandoff for
        # single-process setups).
        self.role = validate_roles(role)
        # decode claims from the same store the engine publishes into
        # unless the caller splits them explicitly
        self.handoff = (handoff if handoff is not None
                        else getattr(engine, "handoff", None))
        self.handoff_meter = HandoffMeter()
        # vLLM ``--enable-lora --lora-modules name=path`` parity: additional
        # model names served from adapter-merged weights, picked by the
        # request's ``model`` field (see serve/adapters.py).
        self.adapters = dict(adapters or {})
        self._httpd: ThreadingHTTPServer | None = None
        # lazily jitted /v1/embeddings pooler, keyed per engine: adapter
        # engines may carry different modules, and a pooler closing over
        # one engine's model must never run another's params
        self._embed_fns: dict[int, object] = {}
        # request tracing (obs/trace.py): the API layer mints/extends the
        # per-request TraceContext; the engine parents its phase spans to
        # it. Default = the process tracer, so colocated components share
        # one ring and GET /debug/traces sees the whole request.
        self.tracer = tracer if tracer is not None else get_tracer()
        # Structured output (serve/constrain.py, ISSUE 12): the
        # per-server grammar compile cache plus the per-engine decoded
        # vocab it compiles against. Handler threads compile; repeat
        # schemas (the agent-loop shape) hit the cache.
        self._constraints = constrain.ConstraintCompiler()
        self._vocab_lock = threading.Lock()
        self._constraint_vocabs: dict[int, list[str]] = {}  # guarded-by: _vocab_lock
        self._structured_lock = threading.Lock()
        # llm_structured_requests_total{kind=…}; scrapes read the ints
        # lock-free (monotone counters — the spec_* convention)
        self._structured_counts = {"json_object": 0, "json_schema": 0,
                                   "tool_call": 0}  # guarded-by: _structured_lock
        # unified metrics registry (obs/registry.py): scrape-time
        # callbacks over the live engine/meter counters — the ONE
        # exposition renderer, replacing the hand-formatted text block
        self.registry = self._build_registry()

    # --- structured output ----------------------------------------------------

    def _constraint_vocab(self, engine: InferenceEngine) -> tuple[list, int]:
        """Decoded per-id vocab pieces for ``engine`` (cached). Raises
        :class:`~llm_in_practise_tpu.serve.constrain.ConstraintError`
        when the model exposes no vocab size (structured output is then
        a 422 — the server cannot promise schema conformance)."""
        key = id(engine)
        with self._vocab_lock:
            got = self._constraint_vocabs.get(key)
        if got is None:
            vs = getattr(getattr(engine.model, "config", None),
                         "vocab_size", None)
            if vs is None:
                raise constrain.ConstraintError(
                    "this model exposes no vocab_size; structured "
                    "output is unavailable")
            got = constrain.vocab_strings(self.tokenizer, int(vs))
            with self._vocab_lock:
                self._constraint_vocabs[key] = got
        return got, key

    def _compile_constraint(self, engine: InferenceEngine,
                            req: "schemas.ChatCompletionRequest"):
        """Request fields → shared compiled automaton (or None). Raises
        ConstraintError on invalid/unsupported specs (HTTP 422)."""
        rf_type = (req.response_format or {}).get("type")
        if (rf_type in (None, "text")
                and req.tool_choice in (None, "auto", "none")):
            # unconstrained request (the SDK default response_format
            # {"type": "text"} included): never touch the vocab cache
            # — a model without vocab_size must still serve plain chat
            return None
        vocab, vocab_key = self._constraint_vocab(engine)
        return self._constraints.get(
            response_format=req.response_format, tools=req.tools,
            tool_choice=req.tool_choice, vocab=vocab,
            vocab_key=vocab_key, eos_id=engine.eos_id)

    def _note_structured(self, kind: str) -> None:
        with self._structured_lock:
            self._structured_counts[kind] = (
                self._structured_counts.get(kind, 0) + 1)

    def engine_for(self, model: str | None) -> InferenceEngine | None:
        if model in (None, "", self.model_name):
            return self.engine
        return self.adapters.get(model)

    # --- request handling ----------------------------------------------------

    def handle_embeddings(self, body: dict, send_json):
        """``POST /v1/embeddings`` — OpenAI embeddings schema over
        mean-pooled final hidden states (``return_hidden``). This is the
        in-tree counterpart of the embedding service the reference's
        semantic cache and RAG stack call out to."""
        import jax
        import jax.numpy as jnp

        inputs = body.get("input")
        if isinstance(inputs, str):
            inputs = [inputs]
        def _ok(x):
            if isinstance(x, str):
                return True
            return (isinstance(x, list)
                    and all(isinstance(t, int) for t in x))

        if not isinstance(inputs, list) or not inputs or not all(
                _ok(x) for x in inputs):
            return send_json(422, {"error": {
                "message": "input must be a string, list of strings, or "
                           "list of integer token lists",
                "type": "invalid_request_error"}})
        engine = self.engine_for(body.get("model"))
        if engine is None:
            return send_json(404, {"error": {
                "message": f"model {body.get('model')!r} not found",
                "type": "invalid_request_error"}})

        embed_fn = self._embed_fns.get(id(engine))
        if embed_fn is None:
            model = engine.model

            def embed(params, ids, length):
                h = model.apply({"params": params}, ids,
                                deterministic=True, return_hidden=True)
                mask = (jnp.arange(ids.shape[1]) < length)[None, :, None]
                pooled = (h * mask).sum(axis=1) / jnp.maximum(length, 1)
                return pooled[0].astype(jnp.float32)

            # lazily built ONCE per engine and cached in self._embed_fns
            # (checked above) — later requests reuse the compiled pooler
            embed_fn = self._embed_fns[id(engine)] = jax.jit(embed)  # graftlint: disable=jit-in-handler

        data, total = [], 0
        for i, item in enumerate(inputs):
            ids = (list(item) if isinstance(item, list)
                   else self.tokenizer.encode(item))
            ids = ids[: engine.cache_len] or [0]
            total += len(ids)
            bucket = engine._bucket_for(len(ids))  # reuse prefill buckets
            padded = np.zeros((1, bucket), np.int32)
            padded[0, : len(ids)] = ids
            try:
                vec = np.asarray(embed_fn(
                    engine.params, jnp.asarray(padded),
                    jnp.asarray(len(ids), jnp.int32)), np.float64)
            except TypeError:
                return send_json(501, {"error": {
                    "message": "this model does not expose hidden states "
                               "(return_hidden)",
                    "type": "unsupported_error"}})
            norm = float(np.linalg.norm(vec)) or 1.0
            data.append({"object": "embedding", "index": i,
                         "embedding": (vec / norm).tolist()})
        return send_json(200, {
            "object": "list",
            "data": data,
            "model": body.get("model") or self.model_name,
            "usage": {"prompt_tokens": total, "total_tokens": total},
        })

    def handle_prefill(self, body: dict, send_json, trace=None):
        """``POST /internal/handoff/prefill`` — the prefill half of
        disaggregated serving (serve/disagg.py). Runs prefill only,
        publishes the prompt KV into the handoff store, and returns the
        handoff id the router passes to a decode replica via
        ``kv_transfer_params``. Internal: only the gateway calls this
        (it is absent on pure-decode replicas). ``trace``: the gateway's
        TraceContext (from the ``traceparent`` header) — the prefill
        phase's engine spans join the request's trace."""
        from llm_in_practise_tpu.serve.disagg import new_handoff_id

        if self.role == "decode":
            return send_json(501, {"error": {
                "message": "decode replicas do not prefill for handoff",
                "type": "unsupported_error"}})
        try:
            req = schemas.ChatCompletionRequest.from_dict(
                dict(body, model=body.get("model") or self.model_name))
        except schemas.ValidationError as e:
            return send_json(422, {"error": {
                "message": str(e), "type": "invalid_request_error"}})
        engine = self.engine_for(req.model)
        if engine is None:
            return send_json(404, {"error": {
                "message": f"model {req.model!r} not found",
                "type": "invalid_request_error"}})
        if getattr(engine, "handoff", None) is None:
            # per-MODEL capability: an adapter engine without its own
            # handoff store must 501 here, not burn a prefill whose
            # publish is guaranteed to fail (the gateway treats 501 as
            # "serve undisaggregated", not as an upstream failure)
            return send_json(501, {"error": {
                "message": f"model {req.model!r} has no handoff store "
                           "on this replica",
                "type": "unsupported_error"}})
        prompt_ids = self.tokenizer.encode(self.prompt_builder(req.messages))
        hid = new_handoff_id()
        span = self.tracer.start_span("api.prefill", parent=trace,
                                      model=req.model, handoff_id=hid)
        from llm_in_practise_tpu.serve.engine import EngineDeadError

        outcome = "error"  # the span's finish_reason mirrors the HTTP
        # outcome (handle.finish_reason is None on engine death and
        # partial on sheds — /debug/traces must say what the caller saw)
        try:
            # inside the span's try: a submit failure (bad prompt, dead
            # engine thread) must end the span as an error, not leak it
            # unrecorded while do_POST answers 500
            handle = engine.submit(prompt_ids, SamplingParams(max_tokens=1),
                                   handoff_id=hid, trace=span.context())
            try:
                handle.result()  # drains to _FINISH; prefill emits no
                # tokens
            except EngineDeadError:
                outcome = "engine_dead"
                return send_json(503, {"error": {
                    "message": "engine is not running",
                    "type": "internal_error",
                    "code": "engine_dead"}})
            if handle.finish_reason == "too_large":
                outcome = "too_large"
                detail = engine.page_capacity_detail(len(prompt_ids))
                return send_json(422, {"error": {
                    "message": (
                        "prompt can never fit this replica's KV page "
                        f"pool ({detail['pages_needed']} pages needed "
                        f"vs {detail['pages_capacity']} capacity)"),
                    "type": "invalid_request_error",
                    "code": "prompt_too_large",
                    "detail": detail}})
            if handle.finish_reason == "queue_full":
                outcome = "queue_full"
                return send_json(429, {"error": {
                    "message": "prefill queue full — retry another replica",
                    "type": "rate_limit_error", "code": "queue_full"}})
            if handle.finish_reason != "handoff":
                outcome = "handoff_failed"
                return send_json(503, {"error": {
                    "message": "KV publish failed (pool unreachable or "
                               "handoff budget exhausted) — serve this "
                               "request undisaggregated",
                    "type": "internal_error", "code": "handoff_failed"}})
            outcome = "handoff"
            return send_json(200, {
                "handoff_id": hid,
                "prompt_tokens": len(handle.prompt_ids),
                "model": req.model,
            })
        finally:
            span.end(finish_reason=outcome)

    def handle_chat(self, body: dict, send_json, send_stream, trace=None,
                    session_id: str | None = None):
        try:
            req = schemas.ChatCompletionRequest.from_dict(body)
        except schemas.ValidationError as e:
            return send_json(422, {"error": {"message": str(e), "type": "invalid_request_error"}})
        # session-native serving (serve/sessions.py, ISSUE 17): the
        # X-Session-ID header wins; the body field covers clients that
        # can't set headers. Ignored entirely on engines without a store.
        if session_id is None and isinstance(body.get("session_id"), str):
            session_id = body["session_id"]

        engine = self.engine_for(req.model)
        if engine is None:
            return send_json(404, {"error": {
                "message": f"model {req.model!r} not found; have "
                           f"{[self.model_name, *self.adapters]}",
                "type": "invalid_request_error",
            }})
        prompt = self.prompt_builder(req.messages)
        prompt_ids = self.tokenizer.encode(prompt)
        params = SamplingParams(
            temperature=req.temperature,
            top_k=req.top_k,
            top_p=req.top_p,
            greedy=req.temperature == 0.0,
            max_tokens=req.max_tokens,
        )
        # structured output (serve/constrain.py): compile the grammar
        # the engine will enforce in-dispatch; an invalid/unsupported
        # schema is a client error — 422 BEFORE any engine work
        constraint_kind = None
        try:
            automaton = self._compile_constraint(engine, req)
        except constrain.ConstraintError as e:
            return send_json(422, {"error": {
                "message": str(e), "type": "invalid_request_error",
                "code": "invalid_constraint"}})
        if automaton is not None:
            constraint_kind = automaton.kind
            self._note_structured(constraint_kind)
            params = dataclasses.replace(params, constraint=automaton)
        # disaggregated serving: a router that already prefilled this
        # prompt elsewhere points us at the pinned KV entry; a lost claim
        # (expired/claimed/unreachable) degrades to local prefill — the
        # engine counts it, the stream is correct either way
        kv_entry = None
        xfer = body.get("kv_transfer_params")
        # trace continuity: the traceparent header is primary; the
        # handoff body's ride-along copy covers intermediaries that
        # strip headers (the prefill→decode hop must stay one trace)
        ctx = trace
        if ctx is None and isinstance(xfer, dict) and xfer.get("trace"):
            ctx = parse_traceparent(str(xfer["trace"]))
        span = self.tracer.start_span(
            "api.chat", parent=ctx, model=req.model or self.model_name,
            stream=bool(req.stream),
            handed_off=bool(isinstance(xfer, dict)
                            and xfer.get("handoff_id")))
        try:
            if isinstance(xfer, dict) and xfer.get("handoff_id"):
                # claim from the target MODEL's store when it has one (each
                # model's handoff namespace is distinct — base vs adapters),
                # else the server-level store
                store = getattr(engine, "handoff", None) or self.handoff
                with self.tracer.span("handoff.claim", parent=span,
                                      handoff_id=str(xfer["handoff_id"])) as cs:
                    if store is not None:
                        kv_entry = store.claim(str(xfer["handoff_id"]))
                    cs.set(found=kv_entry is not None)
                self.handoff_meter.claim_outcome(kv_entry is not None)
                if kv_entry is not None:
                    # claim-side staging: the host entry lives only
                    # until admission scatters it — shorter than any
                    # scrape, so pulse (peak), don't book (level)
                    get_ledger().pulse("handoff_staging",
                                       host_entry_bytes(kv_entry))
            # session fleet miss path (serve/sessions.py): an unknown
            # session on this replica (ring rebalance / replica death
            # remapped it here) pulls its KV from the pool's handoff
            # namespace on THIS thread; a lost entry just means a local
            # re-prefill — counted, never an error
            sess_store = getattr(engine, "session_store", None)
            if session_id is not None and sess_store is not None \
                    and not sess_store.known(session_id):
                pool = getattr(engine, "handoff", None) or self.handoff
                if pool is not None:
                    from llm_in_practise_tpu.serve.sessions import (
                        session_hid,
                    )

                    with self.tracer.span("session.pull", parent=span,
                                          session=session_id) as ps:
                        pulled = pool.claim(session_hid(session_id))
                        ps.set(found=pulled is not None)
                    if pulled is not None:
                        sess_store.adopt(session_id, pulled)
                        get_ledger().pulse("handoff_staging",
                                           host_entry_bytes(pulled))
                    else:
                        sess_store.note_lost()
            handle = engine.submit(prompt_ids, params, kv_entry=kv_entry,
                                   trace=span.context(),
                                   session_id=session_id)
            req_id = schemas.completion_id()

            def queue_full_429(message):
                # one shape for every shed path (max_queue at submit AND the
                # later queue_timeout sheds): the gateway's retry policy
                # keys on the status + code. A shed request never used its
                # claimed (claim-once) handoff entry, so re-pin it first —
                # the gateway's retry against another decode upstream then
                # claims it instead of paying prefill again, exactly when
                # the pool is saturated.
                if kv_entry is not None:
                    try:
                        store.publish(str(xfer["handoff_id"]), kv_entry)
                    except Exception as e:  # noqa: BLE001 — the retry will
                        # degrade to a local prefill; leave a trace of where
                        # the entry went (silent loss is undebuggable)
                        self.handoff_meter.note_repin(False)
                        from llm_in_practise_tpu.obs.logging import get_logger

                        get_logger("serve.api").warning(
                            "could not re-pin shed handoff entry %s (%s: "
                            "%s); the retry will re-prefill",
                            xfer["handoff_id"], type(e).__name__, e)
                    else:
                        self.handoff_meter.note_repin(True)
                span.end(status=429, finish_reason="queue_full")
                return send_json(429, {"error": {
                    "message": message + " — retry later or against "
                               "another replica",
                    "type": "rate_limit_error",
                    "code": "queue_full",
                }})

            # paged KV admission: a prompt that can NEVER fit the page
            # pool (prompt pages + 1 > capacity) is a client error, not
            # load — 422 with the page math, synchronously at submit,
            # instead of aging into a generic queue-full 429
            if handle.finish_reason == "too_large":
                detail = engine.page_capacity_detail(len(prompt_ids))
                span.end(status=422, finish_reason="too_large")
                return send_json(422, {"error": {
                    "message": (
                        "prompt can never fit this replica's KV page "
                        f"pool: {detail['pages_needed']} pages needed "
                        f"(prompt {detail['prompt_tokens']} tokens + 1 "
                        f"at page_size {detail['page_size']}) vs "
                        f"{detail['pages_capacity']} pages capacity"),
                    "type": "invalid_request_error",
                    "code": "prompt_too_large",
                    "detail": detail,
                }})
            # admission control: a max_queue rejection is synchronous at
            # submit — return 429 before any stream starts (vLLM/ingress
            # backpressure parity; the gateway's retry policy keys on 429).
            # A queue_timeout shed happens later and surfaces through the
            # normal finish path below.
            if handle.finish_reason == "queue_full":
                return queue_full_429("engine queue full")

            from llm_in_practise_tpu.serve.engine import _FINISH, EngineDeadError

            def engine_dead_503():
                span.end(status=503, finish_reason="engine_dead")
                return send_json(503, {"error": {
                    "message": "engine is not running — request cannot be "
                               "served; retry against another replica",
                    "type": "internal_error",
                    "code": "engine_dead",
                }})

            if req.stream:
                # hold the 200 until the request survives admission: a
                # queue_timeout shed must surface as a retriable 429, not a
                # silently empty SSE stream. Blocks until the first token
                # (or finish) — exactly when the first data chunk could be
                # sent anyway, so client-visible TTFT is unchanged. The
                # wait is liveness-bounded (Request.next_item): a dead
                # engine is a 503, not a client hanging with no headers.
                try:
                    first = handle.next_item()
                except EngineDeadError:
                    return engine_dead_503()
                if first is _FINISH and handle.finish_reason == "queue_full":
                    return queue_full_429("request timed out waiting for a slot")

                def chunks():
                    # flush_s sums only the yield→resume gaps (the
                    # consumer formatting + writing each SSE chunk) —
                    # engine decode waits happen inside next_item() and
                    # must NOT count, or this span would shadow
                    # engine.decode in the per-phase breakdown
                    flush_s = 0.0
                    n_chunks = 0
                    try:
                        t = time.monotonic()
                        yield schemas.chat_completion_chunk(
                            req_id=req_id, model=req.model, delta=None
                        )
                        flush_s += time.monotonic() - t
                        n_chunks += 1
                        tokens, prev_text = [], ""

                        def stream_toks():
                            # mid-stream liveness: headers are out, so a dead
                            # engine propagates EngineDeadError into _sse's
                            # in-band error event instead of freezing the
                            # stream
                            tok = first
                            while tok is not _FINISH:
                                yield tok
                                tok = handle.next_item()
                        for tok in stream_toks():
                            tokens.append(tok)
                            text = self.tokenizer.decode(tokens)
                            delta, prev_text = text[len(prev_text):], text
                            if delta:
                                t = time.monotonic()
                                yield schemas.chat_completion_chunk(
                                    req_id=req_id, model=req.model, delta=delta
                                )
                                flush_s += time.monotonic() - t
                                n_chunks += 1
                        t = time.monotonic()
                        yield schemas.chat_completion_chunk(
                            req_id=req_id, model=req.model, delta=None,
                            finish_reason=handle.finish_reason or "stop",
                        )
                        flush_s += time.monotonic() - t
                        n_chunks += 1
                    finally:
                        # SSE write loop = the stream-flush phase; its span
                        # closes the trace's client-visible tail
                        self.tracer.record(
                            "api.stream_flush", span,
                            duration_s=flush_s,
                            chunks=n_chunks)
                        exc = sys.exc_info()[1]
                        # critical-path: the stream tail joins the
                        # request's /debug/requests breakdown and the
                        # aggregate counter. Per-request cp is written
                        # ONLY on a clean stream end: the generator then
                        # saw _FINISH, which the engine releases after
                        # its last cp write (_record_finished), so this
                        # thread owns the dict. A disconnect
                        # (GeneratorExit) mid-decode would race the
                        # engine's writers — skip cp there (the debug
                        # view documents stream_flush as possibly
                        # absent) and book the aggregate only, which
                        # goes through note_stream_flush ONLY —
                        # _record_finished skips this segment. The
                        # write is still a dict SWAP, not an insert:
                        # /debug/requests readers may be iterating the
                        # old object.
                        if exc is None:
                            handle.cp = {
                                **handle.cp,
                                "stream_flush":
                                    handle.cp.get("stream_flush", 0.0)
                                    + flush_s,
                            }
                        engine.stats.note_stream_flush(flush_s)
                        # headers already went out as 200, but the span
                        # must say how the stream actually ended: a mid-
                        # flight engine death surfaces as an in-band
                        # error event, a client disconnect as
                        # GeneratorExit — neither is a clean "stop"
                        if exc is None:
                            span.end(status=200,
                                     finish_reason=handle.finish_reason
                                     or "stop")
                        elif isinstance(exc, GeneratorExit):
                            span.end(status=200,
                                     finish_reason="client_disconnect",
                                     chunks_sent=n_chunks)
                        else:
                            span.end(status=200,
                                     finish_reason="stream_error",
                                     error=type(exc).__name__,
                                     chunks_sent=n_chunks)
                return send_stream(chunks())

            try:
                out_ids = handle.result()
            except EngineDeadError:
                return engine_dead_503()
            if handle.finish_reason == "queue_full":  # queue_timeout shed
                return queue_full_429("request timed out waiting for a slot")
            text = self.tokenizer.decode(out_ids)
            usage = schemas.Usage(len(prompt_ids), len(out_ids))
            tool_calls = None
            if (constraint_kind == "tool_call"
                    and handle.finish_reason == "stop"):
                # the grammar guarantees {"name": …, "arguments": {…}};
                # re-shape it into the OpenAI tool_calls wire format
                # (a "length"-truncated call stays raw content — the
                # client sees exactly what was generated)
                try:
                    call = json.loads(text)
                    tool_calls = [schemas.tool_call_entry(
                        call["name"],
                        json.dumps(call["arguments"],
                                   separators=(",", ":")))]
                except (ValueError, KeyError, TypeError):
                    tool_calls = None
            span.end(status=200, finish_reason=handle.finish_reason or "stop",
                     completion_tokens=len(out_ids))
            return send_json(200, schemas.chat_completion_response(
                req_id=req_id, model=req.model, text=text,
                finish_reason=handle.finish_reason or "stop", usage=usage,
                tool_calls=tool_calls,
            ))
        except BaseException as e:
            # a handler exception (kv upload on submit, tokenizer
            # decode, ...) surfaces as do_POST's catch-all 500 — the
            # span must record the failure, not leak unrecorded
            span.end(status=500, finish_reason="error",
                     error=type(e).__name__)
            raise

    def _build_registry(self) -> Registry:
        """Every family reads the live engine/meter counters at scrape
        time — no double bookkeeping, one canonical renderer (TYPE
        header per family, strict label escaping; pinned by the
        exposition-parser tests)."""
        reg = Registry()
        eng = self.engine
        s = eng.stats
        # build identity (obs/buildinfo.py): the fleet collector keys
        # its per-version scoreboard and canary verdict on these labels
        from llm_in_practise_tpu.obs.buildinfo import register_build_info

        register_build_info(reg, {
            "server": "api",
            "model": self.model_name,
            "role": self.role,
            "max_slots": eng.max_slots,
            "cache_len": eng.cache_len,
            "kv_layout": "paged" if eng.paged is not None else "dense",
            "speculative_k": getattr(eng, "speculative_k", 0),
            "decode_steps": getattr(eng, "decode_steps", 1),
            "adapters": sorted(self.adapters),
        })
        reg.counter_func("llm_requests_total",
                         lambda: s.requests_total,
                         "requests submitted to the engine")
        reg.counter_func("llm_tokens_generated_total",
                         lambda: s.tokens_generated_total,
                         "output tokens emitted")
        reg.gauge_func("llm_num_requests_waiting", lambda: s.queue_depth,
                       "requests queued for a slot")
        reg.gauge_func("llm_num_requests_running", lambda: s.active_slots,
                       "requests occupying slots")
        reg.counter_func("llm_requests_shed_total",
                         lambda: s.requests_shed,
                         "requests shed by admission control")
        # dispatch accounting (docs/perf.md Findings 5/16/17): on a
        # dispatch-taxed host, dispatches/step IS the latency model —
        # the fused mixed step's win shows up here as ~1.0 under
        # simultaneous prefill+decode (it was 2 before)
        dm = eng.dispatch_meter
        reg.counter_func("llm_dispatches_total", lambda: dm.total,
                         "jitted engine-program launches")
        reg.gauge_func("llm_dispatches_per_step",
                       lambda: dm.mean_per_step,
                       "rolling mean dispatches per engine step")
        reg.counter_func("llm_mixed_blocks_total",
                         lambda: eng.mixed_blocks,
                         "fused prefill+decode dispatches")
        # device plane (obs/cost.py + DispatchMeter.note_phase): live
        # per-phase MFU / HBM-bandwidth-utilization / tokens-per-
        # dispatch — the compute-vs-bandwidth-bound dial. Phases appear
        # as they first dispatch; without a cost model (uncovered model
        # family) the utilization gauges render no samples but the
        # token gauge still does.
        def _phase_gauge(field):
            def read():
                return [({"phase": phase}, snap[field])
                        for phase, snap in dm.phase_snapshot().items()
                        if snap.get(field) is not None]
            return read

        reg.gauge_func("llm_dispatch_mfu", _phase_gauge("mfu"),
                       "rolling per-dispatch model FLOP utilization "
                       "(useful FLOPs / wall time / chip peak)")
        reg.gauge_func("llm_dispatch_hbm_bw_util",
                       _phase_gauge("hbm_bw_util"),
                       "rolling per-dispatch HBM bandwidth utilization "
                       "(weights + KV traffic / wall time / peak BW)")
        reg.gauge_func("llm_dispatch_tokens_per_dispatch",
                       _phase_gauge("tokens_per_dispatch"),
                       "rolling mean tokens processed per dispatch")
        # compile telemetry (obs/prof.py CompileMeter over every jitted
        # engine program): a serving-time recompile is a latency cliff
        # this pair turns into an alertable counter
        cmeter = eng.compile_meter
        reg.counter_func("llm_compile_events_total",
                         lambda: cmeter.compile_events,
                         "jit executable-cache misses paid by the "
                         "serving thread")
        reg.counter_func("llm_compile_seconds_total",
                         lambda: cmeter.compile_seconds,
                         "cumulative seconds stalled in jit "
                         "trace/compile (persistent-cache loads "
                         "included)")
        # device memory telemetry — read LIVE at scrape; backends that
        # report no memory_stats (CPU, the axon tunnel) render the
        # family with no samples (fail-open, bench.py:450 case)
        def _hbm():
            from llm_in_practise_tpu.obs.cost import device_memory_stats

            stats = device_memory_stats()
            return [({"kind": kind}, value)
                    for kind, value in (("in_use",
                                         stats.get("bytes_in_use")),
                                        ("peak",
                                         stats.get("peak_bytes_in_use")),
                                        ("limit",
                                         stats.get("bytes_limit")))
                    if value is not None]

        reg.gauge_func("llm_device_hbm_bytes", _hbm,
                       "device memory from device.memory_stats(): "
                       "bytes in use / peak / limit")
        # HBM ownership ledger (obs/hbm.py, ISSUE 19): per-owner
        # attribution of the bytes the aggregate family above only
        # totals, plus the reconciliation residual between the two
        register_hbm_ledger(reg)
        # tensor-parallel plane (docs/serving-tp.md): the mesh extent
        # and the analytic per-chip collective attribution — wire bytes
        # of the row-parallel activation all-reduces and the
        # lower-bound seconds they cost at datasheet ICI bandwidth.
        # Registered unconditionally (zeros at tp=1) so dashboards and
        # the metric-docs census see one stable family set.
        reg.gauge_func("llm_tp_size", lambda: eng.tp,
                       "tensor-parallel extent of the serving mesh's "
                       "model axis (1 = single chip)")
        reg.counter_func("llm_collective_bytes_total",
                         lambda: eng.collective_bytes_total,
                         "analytic per-chip ICI wire bytes of the "
                         "row-parallel activation all-reduces "
                         "(halved under --tp-quantized-collectives)")
        reg.counter_func("llm_collective_seconds_total",
                         lambda: eng.collective_seconds_total,
                         "analytic lower-bound seconds those bytes "
                         "cost at datasheet ICI bandwidth (XLA "
                         "overlaps collectives with compute)")
        # SLO goodput (obs/meter.py GoodputMeter): tokens priced by
        # whether their request met the TTFT/TPOT SLOs; zero until
        # thresholds are configured (engine ttft_slo_s/tpot_slo_s)
        from llm_in_practise_tpu.obs.meter import register_goodput

        register_goodput(reg, s.goodput)
        # per-role latency labels (disaggregated serving): a prefill
        # replica's "TTFT" is KV-ready time, a decode replica's TPOT is
        # the interference-free number the split exists for. Plain
        # (unlabeled) series are kept for role=both so existing
        # dashboards/scrapes see the same names. Bucketed histograms
        # (was: full-history summaries) — PromQL quantiles come from
        # histogram_quantile() over the _bucket series.
        role_labels = {} if self.role == "both" else {"role": self.role}

        # warm-vs-cold TTFT attribution (ISSUE 11 satellite): the plain
        # series stays (dashboards/tests key on it); the cache-labeled
        # children split the SAME observations by the prefix-/handoff-
        # hit outcome at admission, so the warm-vs-cold win (perf.md
        # Finding 16's 1783→176 ms pair) is a live PromQL ratio
        def _ttft():
            out = [(role_labels, s.ttft)]
            out.extend(({**role_labels, "cache": k}, acc)
                       for k, acc in sorted(s.ttft_by_cache.items()))
            return out

        reg.histogram_func("llm_ttft_seconds", _ttft,
                           "time to first token (prefill replicas: "
                           "KV-claimable time); cache-labeled children "
                           "split by admission prefix/handoff outcome")
        reg.histogram_func("llm_tpot_seconds",
                           lambda: [(role_labels, s.tpot)],
                           "mean time per output token after the first")
        # host-gap plane (obs/steptrace.py, ISSUE 11): the per-step
        # engine-loop timeline — where the host spends the time between
        # dispatches, and the live device-busy/host-gap dial the
        # ROADMAP item-3 overlap refactor must move. All reads go
        # through the recorder's atomically swapped snapshot (single-
        # writer convention; a scrape never mixes two steps' totals).
        stp = eng.steptrace

        def _host_gap():
            snap = stp.snapshot()
            return [({"activity": a}, v)
                    for a, v in sorted(snap["host_seconds"].items())]

        reg.counter_func("llm_host_gap_seconds_total", _host_gap,
                         "engine-thread seconds between dispatches, by "
                         "host activity (queue_drain/admit/plan/"
                         "index_build/draft_propose/grammar_compile/"
                         "grammar_mask/dispatch_wait/sample_commit/"
                         "publish/other)")
        reg.counter_func(
            "llm_step_wall_seconds_total",
            lambda: stp.snapshot()["step_wall_seconds_total"],
            "cumulative engine step() wall seconds (non-idle steps)")
        reg.counter_func(
            "llm_engine_steps_total",
            lambda: stp.snapshot()["steps"],
            "non-idle engine step() iterations recorded")
        reg.gauge_func(
            "llm_device_busy_fraction",
            lambda: stp.snapshot()["device_busy_fraction"],
            "rolling fraction of step wall time the device was busy "
            "(forced dispatch windows / step wall, last 50 steps)")
        reg.gauge_func(
            "llm_host_gap_fraction",
            lambda: stp.snapshot()["host_gap_fraction"],
            "rolling fraction of step wall time the chip waited on "
            "Python (1 - device_busy; the item-3 overlap target)")
        # per-request critical-path aggregate: every finished request's
        # wall time decomposed into segments (GET /debug/requests has
        # the per-request view)
        reg.counter_func(
            "llm_request_critical_path_seconds_total",
            lambda: [({"segment": seg}, v) for seg, v in
                     sorted(s.critical_path_snapshot().items())],
            "finished requests' wall seconds by critical-path segment")
        # disaggregation accounting: published/claimed say the handoff
        # plane works; lost + local re-prefills say how often the decode
        # pool fell back to doing prefill itself (the llm-d health signal)
        hm = self.handoff_meter
        reg.counter_func(
            "llm_handoff_total",
            lambda: [({"event": "published"}, eng.handoff_published),
                     ({"event": "publish_failed"},
                      eng.handoff_publish_failed),
                     ({"event": "claimed"}, hm.claimed),
                     ({"event": "kv_admitted"}, eng.kv_admitted),
                     ({"event": "kv_rejected"}, eng.kv_rejected),
                     ({"event": "repinned"}, hm.repinned),
                     ({"event": "repin_failed"}, hm.repin_failed)],
            "disaggregated KV handoff events")
        reg.counter_func("llm_handoff_lost_total", lambda: hm.lost,
                         "handoff ids that resolved to no entry")
        reg.counter_func("llm_local_prefills_total",
                         lambda: eng.local_prefills,
                         "prefills a decode-role replica ran itself")
        # session-native serving (serve/sessions.py, ISSUE 17): read the
        # store LIVE at scrape — registered unconditionally so the
        # metric-docs census and dashboards see one stable family set;
        # no store → families present, no samples
        def _sess(reader):
            def read():
                st = getattr(eng, "session_store", None)
                return [] if st is None else reader(st.counters())
            return read

        reg.gauge_func("llm_sessions_active",
                       _sess(lambda c: [({}, c["active"])]),
                       "conversations with server-held KV pinned on "
                       "this replica")
        reg.gauge_func("llm_session_pinned_pages",
                       _sess(lambda c: [({}, c["pinned_pages"])]),
                       "KV pages refcount-pinned under session handles")
        reg.counter_func(
            "llm_session_turns_total",
            _sess(lambda c: [({"cache": k}, v)
                             for k, v in sorted(c["turns"].items())]),
            "finished session turns by admission cache outcome "
            "(hit / partial / cold)")
        reg.counter_func(
            "llm_session_evictions_total",
            _sess(lambda c: [({"reason": k}, v)
                             for k, v in sorted(c["evictions"].items())]),
            "session pin evictions (ttl / pressure / capacity)")
        reg.counter_func(
            "llm_session_pulls_total",
            _sess(lambda c: [({"event": k}, v)
                             for k, v in sorted(c["pulls"].items())]),
            "fleet warm-path events (published / publish_failed / "
            "claimed / lost)")
        # read eng.prefix_cache LIVE at scrape time: benches and serving
        # setups attach/replace the cache after server construction
        # (e.g. tools/tpu_serve_qwen3_bench.py), and the pre-registry
        # exposition tracked that; no cache → family present, no samples
        def _pc(attr):
            def read():
                pc = eng.prefix_cache
                return [] if pc is None else [({}, getattr(pc, attr))]
            return read

        reg.counter_func("llm_prefix_cache_hits_total", _pc("hits"))
        reg.counter_func("llm_prefix_cache_full_hits_total",
                         _pc("full_hits"))
        reg.counter_func("llm_prefix_cache_misses_total", _pc("misses"))
        reg.counter_func("llm_prefix_cache_tokens_saved_total",
                         _pc("tokens_saved"))
        reg.gauge_func("llm_prefix_cache_tokens", _pc("cached_tokens"))
        if getattr(eng, "paged", None) is not None:
            # paged KV plane (docs/paged-kv.md): occupancy is THE
            # admission signal — free pages are admittable tokens, the
            # shared count is prefix reuse working, and preemptions
            # mean the pool is undersized for the offered load
            pool = eng.paged.pool

            def _pages():
                free = pool.free_pages
                shared = pool.shared_pages
                return [({"state": "free"}, free),
                        ({"state": "used"}, pool.capacity - free),
                        ({"state": "shared"}, shared)]

            reg.gauge_func("llm_kv_pages", _pages,
                           "page-pool occupancy by state (shared = "
                           "refcount > 1, also counted in used)")
            reg.gauge_func("llm_kv_pages_total", lambda: pool.capacity,
                           "allocatable pages in the pool")
            reg.gauge_func("llm_kv_page_size",
                           lambda: pool.page_size,
                           "tokens per KV page")
            reg.gauge_func(
                "llm_kv_page_fragmentation",
                lambda: [({}, eng.debug_kv().get("fragmentation", 0.0))],
                "allocated-but-unfilled token slack of slot-mapped "
                "pages (contiguous layouts waste cache_len - context "
                "per slot; paged keeps this under one page)")
            reg.counter_func("llm_kv_preemptions_total",
                             lambda: eng.preemptions,
                             "slots preempted (recompute-resume) under "
                             "page-pool pressure")
            reg.counter_func("llm_kv_rejected_too_large_total",
                             lambda: eng.rejected_too_large,
                             "prompts refused at submit: pages needed "
                             "exceed pool capacity (HTTP 422)")
        if eng.speculative_k is not None:
            # speculation plane (ISSUE 9): proposed/accepted drafted
            # tokens, fused verify dispatches, the tokens those
            # dispatches committed (accepted + bonus + extension), and
            # a ready-made acceptance-rate gauge — the live "is the
            # spec bet paying" dial next to llm_dispatch_hbm_bw_util
            reg.counter_func("llm_spec_proposed_total",
                             lambda: eng.spec_proposed,
                             "drafted tokens submitted to verify")
            reg.counter_func("llm_spec_accepted_total",
                             lambda: eng.spec_accepted,
                             "drafted tokens the verify accepted")
            reg.counter_func("llm_spec_rounds_total",
                             lambda: eng.spec_rounds,
                             "fused spec-verify dispatches issued")
            reg.counter_func("llm_spec_round_tokens_total",
                             lambda: eng.spec_round_tokens,
                             "tokens committed by spec dispatches "
                             "(accepted + bonus + block extension)")

            def _acceptance():
                proposed = eng.spec_proposed     # snapshot: torn reads
                accepted = eng.spec_accepted     # stay <= 1.0
                if proposed <= 0:
                    return []
                return [({}, min(accepted / proposed, 1.0))]

            reg.gauge_func("llm_spec_acceptance_rate", _acceptance,
                           "lifetime accepted/proposed drafted tokens "
                           "(no samples until the first draft)")
        if getattr(eng, "decode_steps", 1) > 1:
            # operators tuning --decode-steps need to see whether blocks
            # actually run (the gate silently falls back to single-step)
            reg.counter_func("llm_multi_decode_blocks_total",
                             lambda: eng.multi_blocks)
        # structured output (serve/constrain.py, ISSUE 12): registered
        # unconditionally — zeros until the first constrained request,
        # so dashboards and the metric-docs census see one stable set
        sc = self._structured_counts
        reg.counter_func(
            "llm_structured_requests_total",
            lambda: [({"kind": k}, v) for k, v in sorted(sc.items())],
            "requests that carried a grammar constraint, by kind "
            "(json_object / json_schema / tool_call)")
        reg.counter_func(
            "llm_grammar_mask_seconds_total",
            lambda: eng.grammar_mask_seconds_total,
            "engine-thread seconds staging grammar logit masks "
            "(includes lazy automaton-state compiles; the steptrace "
            "grammar_compile/grammar_mask activities split the two)")
        reg.counter_func(
            "llm_spec_grammar_rejects_total",
            lambda: eng.spec_grammar_rejects,
            "drafted tokens rejected by the grammar during fused "
            "spec-round mask staging (the on-device acceptance "
            "cumprod truncates at each)")
        # multi-LoRA plane (serve/multi_lora.py, ISSUE 15): read the
        # adapter registries LIVE at scrape — the base engine's (when it
        # serves adapters) plus any distinct registry behind the
        # adapters= handles (the build_adapter_engines shim's shared
        # engine). Registered unconditionally; no registry → families
        # present, no samples.
        def _adapter_regs():
            seen = {}
            for e in (eng, *self.adapters.values()):
                r = getattr(e, "adapter_registry", None)
                if r is not None:
                    seen[id(r)] = r
            return list(seen.values())

        def _adapter_sum(key):
            def read():
                regs = _adapter_regs()
                if not regs:
                    return []
                return [({}, sum(r.stats()[key] for r in regs))]
            return read

        reg.gauge_func("llm_adapters_loaded", _adapter_sum("loaded"),
                       "LoRA adapters resident in the registry banks")
        reg.gauge_func("llm_adapter_bytes", _adapter_sum("bytes_loaded"),
                       "HBM bytes held by loaded adapter factor rows "
                       "(f32 payload at the padded bucket rank)")
        reg.counter_func("llm_adapter_swap_seconds_total",
                         _adapter_sum("swap_seconds_total"),
                         "cumulative seconds spent hot-loading adapter "
                         "checkpoints into the banks")
        reg.counter_func("llm_adapter_evictions_total",
                         _adapter_sum("evictions_total"),
                         "adapter rows evicted under the registry byte "
                         "budget (refcount-0 LRU only)")

        def _tenant_tokens():
            out: dict[str, int] = {}
            for r in _adapter_regs():
                for name, n in r.stats()["tenant_tokens"].items():
                    out[name] = out.get(name, 0) + n
            return [({"adapter": name}, n)
                    for name, n in sorted(out.items())]

        reg.counter_func("llm_tenant_tokens_total", _tenant_tokens,
                         "output tokens generated per adapter tenant "
                         "(finished requests; base-model traffic is "
                         "not labeled)")
        return reg

    def metrics_text(self) -> str:
        return self.registry.render()

    # --- HTTP plumbing -------------------------------------------------------

    def make_handler(self):
        server = self

        class Handler(JsonHandler):
            def _sse(self, events):
                self._responded = True
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                try:
                    try:
                        for event in events:
                            payload = f"data: {json.dumps(event)}\n\n".encode()
                            self.wfile.write(payload)
                            self.wfile.flush()
                    except Exception as e:  # noqa: BLE001 — headers are out;
                        # surface the fault as an SSE error event, then DONE.
                        err = {"error": {"message": f"{type(e).__name__}: {e}",
                                         "type": "internal_error"}}
                        self.wfile.write(f"data: {json.dumps(err)}\n\n".encode())
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-stream

            def do_GET(self):
                if serve_obs_get(self, server.metrics_text,
                                 server.tracer):
                    return
                try:
                    if self.path == "/debug/kv":
                        # page-pool occupancy / sharing / fragmentation
                        # / block-table sizes (docs/paged-kv.md); the
                        # contiguous layout reports its reservation
                        return self._json(200, server.engine.debug_kv())
                    if self.path == "/debug/requests":
                        # recent-finished ring with per-request
                        # critical-path breakdowns (ISSUE 11; see
                        # docs/observability.md "Host timeline")
                        return self._json(
                            200, server.engine.debug_requests())
                    if self.path == "/debug/sessions":
                        # server-held conversation pins + fleet pull
                        # accounting (serve/sessions.py, ISSUE 17)
                        return self._json(
                            200, server.engine.debug_sessions())
                    if self.path == "/debug/hbm":
                        # HBM ownership tree + per-account high-water
                        # marks + reconciliation residual (obs/hbm.py,
                        # docs/observability.md "Memory plane")
                        return self._json(
                            200, get_ledger().debug_tree())
                    if self.path == "/v1/models":
                        return self._json(200, {
                            "object": "list",
                            "data": [{
                                "id": name,
                                "object": "model",
                                "owned_by": "llm-in-practise-tpu",
                            } for name in (server.model_name,
                                           *server.adapters)],
                        })
                    if self.path in ("/", "/chat"):
                        return self._text(
                            200, webui_html(server.model_name).encode(),
                            "text/html; charset=utf-8",
                        )
                except Exception as e:  # noqa: BLE001 — a GET fault must
                    # answer the client, not drop the connection
                    return self._json(500, {"error": {
                        "message": f"{type(e).__name__}: {e}",
                        "type": "internal_error"}})
                return self._json(404, {"error": {"message": "not found"}})

            def do_POST(self):
                if self.path not in ("/v1/chat/completions",
                                     "/v1/embeddings",
                                     "/internal/handoff/prefill",
                                     "/debug/profile"):
                    return self._json(404, {"error": {"message": "not found"}})
                body, err = self._read_json()
                if err:
                    return self._json(400, err)
                if serve_obs_post(self, body):
                    return None
                # cross-hop trace continuity: the gateway (or any
                # client) propagates a traceparent header; spans minted
                # here join that trace instead of starting a new one
                ctx = parse_traceparent(self.headers.get("traceparent"))
                # session-native serving (serve/sessions.py): the
                # conversation handle rides the header (gateway/client)
                # or the body field — the header wins on conflict, the
                # same precedence rule traceparent follows
                sid = self.headers.get("X-Session-ID")
                try:
                    if self.path == "/v1/embeddings":
                        return server.handle_embeddings(body, self._json)
                    if self.path == "/internal/handoff/prefill":
                        return server.handle_prefill(body, self._json,
                                                     trace=ctx)
                    return server.handle_chat(body, self._json, self._sse,
                                              trace=ctx, session_id=sid)
                except Exception as e:  # noqa: BLE001 — a handler fault must
                    # still answer the client, not drop the connection. If a
                    # response already went out (SSE underway), sending a
                    # second status line would corrupt the stream — _sse has
                    # its own in-band error path; just stop.
                    if self._responded:
                        return None
                    return self._json(500, {"error": {
                        "message": f"{type(e).__name__}: {e}",
                        "type": "internal_error",
                    }})

        return Handler

    def serve(self, host: str = "0.0.0.0", port: int = 8000, *, background: bool = False):
        """Start engine loop + HTTP server. Returns the bound port."""
        for eng in (self.engine, *self.adapters.values()):
            if eng._thread is None:
                eng.start()

        # The stdlib default listen backlog is 5: at a few hundred
        # concurrent connects the SYN queue overflows and clients see
        # ECONNRESET (measured: 101/512 requests lost at concurrency 256
        # before this). Size it for the benchmark ladder's worst burst.
        class _Server(ThreadingHTTPServer):
            request_queue_size = 1024
            daemon_threads = True

        self._httpd = _Server((host, port), self.make_handler())
        bound = self._httpd.server_address[1]
        if background:
            threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        else:
            self._httpd.serve_forever()
        return bound

    def shutdown(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        self.engine.stop()
        for eng in self.adapters.values():
            eng.stop()


def webui_html(model_name: str) -> str:
    """Minimal streaming chat page — the reference's Gradio web UIs
    (``Scripts/inference/05-…-webui-infr.py``, streaming ``06-…:52-75``)
    without the Gradio dependency: vanilla HTML + fetch over the SSE
    endpoint, incremental delta rendering, multi-turn history."""
    name_html = html.escape(model_name)
    name_js = json.dumps(model_name)  # JS string literal, quotes included
    return """<!doctype html>
<meta charset="utf-8"><title>chat — """ + name_html + """</title>
<style>
 body{font-family:system-ui,sans-serif;max-width:720px;margin:2rem auto;padding:0 1rem}
 #log{border:1px solid #ccc;border-radius:8px;padding:1rem;min-height:300px;
      white-space:pre-wrap}
 .u{color:#036;font-weight:600}.a{color:#222}
 form{display:flex;gap:.5rem;margin-top:1rem}
 input{flex:1;padding:.5rem;font-size:1rem}
 button{padding:.5rem 1rem}
</style>
<h2>""" + name_html + """</h2>
<div id=log></div>
<form id=f><input id=q autocomplete=off placeholder="message…">
<button>send</button></form>
<script>
const log=document.getElementById('log'),f=document.getElementById('f'),
      q=document.getElementById('q'),history=[];
f.onsubmit=async e=>{
  e.preventDefault();
  const text=q.value.trim(); if(!text)return; q.value='';
  history.push({role:'user',content:text});
  log.append(Object.assign(document.createElement('div'),
    {className:'u',textContent:'you: '+text}));
  const out=Object.assign(document.createElement('div'),
    {className:'a',textContent:'bot: '});
  log.append(out);
  const r=await fetch('/v1/chat/completions',{method:'POST',
    headers:{'Content-Type':'application/json'},
    body:JSON.stringify({model:""" + name_js + """,messages:history,
                         stream:true,max_tokens:256})});
  const reader=r.body.getReader(),dec=new TextDecoder();
  let buf='',answer='';
  for(;;){
    const {done,value}=await reader.read(); if(done)break;
    buf+=dec.decode(value,{stream:true});
    let i;
    while((i=buf.indexOf('\\n\\n'))>=0){
      const line=buf.slice(0,i).trim(); buf=buf.slice(i+2);
      if(!line.startsWith('data:'))continue;
      const data=line.slice(5).trim();
      if(data==='[DONE]')continue;
      const delta=JSON.parse(data).choices?.[0]?.delta?.content;
      if(delta){answer+=delta;out.textContent='bot: '+answer;}
    }
  }
  history.push({role:'assistant',content:answer});
};
</script>"""

"""Session-native serving — server-held conversation KV and the
fleet-wide warm path (ISSUE 17, ROADMAP item 2).

Multi-turn conversations are first-class here, not an accident of the
prefix cache's LRU order:

- :class:`SessionStore` (engine side): when a turn finishes, the
  conversation's full KV pages stay **refcount-pinned** under the
  session handle instead of merely LRU-registered in the paged COW
  index — a follow-up turn page-hits by construction, however much
  unrelated traffic ran in between. Pins are page-granular and yield
  to active slots under pool pressure (newest pages first, so the
  surviving pin is still a valid chain prefix), expire by TTL, and are
  never taken from a live slot (eviction only drops the session's own
  references — an in-flight stream's block-table refs are untouched).
- :class:`ConsistentHashRing` (gateway side, consumed by
  ``gateway.HashRingRouter``): sessions map to replicas by consistent
  hashing keyed on (session id | prefix hash | adapter), so replica
  join/leave remaps only ~1/N sessions instead of rehashing the world.
- the fleet miss path: each finished turn is also published —
  device→host copy + put on a background thread — into the kv-pool's
  pinned handoff namespace under :func:`session_hid`, carrying its
  token ids on the wire (``HostEntry.token_ids``). When the ring
  rebalances or a replica dies, the NEW owner claims the entry,
  validates the token prefix against the incoming prompt, and admits
  it through the engine's partial-prefix path; a lost entry degrades
  to local re-prefill (counted, never a 5xx). No topology change makes
  a session unservable.

The reference platform gets the single-replica half of this from vLLM
automatic prefix caching and the placement half from llm-d's
cache-aware router (SURVEY §6); this module joins the two so the
1783 ms → 176 ms cold/warm TTFT pair (PR 11's ``llm_ttft_seconds``
labels) is the fleet default, not a same-replica trick.

Lifecycle of one session (paged engine, fleet mode)::

    turn 1  gateway ring → replica A → cold prefill → finish:
            pages pinned under sid, entry published to the pool
    turn 2  ring → A → page-index chain hit on the pinned pages
            (warm TTFT), finish re-pins the longer chain + republishes
    A dies  ring rebuild remaps sid to B (~1/N of sessions move)
    turn 3  B has no pages → claims ``session_hid(sid)`` from the
            pool, token-prefix validates, scatters the rows, prefills
            only the new turn's suffix — warm again
    idle    TTL sweep drops the pin; the pool entry expires on its own
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import queue
import threading
import time
from collections import OrderedDict

from llm_in_practise_tpu.obs.hbm import get_ledger
from llm_in_practise_tpu.obs.logging import get_logger


def session_hid(session_id: str) -> str:
    """Handoff-namespace key for a client-chosen session id.

    Client ids are arbitrary strings (headers, JSON fields) — hashing
    keeps the pool-server key set fixed-width and free of separator
    collisions with the ``__handoff__/`` namespace convention."""
    digest = hashlib.sha256(str(session_id).encode()).hexdigest()
    return "session-" + digest[:32]


def _ring_hash(s: str) -> int:
    """64-bit stable point on the ring (sha256-derived — ``hash()`` is
    per-process salted, and the whole point is that every gateway
    restart maps sessions to the SAME replicas)."""
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


class ConsistentHashRing:
    """Classic consistent hashing with virtual nodes.

    Each node contributes ``vnodes`` points; a key is owned by the
    first node point at-or-after its hash (wrapping). Adding or
    removing one node moves only the keys in that node's arcs —
    ~1/N of the keyspace — which is the whole reason the gateway's
    session affinity uses a ring instead of a rehash-the-world map.

    Immutable after construction: topology changes build a NEW ring
    (``HashRingRouter`` swaps the reference under its lock), so reads
    need no synchronization.
    """

    def __init__(self, nodes, *, vnodes: int = 64):
        self.vnodes = int(vnodes)
        # preserve caller order, drop duplicates (a duplicate node would
        # double its arc share silently)
        self._nodes = list(dict.fromkeys(nodes))
        points = []
        for node in self._nodes:
            for i in range(self.vnodes):
                points.append((_ring_hash(f"{node}#{i}"), node))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [n for _, n in points]

    def __len__(self) -> int:
        return len(self._nodes)

    @property
    def nodes(self) -> list:
        return list(self._nodes)

    def owners(self, key, n: int = 1) -> list:
        """The first ``n`` DISTINCT nodes clockwise from ``key``'s
        point — ``owners(key, 2)`` is the two-choice set bounded-load
        routing overflows into; walking further is the natural
        fallback order when owners are cooling down."""
        if not self._hashes or n <= 0:
            return []
        start = bisect.bisect_right(self._hashes, _ring_hash(str(key)))
        out: list = []
        for j in range(len(self._owners)):
            node = self._owners[(start + j) % len(self._owners)]
            if node not in out:
                out.append(node)
                if len(out) >= n:
                    break
        return out

    def owner(self, key):
        got = self.owners(key, 1)
        return got[0] if got else None


@dataclasses.dataclass
class _Session:
    """One conversation's server-held state (all fields guarded by the
    store's lock)."""

    sid: str
    token_ids: list          # full conversation history (prompt+output)
    pages: list              # pinned physical pages (chain prefix order)
    adapter: str | None = None
    turns: int = 0
    created: float = 0.0
    last_used: float = 0.0


class SessionStore:
    """Server-held conversation KV: pin-across-turns + fleet publish.

    Attach to ONE engine (:meth:`attach`); the store chains itself into
    the page pool's ``reclaim`` hook AFTER the COW index, so under
    admission pressure cold shared prefixes go first and session pins
    yield next — active slots always win, and a session degrades to a
    shorter warm prefix instead of blocking admission.

    Thread contract: ``note_finish``/``take_pending`` run on the engine
    thread; ``adopt``/``known`` on HTTP handler threads; the publisher
    thread drains ``_pub_q``; ``/metrics`` and ``/debug/sessions`` read
    under the same lock. Lock order is store lock → pool lock, never
    the reverse (the pool calls :meth:`reclaim_pages` OUTSIDE its own
    lock by the ``PagePool.reclaim`` contract).
    """

    def __init__(self, *, ttl_s: float = 600.0, max_sessions: int = 1024,
                 clock=None):
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        if max_sessions < 1:
            raise ValueError(
                f"max_sessions must be >= 1, got {max_sessions}")
        self.ttl_s = float(ttl_s)
        self.max_sessions = int(max_sessions)
        self._clock = clock or time.monotonic
        self._log = get_logger("serve.sessions")
        self._lock = threading.Lock()
        # LRU-ordered by last touch (OrderedDict re-insert on finish)
        self._sessions: "OrderedDict[str, _Session]" = OrderedDict()  # guarded-by: _lock
        # fleet entries claimed for a session but not yet consumed by
        # admission (consume-once, superseded by any local finish)
        self._pending: dict = {}  # guarded-by: _lock
        # per-outcome finished turns (llm_session_turns_total{cache=…})
        self.turns_by_cache = {"hit": 0, "partial": 0, "cold": 0}  # guarded-by: _lock
        # pin-eviction events (llm_session_evictions_total{reason=…})
        self.evictions = {"ttl": 0, "pressure": 0, "capacity": 0}  # guarded-by: _lock
        # fleet-path events (llm_session_pulls_total{event=…})
        self.pulls = {"published": 0, "publish_failed": 0,
                      "claimed": 0, "lost": 0}  # guarded-by: _lock
        # engine wiring (attach): None until attached / contiguous
        self.engine = None
        self.pool = None
        self.page_size = 0
        self._page_bytes = 0  # set by attach() from the paged pool's rate
        self.handoff = None
        self._pub_q: "queue.Queue" = queue.Queue()
        self._pub_thread: threading.Thread | None = None

    # --- wiring --------------------------------------------------------------

    def attach(self, engine) -> None:
        """Bind to ``engine``: take its page pool (paged layouts) and
        handoff store, and chain the pool's reclaim hook — prior hook
        (the COW index's ``evict_pages``) first, session pins for the
        remaining shortfall."""
        self.engine = engine
        self.handoff = getattr(engine, "handoff", None)
        paged = getattr(engine, "paged", None)
        if paged is None:
            # contiguous engines: turn/TTL bookkeeping only — there are
            # no pages to pin; warm turns come from the row-based
            # PrefixCache's LRU, and the fleet path still works through
            # adopt/take_pending on the row entries.
            return
        self.pool = paged.pool
        self.page_size = paged.page_size
        self._page_bytes = paged.page_bytes
        prior = self.pool.reclaim

        def _reclaim(n: int, _prior=prior) -> int:
            freed = _prior(n) if _prior is not None else 0
            if freed < n:
                freed += self.reclaim_pages(n - freed)
            return freed

        self.pool.reclaim = _reclaim

    def _book_pins(self, delta_pages: int) -> None:
        """Move ledger account ``session_pins`` by ``delta_pages`` at
        the pool's page byte rate. A VIEW account: the bytes belong to
        ``kv_pool.pages`` — this re-attributes them to the sessions
        holding the refs, it never adds to the device sum."""
        if delta_pages and self._page_bytes:
            get_ledger().book("session_pins",
                              delta_pages * self._page_bytes)

    def known(self, sid: str) -> bool:
        """Whether this replica already holds state for ``sid`` (pinned
        session or an unconsumed fleet pull) — the API layer claims
        from the pool only when this is False."""
        with self._lock:
            return sid in self._sessions or sid in self._pending

    def note_finish(self, sid: str, token_ids, pages, *,
                    adapter: str | None = None,
                    cache_outcome: str | None = None) -> None:
        """A turn of ``sid`` finished: pin ``pages`` (the conversation's
        full-page chain, still mapped by the finishing slot) under the
        session, replacing any previous pin. Runs on the engine thread
        BEFORE the slot releases its own references, so the pages can
        never hit refcount zero in between."""
        now = self._clock()
        release: list = []
        with self._lock:
            if self.pool is not None and pages:
                self.pool.share(pages)
            sess = self._sessions.pop(sid, None)
            if sess is None:
                sess = _Session(sid=sid, token_ids=[], pages=[],
                                created=now)
            release.extend(sess.pages)
            sess.token_ids = list(map(int, token_ids))
            sess.pages = list(pages)
            sess.adapter = adapter
            sess.turns += 1
            sess.last_used = now
            self._sessions[sid] = sess
            # a local finish supersedes any unconsumed fleet pull — the
            # pin is strictly fresher than the claimed entry
            self._pending.pop(sid, None)
            if cache_outcome in self.turns_by_cache:
                self.turns_by_cache[cache_outcome] += 1
            release.extend(self._enforce_locked(now))
        self._book_pins(len(pages) - len(release))
        if release and self.pool is not None:
            self.pool.release(release)

    def touch(self, sid: str) -> None:
        """Refresh ``sid``'s LRU/TTL position (a new turn arrived)."""
        now = self._clock()
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is not None:
                sess.last_used = now
                self._sessions.move_to_end(sid)

    def lookup(self, sid: str) -> "_Session | None":
        """The live session record (tests/introspection; the engine's
        admission path reads pages through the COW index, not here)."""
        with self._lock:
            return self._sessions.get(sid)

    def _enforce_locked(self, now: float) -> list:
        """TTL + capacity eviction; returns pages to release (caller
        releases OUTSIDE this store's lock-held pool calls ordering is
        still store→pool, but batching keeps the hot path short)."""
        release: list = []
        led = get_ledger()
        dead = [sid for sid, s in self._sessions.items()
                if s.last_used + self.ttl_s <= now]
        for sid in dead:
            release.extend(self._sessions.pop(sid).pages)
            self.evictions["ttl"] += 1
            led.note_reclaim("session_pins", "ttl")
        while len(self._sessions) > self.max_sessions:
            _, sess = self._sessions.popitem(last=False)
            release.extend(sess.pages)
            self.evictions["capacity"] += 1
            led.note_reclaim("session_pins", "capacity")
        return release

    def sweep(self) -> int:
        """Drop TTL-expired sessions now; returns how many died."""
        now = self._clock()
        with self._lock:
            before = len(self._sessions)
            release = self._enforce_locked(now)
            died = before - len(self._sessions)
        self._book_pins(-len(release))
        if release and self.pool is not None:
            self.pool.release(release)
        return died

    def reclaim_pages(self, n: int) -> int:
        """``PagePool.reclaim`` chain link: drop up to ``n`` session pin
        references, least-recently-used session first and each
        session's NEWEST pages first — the surviving pin remains a
        valid chain prefix, so the session degrades to a shorter warm
        prefix instead of losing coherence. Live slots are unaffected
        (only the session's own refs drop)."""
        if n <= 0:
            return 0
        released: list = []
        with self._lock:
            for sid in list(self._sessions):
                if len(released) >= n:
                    break
                sess = self._sessions[sid]
                take = min(len(sess.pages), n - len(released))
                if take <= 0:
                    continue
                released.extend(sess.pages[len(sess.pages) - take:])
                del sess.pages[len(sess.pages) - take:]
                self.evictions["pressure"] += 1
                get_ledger().note_reclaim("session_pins", "pressure")
        self._book_pins(-len(released))
        if released and self.pool is not None:
            self.pool.release(released)
        return len(released)

    def drop(self, sid: str) -> bool:
        """Forget ``sid`` entirely (client DELETE / tests)."""
        with self._lock:
            sess = self._sessions.pop(sid, None)
            self._pending.pop(sid, None)
        if sess is None:
            return False
        self._book_pins(-len(sess.pages))
        if sess.pages and self.pool is not None:
            self.pool.release(sess.pages)
        return True

    # --- fleet path ----------------------------------------------------------

    def adopt(self, sid: str, host) -> bool:
        """Take ownership of a fleet-claimed :class:`~.kv_pool.HostEntry`
        for ``sid`` (HTTP thread). The entry waits in the pending map
        until the engine's admission consumes it (:meth:`take_pending`)
        — consume-once, like the handoff claim that produced it.
        Entries without token ids can't be prefix-validated and are
        counted lost."""
        if host is None or getattr(host, "token_ids", None) is None \
                or host.length <= 0:
            with self._lock:
                self.pulls["lost"] += 1
            return False
        with self._lock:
            self._pending[sid] = host
            self.pulls["claimed"] += 1
        return True

    def note_lost(self) -> None:
        """A fleet claim came back empty — the request re-prefills
        locally (the counted, never-5xx degradation)."""
        with self._lock:
            self.pulls["lost"] += 1

    def take_pending(self, sid: str, prompt_ids):
        """Consume ``sid``'s pending fleet entry, validated against the
        incoming prompt: returns ``(host, n)`` where the first ``n``
        prompt tokens match the entry's token ids (the LONGEST common
        prefix, capped at the entry's KV length), or ``None`` if
        nothing usable is pending. ``n`` can be shorter than the entry
        — an edited/forked conversation still reuses the shared head —
        but a zero-length match (a different conversation reusing the
        sid) discards the entry: scattering mismatched KV would be
        silent corruption."""
        with self._lock:
            host = self._pending.pop(sid, None)
        if host is None:
            return None
        toks = [int(t) for t in (host.token_ids or [])]
        cap = min(int(host.length), len(toks), len(prompt_ids))
        n = 0
        while n < cap and int(prompt_ids[n]) == toks[n]:
            n += 1
        if n <= 0:
            self._log.warning(
                "session %s: pulled entry shares no token prefix with "
                "the prompt — dropping (tokenizer drift?)", sid)
            with self._lock:
                self.pulls["lost"] += 1
            return None
        return host, n

    def publish(self, sid: str, token_ids, entry) -> None:
        """Queue a finished turn's page-aligned KV entry for the fleet
        (engine thread → publisher thread). ``entry`` is a device
        PrefixEntry gathered while the slot still mapped its pages —
        the device→host copy and the pool put run off the engine
        thread, exactly like the disagg publisher pool."""
        if self.handoff is None:
            return
        self._ensure_publisher()
        self._pub_q.put((sid, [int(t) for t in token_ids], entry))

    def _ensure_publisher(self) -> None:
        if self._pub_thread is None or not self._pub_thread.is_alive():
            self._pub_thread = threading.Thread(
                target=self._run_publisher, daemon=True,
                name="session-publisher")
            self._pub_thread.start()

    def _run_publisher(self) -> None:
        from llm_in_practise_tpu.obs.hbm import host_entry_bytes
        from llm_in_practise_tpu.serve.kv_pool import entry_to_host

        while True:
            item = self._pub_q.get()
            staged = 0
            try:
                if item is None:
                    return
                sid, toks, entry = item
                try:
                    host = entry_to_host(entry)
                    host.token_ids = toks
                    # ledger account handoff_staging (host plane): the
                    # entry's RAM until the pool put returns
                    staged = host_entry_bytes(host)
                    get_ledger().book("handoff_staging", staged)
                    self.handoff.publish(session_hid(sid), host)
                except Exception as e:  # noqa: BLE001 — a dead pool
                    # degrades THIS session's future migration, nothing
                    # else; the engine loop must never notice
                    with self._lock:
                        self.pulls["publish_failed"] += 1
                    self._log.warning(
                        "session %s: fleet publish failed (%s: %s)",
                        sid, type(e).__name__, e)
                else:
                    with self._lock:
                        self.pulls["published"] += 1
            finally:
                if staged:
                    get_ledger().book("handoff_staging", -staged)
                self._pub_q.task_done()

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued publish drained (tests/benches —
        the kill-a-replica drill needs the last turn's entry in the
        pool before the replica dies). Returns False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._pub_q.unfinished_tasks == 0:
                return True
            time.sleep(0.01)
        return self._pub_q.unfinished_tasks == 0

    def close(self) -> None:
        """Stop the publisher and drop every pin (engine shutdown)."""
        if self._pub_thread is not None and self._pub_thread.is_alive():
            self._pub_q.put(None)
            self._pub_thread.join(timeout=5.0)
        with self._lock:
            release = [p for s in self._sessions.values() for p in s.pages]
            self._sessions.clear()
            self._pending.clear()
        self._book_pins(-len(release))
        if release and self.pool is not None:
            self.pool.release(release)

    # --- introspection -------------------------------------------------------

    @property
    def active(self) -> int:
        with self._lock:
            return len(self._sessions)

    @property
    def pinned_pages(self) -> int:
        with self._lock:
            return sum(len(s.pages) for s in self._sessions.values())

    def counters(self) -> dict:
        """Atomic snapshot for /metrics (one lock hold, no torn reads
        across families)."""
        with self._lock:
            return {
                "active": len(self._sessions),
                "pinned_pages": sum(len(s.pages)
                                    for s in self._sessions.values()),
                "turns": dict(self.turns_by_cache),
                "evictions": dict(self.evictions),
                "pulls": dict(self.pulls),
            }

    def debug_snapshot(self, limit: int = 64) -> dict:
        """The ``GET /debug/sessions`` payload."""
        now = self._clock()
        with self._lock:
            sessions = [{
                "session_id": s.sid,
                "turns": s.turns,
                "pinned_pages": len(s.pages),
                "pinned_tokens": len(s.pages) * self.page_size,
                "history_tokens": len(s.token_ids),
                "adapter": s.adapter,
                "idle_s": round(now - s.last_used, 3),
                "ttl_left_s": round(s.last_used + self.ttl_s - now, 3),
            } for s in list(self._sessions.values())[-limit:]]
            return {
                "enabled": True,
                "ttl_s": self.ttl_s,
                "max_sessions": self.max_sessions,
                "page_size": self.page_size,
                "fleet": self.handoff is not None,
                "active": len(self._sessions),
                "pending_pulls": len(self._pending),
                "pinned_pages": sum(len(s.pages)
                                    for s in self._sessions.values()),
                "turns": dict(self.turns_by_cache),
                "evictions": dict(self.evictions),
                "pulls": dict(self.pulls),
                "sessions": sessions,
            }

"""Serve-time LoRA adapter loading — vLLM ``--lora-modules`` parity.

The reference serves fine-tuned adapters with
``vllm serve … --enable-lora --lora-modules qwen3-8b-lora=/path/to/adapter``
(``Fine-Tuning/README.md:340-361``): one base model, extra model names
backed by LoRA deltas, selected per request via the OpenAI ``model`` field.

Here each adapter name maps to an :class:`InferenceEngine` whose params are
the base with the adapter folded in (merge at load — on TPU the merged
matmul is strictly cheaper than per-request delta application, and slots
inside one engine batch share weights). Adapters are the ``adapter.msgpack``
+ ``adapter.json`` pairs written by ``examples/qwen3_lora_sft.py`` /
``ckpt.save_named``.
"""

from __future__ import annotations

import os

from llm_in_practise_tpu.ckpt import checkpoint as ckpt_lib
from llm_in_practise_tpu.peft import LoRAConfig, merge_lora
from llm_in_practise_tpu.serve.engine import InferenceEngine


def parse_lora_modules(specs: list[str]) -> dict[str, str]:
    """``["name=/path", ...]`` → {name: path} (the vLLM CLI syntax)."""
    out = {}
    for spec in specs:
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            raise ValueError(f"expected name=path, got {spec!r}")
        out[name] = path
    return out


def load_adapter(base_params, adapter_path: str):
    """Restore one adapter checkpoint and merge it into ``base_params``."""
    if os.path.isdir(adapter_path):
        adapter_path = os.path.join(adapter_path, "adapter.msgpack")
    lora_params, meta = ckpt_lib.restore_checkpoint(adapter_path)
    if "lora_config" not in meta:
        raise ValueError(
            f"{adapter_path} has no lora_config metadata sidecar"
        )
    cfg = LoRAConfig.from_dict(meta["lora_config"])
    return merge_lora(base_params, lora_params, cfg)


def build_adapter_engines(
    model,
    base_params,
    modules: dict[str, str],
    param_transform=None,
    engine_kw_for=None,
    **engine_kw,
) -> dict[str, InferenceEngine]:
    """One engine per adapter name, merged weights, shared model/config.

    ``param_transform`` (optional) post-processes each adapter's merged
    params — e.g. :func:`..serve.engine.shard_params_for_serving` so
    adapters follow the base engine's tensor-parallel placement instead of
    replicating host arrays onto every mesh device.

    ``engine_kw_for(name)`` (optional) returns per-adapter kwargs merged
    over ``engine_kw`` — needed for anything that must NOT be shared
    across weight sets, like a ``kv_pool`` (each adapter's KV is only
    valid under its own merged weights).
    """
    def prep(path):
        merged = load_adapter(base_params, path)
        return param_transform(merged) if param_transform else merged

    return {
        name: InferenceEngine(
            model, prep(path),
            **{**engine_kw, **(engine_kw_for(name) if engine_kw_for else {})})
        for name, path in modules.items()
    }

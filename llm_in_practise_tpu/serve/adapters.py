"""Serve-time LoRA adapter loading — vLLM ``--lora-modules`` parity.

The reference serves fine-tuned adapters with
``vllm serve … --enable-lora --lora-modules qwen3-8b-lora=/path/to/adapter``
(``Fine-Tuning/README.md:340-361``): one base model, extra model names
backed by LoRA deltas, selected per request via the OpenAI ``model`` field.

Since ISSUE 15 this module is a thin compatibility shim over
``serve/multi_lora.py``: :func:`build_adapter_engines` builds ONE shared
:class:`InferenceEngine` with an :class:`~.multi_lora.AdapterRegistry`
and returns engine-shaped :class:`~.multi_lora.AdapterHandle` views, so
every adapter rides the same fused dispatch and the base weights live in
HBM exactly once. The legacy engine-per-adapter merged-weight path is
kept (with a warning) only for the cases the batched-BGMV twins cannot
serve: scan-layers models (stacked cache layout, no per-block module
paths for the interceptor) and callers passing per-adapter engine
kwargs (``engine_kw_for`` — separate kv pools / handoff namespaces imply
separate weight sets). Adapters are the ``adapter.msgpack`` +
``adapter.json`` pairs written by ``examples/qwen3_lora_sft.py`` /
``ckpt.save_named``.
"""

from __future__ import annotations

import os

from llm_in_practise_tpu.ckpt import checkpoint as ckpt_lib
from llm_in_practise_tpu.obs.logging import get_logger
from llm_in_practise_tpu.peft import LoRAConfig, merge_lora
from llm_in_practise_tpu.serve.engine import InferenceEngine

_log = get_logger("serve.adapters")


def parse_lora_modules(specs: list[str]) -> dict[str, str]:
    """``["name=/path", ...]`` → {name: path} (the vLLM CLI syntax)."""
    out = {}
    for spec in specs:
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            raise ValueError(f"expected name=path, got {spec!r}")
        out[name] = path
    return out


def load_adapter(base_params, adapter_path: str):
    """Restore one adapter checkpoint and merge it into ``base_params``."""
    if os.path.isdir(adapter_path):
        adapter_path = os.path.join(adapter_path, "adapter.msgpack")
    lora_params, meta = ckpt_lib.restore_checkpoint(adapter_path)
    if "lora_config" not in meta:
        raise ValueError(
            f"{adapter_path} has no lora_config metadata sidecar"
        )
    cfg = LoRAConfig.from_dict(meta["lora_config"])
    return merge_lora(base_params, lora_params, cfg)


def build_adapter_engines(
    model,
    base_params,
    modules: dict[str, str],
    param_transform=None,
    engine_kw_for=None,
    **engine_kw,
):
    """Adapter-name → engine-shaped handle map for ``OpenAIServer``.

    Default (registry) path: ONE shared :class:`InferenceEngine` carrying
    an :class:`~.multi_lora.AdapterRegistry`; each name maps to an
    :class:`~.multi_lora.AdapterHandle` that pins its adapter on
    ``submit``. Mixed-adapter slots batch into the same fused dispatch
    and base HBM is paid once regardless of the adapter count.

    Legacy (merged-weight engine-per-adapter) fallback, warned:

    - scan-layers models (``cache_slot_axis == 1``): the stacked scan
      body has no per-block module paths for the LoRA interceptor, so
      the adapter merges into the stacked kernels instead
    - ``engine_kw_for`` given: per-adapter kwargs (kv pools, handoff
      namespaces) assume one weight set per engine

    ``param_transform`` (optional) post-processes the params handed to
    each built engine — e.g. :func:`..serve.engine.shard_params_for_serving`
    so they follow the base engine's tensor-parallel placement instead of
    replicating host arrays onto every mesh device.

    ``engine_kw_for(name)`` (optional, legacy-only) returns per-adapter
    kwargs merged over ``engine_kw``.
    """
    scan_layers = int(getattr(model, "cache_slot_axis", 0)) == 1
    if scan_layers or engine_kw_for is not None:
        why = ("scan-layers model serves contiguous stacked kernels"
               if scan_layers else "per-adapter engine kwargs requested")
        _log.warning(
            "legacy engine-per-adapter path (%s): each of the %d "
            "adapter(s) pays full base-model HBM — the batched "
            "multi-LoRA registry (serve/multi_lora.py) shares one "
            "engine across adapters", why, len(modules))

        def prep(path):
            merged = load_adapter(base_params, path)
            return param_transform(merged) if param_transform else merged

        return {
            name: InferenceEngine(
                model, prep(path),
                **{**engine_kw,
                   **(engine_kw_for(name) if engine_kw_for else {})})
            for name, path in modules.items()
        }

    from llm_in_practise_tpu.serve.multi_lora import (
        AdapterHandle,
        AdapterRegistry,
    )

    registry = AdapterRegistry(base_params, mesh=engine_kw.get("mesh"))
    params = (param_transform(base_params) if param_transform
              else base_params)
    engine = InferenceEngine(model, params, adapter_registry=registry,
                             **engine_kw)
    for name, path in modules.items():
        registry.register(name, path)
    return {name: AdapterHandle(engine, name) for name in modules}

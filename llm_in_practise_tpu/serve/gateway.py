"""Gateway: routing, retries, fallbacks, caching — the LiteLLM-proxy analog.

The reference fronts its model servers with a LiteLLM proxy
(``Deployment/litellm-proxy/config/litellm-config-router-lb.yaml``):
cost/load-based routing over a model list, per-error-class retry policy,
``allowed_fails`` + ``cooldown_time`` circuit breaking, fallback model
chains, context-window fallbacks, Redis exact/semantic response caches, and
a pre-call guard-model hook (``litellm-config-guard.yaml`` +
``llama-guard-wrapper/app.py``). This module is that control plane as one
stdlib-only HTTP proxy in front of any OpenAI-compatible upstreams (ours or
vLLM's):

- :class:`Upstream` — one backend (base_url, model, weight, health state).
- :class:`Router` — picks an upstream for a model group: weighted
  least-pending with cooldown exclusion.
- :class:`RetryPolicy` — retries per error class
  (``retry_policy:`` in the reference yaml).
- :class:`ResponseCache` — TTL'd exact cache keyed on (model, messages,
  params); the semantic tier matches by cosine over hashed bag-of-token
  embeddings (the reference's Redis semantic cache, without the external
  embedding service).
- :class:`Gateway` — the HTTP server wiring it together, with
  ``/v1/chat/completions``, ``/health``, ``/metrics`` and an optional
  pre-call moderation hook.
"""

from __future__ import annotations

import hashlib
import json
import math
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from http.server import ThreadingHTTPServer

from llm_in_practise_tpu.obs.registry import Registry
from llm_in_practise_tpu.obs.trace import (
    format_traceparent,
    get_tracer,
    parse_traceparent,
)
from llm_in_practise_tpu.serve.http_util import (
    JsonHandler,
    serve_obs_get,
    serve_obs_post,
)
from llm_in_practise_tpu.serve.sessions import ConsistentHashRing


@dataclass
class Upstream:
    """One backend endpoint for a served model."""

    base_url: str                  # e.g. http://127.0.0.1:8000
    model: str                     # model name at the upstream
    group: str                     # public model name this serves
    weight: float = 1.0            # cost-based routing weight (higher = prefer)
    allowed_fails: int = 3         # consecutive fails before cooldown
    cooldown_time: float = 30.0    # seconds out of rotation
    # disaggregated serving (serve/disagg.py): which pool this replica
    # belongs to. "both" replicas serve either pool — they are the
    # graceful-degradation capacity when a role pool is empty.
    role: str = "both"

    fails: int = 0             # guarded-by: lock
    cooldown_until: float = 0.0
    pending: int = 0           # guarded-by: lock
    served: int = 0            # guarded-by: lock
    # per-upstream routing counters, exported at /metrics: picks says
    # where the router actually sends traffic (vs. served, which also
    # counts retries), cooldowns says how often this replica tripped the
    # breaker, affinity_hits says how much of its traffic was cache-warm.
    # Incremented from concurrent handler threads → under the lock
    # (bare += across threads loses counts); scrapes read lock-free.
    picks: int = 0             # guarded-by: lock
    cooldowns: int = 0         # guarded-by: lock
    affinity_hits: int = 0     # guarded-by: lock
    lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def available(self, now: float) -> bool:
        return now >= self.cooldown_until

    def record_success(self):
        with self.lock:
            self.fails = 0

    def record_failure(self, now: float):
        with self.lock:
            self.fails += 1
            if self.fails >= self.allowed_fails:
                self.cooldown_until = now + self.cooldown_time
                self.fails = 0
                self.cooldowns += 1


class _StreamHandle:
    """An open upstream SSE response that releases its replica's pending
    count on close — the stream's lifetime, not its connection setup,
    is what occupies the replica."""

    def __init__(self, resp, release):
        self._resp = resp
        self._release = release
        self.headers = resp.headers
        self.status = resp.status

    def read(self, n: int = -1):
        return self._resp.read(n)

    def close(self):
        release, self._release = self._release, None
        if release is not None:
            release()
        self._resp.close()

    def __del__(self):  # backstop: a dropped handle must not leak pending
        try:
            self.close()
        except Exception:
            pass


class RouterError(Exception):
    pass


class Router:
    """Pick an upstream per model group: weighted least-pending among
    non-cooled-down backends (the yaml's ``routing_strategy:
    cost-based-routing`` + ``cooldown_time`` semantics)."""

    def __init__(self, upstreams: list[Upstream]):
        self.upstreams = list(upstreams)

    def groups(self) -> list[str]:
        return sorted({u.group for u in self.upstreams})

    def candidates(self, group: str) -> list[Upstream]:
        now = time.time()
        return [u for u in self.upstreams
                if u.group == group and u.available(now)]

    @staticmethod
    def _least_pending(cands: list[Upstream]) -> Upstream:
        """Weighted least-pending selection + pick accounting — the one
        load metric every routing strategy (base, disagg pools) ranks
        by; ties broken by total served so sequential traffic
        round-robins instead of pinning the first entry."""
        chosen = min(cands, key=lambda u: (
            (u.pending + 1) / max(u.weight, 1e-9),
            u.served / max(u.weight, 1e-9),
        ))
        with chosen.lock:
            chosen.picks += 1
        return chosen

    def pick(self, group: str, exclude: set[int] = frozenset()) -> Upstream:
        cands = [u for u in self.candidates(group) if id(u) not in exclude]
        if not cands:
            raise RouterError(f"no available upstream for {group!r}")
        return self._least_pending(cands)

    def pick_for_request(self, group: str, body: dict,
                         exclude: set[int] = frozenset()) -> Upstream:
        """Request-aware pick; the base router ignores the body."""
        return self.pick(group, exclude=exclude)


class PrefixAffinityRouter(Router):
    """Cache-aware routing — the llm-d ``load_aware_prefix`` strategy
    (``08-LLM-Router/llm-d/llm-d-config.yaml:20-40``: weighted scoring of
    pending load vs prefix-cache affinity; nginx consistent-hash on
    Session-ID is the same idea one layer down).

    Requests from one conversation hash to the same upstream (its prefix
    KV cache stays hot — see :mod:`.prefix_cache`), unless that upstream
    is cooled down or the load imbalance outweighs the cache miss cost.
    """

    def __init__(self, upstreams: list[Upstream], *,
                 miss_cost: float = 2.0, affinity_ttl_s: float = 600.0,
                 max_sessions: int = 4096):
        super().__init__(upstreams)
        self.miss_cost = miss_cost       # pending-units a cache miss "costs"
        self.affinity_ttl_s = affinity_ttl_s
        self.max_sessions = max_sessions
        # (group, session) -> (ts, upstream base_url); OrderedDict so
        # eviction is O(1) LRU instead of a min() scan under the lock.
        # Keyed per group: a fallback-group pick must not clobber the
        # primary group's pin. The VALUE is the base_url, not
        # id(upstream): ids are reused by the allocator, so after an
        # upstream-list change a stale entry could pin a session to an
        # unrelated replica that happened to inherit the address.
        from collections import OrderedDict

        self._affinity: "OrderedDict[tuple, tuple[float, str]]" = OrderedDict()
        self._urls: frozenset = frozenset(
            u.base_url for u in upstreams)  # guarded-by: _lock
        self._lock = threading.Lock()

    @staticmethod
    def session_key(body: dict) -> str | None:
        """Stable conversation identity: the system + first user message
        (the shared prefix all turns of one chat carry)."""
        msgs = body.get("messages") or []
        head = [m for m in msgs if m.get("role") == "system"][:1]
        head += [m for m in msgs if m.get("role") == "user"][:1]
        if not head:
            return None
        canon = json.dumps(head, sort_keys=True)
        return hashlib.sha256(canon.encode()).hexdigest()

    def pick_for_request(self, group: str, body: dict,
                         exclude: set[int] = frozenset()) -> Upstream:
        session = self.session_key(body)
        key = (group, session) if session is not None else None
        cands = [u for u in self.candidates(group) if id(u) not in exclude]
        if not cands:
            raise RouterError(f"no available upstream for {group!r}")
        now = time.time()
        sticky_url = None
        if key is not None:
            with self._lock:
                # topology change: drop pins whose replica left the
                # list — a stale pin must not bias the score toward a
                # new upstream that reused the address slot
                urls = frozenset(u.base_url for u in self.upstreams)
                if urls != self._urls:
                    self._urls = urls
                    for k in [k for k, v in self._affinity.items()
                              if v[1] not in urls]:
                        del self._affinity[k]
                hit = self._affinity.get(key)
                if hit and now - hit[0] < self.affinity_ttl_s:
                    sticky_url = hit[1]

        def score(u: Upstream) -> tuple:
            load = (u.pending + 1) / max(u.weight, 1e-9)
            miss = 0.0 if u.base_url == sticky_url else self.miss_cost
            return (load + miss, u.served / max(u.weight, 1e-9))

        chosen = min(cands, key=score)
        with chosen.lock:
            chosen.picks += 1
            if chosen.base_url == sticky_url:
                chosen.affinity_hits += 1
        if key is not None:
            with self._lock:
                self._affinity[key] = (now, chosen.base_url)
                self._affinity.move_to_end(key)
                if len(self._affinity) > self.max_sessions:
                    self._affinity.popitem(last=False)
        return chosen


class HashRingRouter(Router):
    """Session-affine routing on a consistent-hash ring — the nginx
    ``hash $http_x_session_id consistent`` / llm-d session-ring idea
    (``08-LLM-Router``), replacing :class:`PrefixAffinityRouter`'s
    sticky table for session-native serving (serve/sessions.py).

    Ownership is a pure function of (key, live topology): every
    gateway replica computes the same owner with no shared state, and
    a replica join/leave remaps only ~1/N sessions (the dead node's
    arcs) instead of whatever a table happened to pin — the surviving
    replicas' pinned session KV stays exactly where it is. The routing
    key is the strongest identity available: explicit session id >
    conversation-prefix hash > tenant/adapter name, so a tenant's
    requests concentrate where its adapter banks and COW chains are
    already resident.

    Bounded-load two-choice keeps one hot session from melting its
    owner: when the owner's pending load exceeds ``bound`` × the group
    mean, the request overflows to the key's SECOND ring owner (still
    deterministic — the same replica every time, so ITS cache warms
    too), and only past that to plain least-pending. Cooled-down or
    excluded owners are skipped by walking the ring's successor order,
    no rebuild — when the replica comes back, its sessions come home.
    """

    def __init__(self, upstreams: list[Upstream], *,
                 bound: float = 1.25, vnodes: int = 64,
                 max_tracked: int = 4096):
        super().__init__(upstreams)
        self.bound = float(bound)
        self.vnodes = int(vnodes)
        self.max_tracked = int(max_tracked)
        from collections import OrderedDict

        self._lock = threading.Lock()
        self._rings: dict[str, ConsistentHashRing] = {}  # guarded-by: _lock
        self._topology: frozenset | None = None          # guarded-by: _lock
        # key -> base_url last served by: REMAP ACCOUNTING only (the
        # ring itself is memoryless); bounded LRU like the old sticky
        # table, but losing an entry only loses a metric sample
        self._last_owner: "OrderedDict[tuple, str]" = OrderedDict()  # guarded-by: _lock
        self.ring_picks = {"primary": 0, "second": 0,
                           "fallback": 0}                # guarded-by: _lock
        self.ring_rebuilds = 0                           # guarded-by: _lock
        self.ring_remapped = 0                           # guarded-by: _lock

    @staticmethod
    def ring_key(body: dict) -> str | None:
        """Strongest stable identity in the request, namespaced so the
        three sources can never collide with each other."""
        body = body or {}
        sid = body.get("session_id")
        if isinstance(sid, str) and sid:
            return "sid:" + sid
        pfx = PrefixAffinityRouter.session_key(body)
        if pfx is not None:
            return "pfx:" + pfx
        model = body.get("model")
        return ("tenant:" + str(model)) if model else None

    def _ring_for(self, group: str) -> ConsistentHashRing:
        """Per-group ring, rebuilt ONLY when the upstream set actually
        changed (compared as (group, base_url) pairs — weight or
        cooldown churn must not move sessions)."""
        topo = frozenset((u.group, u.base_url) for u in self.upstreams)
        with self._lock:
            if topo != self._topology:
                if self._topology is not None:
                    self.ring_rebuilds += 1
                self._topology = topo
                self._rings = {}
            ring = self._rings.get(group)
            if ring is None:
                ring = ConsistentHashRing(
                    [u.base_url for u in self.upstreams
                     if u.group == group],
                    vnodes=self.vnodes)
                self._rings[group] = ring
            return ring

    def pick_for_request(self, group: str, body: dict,
                         exclude: set[int] = frozenset()) -> Upstream:
        cands = [u for u in self.candidates(group) if id(u) not in exclude]
        if not cands:
            raise RouterError(f"no available upstream for {group!r}")
        key = self.ring_key(body)
        if key is None:
            return self._least_pending(cands)
        ring = self._ring_for(group)
        by_url = {u.base_url: u for u in cands}
        # successor walk = cooldown/exclude skipping without a rebuild
        walk = [by_url[u] for u in ring.owners(key, len(ring) or 1)
                if u in by_url]
        avg = sum(u.pending for u in cands) / len(cands)
        limit = self.bound * (avg + 1.0)
        chosen, choice = None, "fallback"
        for rank, u in zip(("primary", "second"), walk):
            if u.pending + 1 <= limit:
                chosen, choice = u, rank
                break
        if chosen is None:
            # both choice owners over the load bound (or none alive):
            # spill anywhere — losing affinity beats queueing
            chosen = min(cands, key=lambda u: (
                (u.pending + 1) / max(u.weight, 1e-9),
                u.served / max(u.weight, 1e-9)))
        with self._lock:
            prev = self._last_owner.get((group, key))
            if prev is not None and prev != chosen.base_url:
                self.ring_remapped += 1
            self._last_owner[(group, key)] = chosen.base_url
            self._last_owner.move_to_end((group, key))
            if len(self._last_owner) > self.max_tracked:
                self._last_owner.popitem(last=False)
            self.ring_picks[choice] += 1
        with chosen.lock:
            chosen.picks += 1
            if prev == chosen.base_url:
                chosen.affinity_hits += 1
        return chosen

    def ring_snapshot(self) -> dict:
        """Ring counters read under the lock — the scrape callbacks'
        one entry point (mirrors Gateway._counter_snapshot)."""
        with self._lock:
            return {
                "picks": dict(self.ring_picks),
                "rebuilds": self.ring_rebuilds,
                "remapped": self.ring_remapped,
                "tracked": len(self._last_owner),
            }


class DisaggRouter(Router):
    """Disaggregated prefill/decode routing — the llm-d role-split
    strategy, sibling of :class:`PrefixAffinityRouter`'s
    ``load_aware_prefix`` (``08-LLM-Router/llm-d``; see serve/disagg.py
    for the replica side).

    New requests are prefilled by the **prefill pool** (via the
    gateway's two-phase dispatch, :meth:`Gateway._disagg_prefill`), then
    the stream is handed to a **decode pool** upstream chosen by
    least-pending. Degradation is built in: when either role pool is
    empty (scale-to-zero, rollout, cooldowns) the router behaves like a
    plain least-pending :class:`Router` over whatever is available —
    ``role="both"`` upstreams are full replicas and absorb either kind
    of work — and the decode replica itself re-prefills when a handoff
    entry is lost, so no pool topology can make a request unservable."""

    def __init__(self, upstreams: list[Upstream]):
        from llm_in_practise_tpu.serve.disagg import validate_roles

        # fail loudly on a typo'd role ("Prefill", "prefil", ...): the
        # pools match exact strings, and a misspelled upstream would
        # silently join NO pool — the whole fleet degrading to plain
        # routing with only a counter as the clue
        for u in upstreams:
            validate_roles(u.role)
        super().__init__(upstreams)
        self.degraded_picks = 0   # picks served outside the role split

    def _role_pool(self, group: str, role: str) -> list[Upstream]:
        return [u for u in self.candidates(group) if u.role == role]

    def disaggregated(self, group: str) -> bool:
        """Both role pools non-empty = the split is operable. "both"
        upstreams back-fill EITHER side, but at least one dedicated
        replica of one role must exist or the two-phase dispatch is
        pure overhead (prefill + decode on the same pool)."""
        pre = self._role_pool(group, "prefill")
        dec = self._role_pool(group, "decode")
        both = self._role_pool(group, "both")
        return bool((pre or dec) and (pre or both) and (dec or both))

    def pick_prefill(self, group: str) -> Upstream | None:
        """Least-pending upstream of the prefill pool, or ``None`` when
        the split is inoperable (caller skips the prefill phase)."""
        if not self.disaggregated(group):
            self.degraded_picks += 1
            return None
        cands = self._role_pool(group, "prefill") or self._role_pool(
            group, "both")
        return self._least_pending(cands)

    def pick_for_request(self, group: str, body: dict,
                         exclude: set[int] = frozenset()) -> Upstream:
        """Decode-pool pick for the generation half. Requests WITHOUT a
        handoff (the prefill phase failed, or the split is inoperable)
        prefer full replicas: a pure-decode replica would pay a local
        re-prefill, and a pure-prefill replica would carry a long-lived
        decode stream that poisons the prefill autoscaler's pending
        signal."""
        handed_off = bool((body or {}).get("kv_transfer_params"))
        if not handed_off:
            if not self.disaggregated(group):
                return self.pick(group, exclude=exclude)
            self.degraded_picks += 1
            for pool in ("both", "decode"):
                cands = [u for u in self._role_pool(group, pool)
                         if id(u) not in exclude]
                if cands:
                    return self._least_pending(cands)
            return self.pick(group, exclude=exclude)
        cands = [u for u in (self._role_pool(group, "decode")
                             or self._role_pool(group, "both"))
                 if id(u) not in exclude]
        # mixed-model pools (|MODEL renames): the entry was published
        # under ONE model's namespace — a decode replica serving a
        # different model can never claim it, so constrain the pick to
        # matching replicas when any exist
        xfer = (body or {}).get("kv_transfer_params") or {}
        xmodel = xfer.get("model")
        if xmodel is not None:
            matching = [u for u in cands if u.model == xmodel]
            if matching:
                cands = matching
        if not cands:
            # every decode-capable upstream tried/cooled: fall back to
            # the whole group rather than failing the request
            self.degraded_picks += 1
            return self.pick(group, exclude=exclude)
        return self._least_pending(cands)


@dataclass(frozen=True)
class RetryPolicy:
    """Per-error-class retry counts (reference ``retry_policy:`` block)."""

    timeout_retries: int = 2
    rate_limit_retries: int = 2      # 429
    server_error_retries: int = 1    # 5xx
    bad_request_retries: int = 0     # 4xx (not worth retrying)
    backoff_s: float = 0.2           # base of exponential backoff

    def retries_for(self, status: int | None) -> int:
        if status is None:
            return self.timeout_retries
        if status == 429:
            return self.rate_limit_retries
        if status >= 500:
            return self.server_error_retries
        return self.bad_request_retries


def _token_embed(text: str, dim: int = 256) -> list[float]:
    """Hashed bag-of-words embedding — stands in for the reference's
    embedding service in its semantic cache (README.md:2845-3488); cosine
    over these catches near-identical rephrasings, and the hook is the
    boundary where a real encoder plugs in."""
    vec = [0.0] * dim
    for word in text.lower().split():
        h = int.from_bytes(hashlib.md5(word.encode()).digest()[:4], "little")
        vec[h % dim] += 1.0
    n = math.sqrt(sum(v * v for v in vec)) or 1.0
    return [v / n for v in vec]


class ResponseCache:
    """Exact + semantic response cache (the compose stack's dual-namespace
    Redis cache, in-process). Exact: TTL'd dict on a canonical request key.
    Semantic: cosine over hashed-BoW embeddings of the last user message."""

    def __init__(self, *, ttl_s: float = 300.0, max_entries: int = 1024,
                 semantic_threshold: float | None = 0.97,
                 embed_fn=None):
        self.ttl_s = ttl_s
        self.max_entries = max_entries
        self.semantic_threshold = semantic_threshold
        # pluggable encoder: the standalone cache service swaps in a real
        # /v1/embeddings call here (the reference's embedding service)
        self._embed = embed_fn or _token_embed
        self._exact: dict[str, tuple[float, dict]] = {}
        self._semantic: list[tuple[float, str, list[float], dict]] = []
        self._lock = threading.Lock()
        self.hits = 0
        self.semantic_hits = 0
        self.misses = 0

    @staticmethod
    def _key(body: dict) -> str:
        # Whole request (minus transport fields) — two requests differing in
        # ANY sampling param must not share a cache entry.
        canon = json.dumps(
            {k: v for k, v in body.items() if k != "stream"}, sort_keys=True,
        )
        return hashlib.sha256(canon.encode()).hexdigest()

    @staticmethod
    def _conversation_text(body: dict) -> str:
        """Full conversation (system + every turn): the semantic key must
        see history, or two chats both ending in 'yes' would collide."""
        return "\n".join(
            f"{m.get('role', '')}: {m.get('content', '')}"
            for m in body.get("messages", [])
        )

    @staticmethod
    def _structured(body: dict) -> bool:
        """Structured-output requests (ISSUE 12 gateway passthrough):
        `response_format`/`tools` forward untouched, but the SEMANTIC
        tier matches on conversation text alone — it could hand a
        schema-constrained request a cached free-text answer. Exact
        hits are safe (the key hashes every non-transport field)."""
        return bool(body.get("response_format") or body.get("tools")
                    or body.get("tool_choice"))

    def get(self, body: dict) -> dict | None:
        if body.get("stream"):
            return None
        now = time.time()
        key = self._key(body)
        # Exact tier first, under the lock: an exact hit must never pay for
        # (or wait behind) an embedding call — with a remote embed_fn a slow
        # embedding backend would otherwise serialize every get/put here.
        with self._lock:
            hit = self._exact.get(key)
            if hit and now - hit[0] < self.ttl_s:
                self.hits += 1
                return hit[1]
            if self.semantic_threshold is None or self._structured(body):
                self.misses += 1
                return None
        # Embed OUTSIDE the lock (may be a remote /v1/embeddings call).
        query = self._embed(self._conversation_text(body))
        with self._lock:
            model = body.get("model")
            best, best_sim = None, 0.0
            for ts, m, emb, resp in self._semantic:
                if m != model or now - ts >= self.ttl_s:
                    continue
                sim = sum(a * b for a, b in zip(query, emb))
                if sim > best_sim:
                    best, best_sim = resp, sim
            if best is not None and best_sim >= self.semantic_threshold:
                self.semantic_hits += 1
                return best
            self.misses += 1
            return None

    def put(self, body: dict, response: dict) -> None:
        if body.get("stream"):
            return
        now = time.time()
        key = self._key(body)
        # Embed before taking the lock — see get() for why. Structured
        # responses never enter the semantic tier (their text answers a
        # schema, not just the conversation — see _structured).
        emb = (self._embed(self._conversation_text(body))
               if self.semantic_threshold is not None
               and not self._structured(body) else None)
        with self._lock:
            self._exact[key] = (now, response)
            if len(self._exact) > self.max_entries:
                oldest = min(self._exact, key=lambda k: self._exact[k][0])
                del self._exact[oldest]
            if emb is not None:
                self._semantic.append((now, body.get("model"), emb, response))
                if len(self._semantic) > self.max_entries:
                    self._semantic.pop(0)


class Gateway:
    """OpenAI-compatible routing proxy.

    ``moderation`` (optional): callable ``(text) -> (flagged, categories)``
    run on user content before forwarding — the reference's guard-model
    pre-call hook; flagged requests get a 400 with the category list
    (LiteLLM's behavior when the guard trips).
    """

    def __init__(
        self,
        router: Router,
        *,
        retry_policy: RetryPolicy = RetryPolicy(),
        cache: ResponseCache | None = None,
        fallbacks: dict[str, list[str]] | None = None,
        context_window_fallbacks: dict[str, list[str]] | None = None,
        max_context_tokens: dict[str, int] | None = None,
        moderation=None,
        timeout_s: float = 120.0,
        health_check_interval_s: float = 30.0,
        tracer=None,
        ttft_slo_s: float | None = None,
        tpot_slo_s: float | None = None,
        tenant_quotas: dict[str, float] | None = None,
        tenant_weights: dict[str, float] | None = None,
        tenant_quota_window_s: float = 60.0,
        canary: dict[str, float] | None = None,
        canary_golden_rate: float = 0.0,
        fleet_fetch=None,
    ):
        self.router = router
        self.retry_policy = retry_policy
        self.cache = cache
        self.fallbacks = fallbacks or {}
        self.context_window_fallbacks = context_window_fallbacks or {}
        self.max_context_tokens = max_context_tokens or {}
        self.moderation = moderation
        self.timeout_s = timeout_s
        self.health_check_interval_s = health_check_interval_s
        # request-plane counters are bumped from CONCURRENT handler
        # threads — a bare `+= 1` there interleaves and loses counts
        # (the unguarded-counter class graftlint's guarded-by pass
        # flags); scrape callbacks read through _counter_snapshot so
        # the seeded attrs are read under their lock via one helper
        self._stats_lock = threading.Lock()
        self.requests_total = 0        # guarded-by: _stats_lock
        self.failures_total = 0        # guarded-by: _stats_lock
        self.fallbacks_total = 0       # guarded-by: _stats_lock
        # prefill phases that published KV / errored (degraded)
        self.handoff_total = 0         # guarded-by: _stats_lock
        self.handoff_failed_total = 0  # guarded-by: _stats_lock
        # per-tenant fairness (multi-LoRA serving, ISSUE 15): one token
        # bucket per tenant (= the request's model/adapter name).
        # ``tenant_quotas[t]`` is t's output-token budget per
        # ``tenant_quota_window_s``; ``tenant_weights[t]`` scales the
        # burst capacity (weighted admission — a weight-2 tenant may
        # burst twice its refill window). Admission only requires a
        # POSITIVE balance; the ACTUAL completion tokens are debited
        # after the response (the gateway cannot know them up front),
        # so one oversized reply overdraws the bucket and the tenant
        # 429s until the refill pays the debt back.
        self.tenant_quotas = dict(tenant_quotas or {})
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_quota_window_s = float(tenant_quota_window_s)
        self._tenant_lock = threading.Lock()
        self._tenant_balance: dict[str, float] = {}   # guarded-by: _tenant_lock
        self._tenant_refill_t: dict[str, float] = {}  # guarded-by: _tenant_lock
        self.tenant_tokens: dict[str, int] = {}       # guarded-by: _tenant_lock
        self.tenant_rejections: dict[str, int] = {}   # guarded-by: _tenant_lock
        # tenant -> {"ok": n, "violated": n} output tokens by SLO verdict
        self.tenant_goodput: dict[str, dict] = {}     # guarded-by: _tenant_lock
        # weighted canary routing (ISSUE 18, ROADMAP 5(c)): ``canary``
        # maps leg URL -> traffic fraction in [0, 1]. Canary legs live
        # OUTSIDE the router (the stable pick never lands on one; a
        # failed canary forward falls back to the stable path, so the
        # canary can never lose a request). ``canary_golden_rate``
        # shadow-samples deterministic (greedy / temperature==0)
        # non-stream canary hits: the same body also goes to a stable
        # upstream and the answers are compared token-for-token —
        # the golden half of the promotion/rollback verdict.
        self.canary_weights = {u.rstrip("/"): float(w)
                               for u, w in (canary or {}).items()}
        self.canary_golden_rate = float(canary_golden_rate)
        self.canary_upstreams = [
            Upstream(url, model="", group="canary", role="both",
                     weight=w)
            for url, w in sorted(self.canary_weights.items())]
        # seeded so a bench/test drives a reproducible traffic split;
        # draws happen under _stats_lock (Random isn't thread-safe)
        import random

        self._canary_rng = random.Random(0x18C0FFEE)  # guarded-by: _stats_lock
        self._canary_requests: dict[tuple, int] = {}  # guarded-by: _stats_lock
        self._canary_golden: dict[str, int] = {}      # guarded-by: _stats_lock
        # GET /fleet: lazily built fleet collector over every upstream
        # (stable + canary); ``fleet_fetch`` is the pluggable scrape
        # transport (obs/fleet.py) — tests/benches go in-process
        self._fleet_fetch = fleet_fetch
        self._fleet_lock = threading.Lock()
        self._fleet_collector = None                  # guarded-by: _fleet_lock
        self._disagg_model_warned: set = set()
        self._httpd: ThreadingHTTPServer | None = None
        self._health_thread: threading.Thread | None = None
        self._stop = threading.Event()
        # request tracing: the gateway mints the root span of every
        # request's trace and propagates it to the upstreams via a
        # traceparent header (and through kv_transfer_params for the
        # prefill→decode hop) — obs/trace.py, docs/observability.md
        self.tracer = tracer if tracer is not None else get_tracer()
        # SLO goodput (obs/meter.py): output tokens priced by whether
        # their request met the configured TTFT/TPOT SLOs — the fleet
        # number a raw tok/s rate lies about. Thresholds come from the
        # kwargs or LLM_TPU_TTFT_SLO_S / LLM_TPU_TPOT_SLO_S; unset =
        # accounting off (counters stay 0). Violations are blamed on
        # the longest request-phase span in the ring (single-process
        # stacks see the engine's phases; cross-process degrades to the
        # gateway's own spans or "unknown").
        import os

        from llm_in_practise_tpu.obs.meter import GoodputMeter

        def _env_slo(name: str) -> float | None:
            raw = os.environ.get(name)
            if not raw:
                return None
            try:
                return float(raw)
            except ValueError:
                # fail OPEN like every other optional telemetry input
                # (bad LLM_TPU_TRACE_FILE, uncovered cost model): a
                # typo'd SLO disables goodput, never the data plane
                import logging

                logging.getLogger(__name__).warning(
                    "ignoring malformed %s=%r (want seconds as a "
                    "float); SLO goodput accounting disabled for this "
                    "threshold", name, raw)
                return None

        if ttft_slo_s is None:
            ttft_slo_s = _env_slo("LLM_TPU_TTFT_SLO_S")
        if tpot_slo_s is None:
            tpot_slo_s = _env_slo("LLM_TPU_TPOT_SLO_S")
        self.goodput = GoodputMeter(ttft_slo_s, tpot_slo_s,
                                    tracer=self.tracer)
        # unified metrics registry: one canonical exposition renderer
        # over the live router/cache counters (obs/registry.py). Built
        # LAST — the callbacks close over attributes set above.
        self.registry = self._build_registry()

    # --- upstream I/O --------------------------------------------------------

    def _forward(self, upstream: Upstream, body: dict,
                 stream: bool = False, trace=None) -> tuple[int, object]:
        """POST to one upstream. Non-stream: (status, parsed-JSON dict).
        Stream success: (200, stream handle) — the caller relays the SSE
        bytes and closes it; ``pending`` is held until that close, so the
        replica counts as busy for the stream's whole lifetime (the
        autoscaler's drain check and least-pending routing both rely on
        this). ``trace``: the request's TraceContext, propagated as a
        traceparent header so the replica's spans join the trace."""
        # canary legs register with no model mapping — they serve
        # whatever the request asked for (same group, newer build)
        payload = dict(body, model=upstream.model or body.get("model"))
        headers = {"Content-Type": "application/json"}
        if trace is not None:
            headers["traceparent"] = format_traceparent(trace)
        req = urllib.request.Request(
            f"{upstream.base_url}/v1/chat/completions",
            data=json.dumps(payload).encode(),
            headers=headers,
        )
        with upstream.lock:
            upstream.pending += 1
            upstream.served += 1
        handed_off = False
        try:
            if stream:
                r = urllib.request.urlopen(req, timeout=self.timeout_s)

                def release():
                    with upstream.lock:
                        upstream.pending -= 1

                handed_off = True
                return r.status, _StreamHandle(r, release)
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read())
            except Exception:
                detail = {"error": {"message": str(e)}}
            return e.code, detail
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            return 0, {"error": {"message": f"upstream unreachable: {e}"}}
        finally:
            if not handed_off:
                with upstream.lock:
                    upstream.pending -= 1

    def _disagg_prefill(self, group: str, body: dict,
                        parent=None) -> dict:
        """Phase one of disaggregated dispatch: have a prefill-pool
        replica compute and pin the prompt KV, and return the body the
        decode-pool forward should carry (``kv_transfer_params``). Any
        failure degrades to the plain single-phase path — the body comes
        back unchanged and whichever upstream serves it prefills
        locally (the decode replica counts that). ``parent``: the
        request's root span — the prefill phase records under it and
        the handoff body carries the trace id to the decode replica."""
        pick_prefill = getattr(self.router, "pick_prefill", None)
        if pick_prefill is None:
            return body
        upstream = pick_prefill(group)
        if upstream is None:
            return body
        span = self.tracer.start_span("gateway.prefill_phase",
                                      parent=parent,
                                      upstream=upstream.base_url)
        try:
            return self._disagg_prefill_call(group, body, upstream, span)
        finally:
            span.end()

    def _disagg_prefill_call(self, group: str, body: dict,
                             upstream: Upstream, span) -> dict:
        # the handoff namespace is the MODEL name: a prefill upstream
        # publishing as m1 can never be claimed by a decode upstream
        # serving m2 — every handoff would silently expire as 'lost'
        # while doubling prefill cost. Skip the phase (warned once).
        dec_models = {u.model
                      for u in (self.router._role_pool(group, "decode")
                                or self.router._role_pool(group, "both"))}
        if dec_models and upstream.model not in dec_models:
            if group not in self._disagg_model_warned:
                self._disagg_model_warned.add(group)
                import logging

                logging.getLogger(__name__).warning(
                    "disagg disabled for group %r: prefill upstream "
                    "serves model %r but the decode pool serves %s — "
                    "handoff namespaces would never match; fix the "
                    "--upstream model names",
                    group, upstream.model, sorted(dec_models))
            with self._stats_lock:
                self.handoff_failed_total += 1
            return body
        ctx = span.context()
        headers = {"Content-Type": "application/json"}
        if ctx is not None:
            headers["traceparent"] = format_traceparent(ctx)
        req = urllib.request.Request(
            f"{upstream.base_url}/internal/handoff/prefill",
            data=json.dumps({"messages": body.get("messages", []),
                             "model": upstream.model}).encode(),
            headers=headers,
        )
        # the prefill call occupies the replica exactly like a
        # completion does — least-pending over the prefill pool needs it
        with upstream.lock:
            upstream.pending += 1
            upstream.served += 1
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                resp = json.loads(r.read())
            hid = resp["handoff_id"]
        except urllib.error.HTTPError as e:
            # 501 = this replica/model cannot prefill for handoff (e.g.
            # a LoRA adapter engine without a handoff store) — the
            # upstream is HEALTHY, so don't feed the circuit breaker:
            # cooling it down would pull it from rotation for every
            # model it serves
            if e.code != 501:
                upstream.record_failure(time.time())
            with self._stats_lock:
                self.handoff_failed_total += 1
            return body
        except (urllib.error.URLError, TimeoutError, OSError,
                ValueError, KeyError):
            upstream.record_failure(time.time())
            with self._stats_lock:
                self.handoff_failed_total += 1
            return body
        finally:
            with upstream.lock:
                upstream.pending -= 1
        upstream.record_success()
        with self._stats_lock:
            self.handoff_total += 1
        span.set(handoff_id=hid, ok=True)
        # the model rides along: the handoff namespace IS the model
        # name, so the decode pick must prefer replicas serving it —
        # and the trace id rides with it, so the decode replica's claim
        # span joins this request's trace even if an intermediary
        # strips the traceparent header
        xfer = {"handoff_id": hid, "model": upstream.model}
        if ctx is not None:
            xfer["trace"] = format_traceparent(ctx)
        return dict(body, kv_transfer_params=xfer)

    def _estimate_tokens(self, body: dict) -> int:
        chars = sum(len(str(m.get("content", "")))
                    for m in body.get("messages", []))
        return chars // 4 + int(body.get("max_tokens", 0) or 0)

    def _chain(self, group: str) -> list[str]:
        """Model group + its fallback chain, context-window-aware."""
        chain = [group]
        chain += [g for g in self.fallbacks.get(group, []) if g not in chain]
        return chain

    def handle_completion(self, body: dict, stream: bool = False,
                          trace=None) -> tuple[int, object]:
        """Route one completion. ``stream=True`` returns ``(200, open http
        response)`` on success (relay its bytes); errors are (status, dict)
        either way. The cache only serves non-stream requests.
        ``trace``: an incoming TraceContext (from a client traceparent
        header); ``None`` starts a fresh trace rooted here."""
        t0 = time.monotonic()
        span = self.tracer.start_span(
            "gateway.route", parent=trace,
            model=body.get("model"), stream=bool(stream))
        try:
            status, resp = self._route(body, stream, span)
            span.set(status=status)
            if status == 200:
                trace_id = getattr(span.context(), "trace_id", None)
                tenant = str(body.get("model") or "")
                if isinstance(resp, dict):
                    if not resp.get("cached"):
                        # non-stream: only end-to-end latency is
                        # observable here — the goodput meter applies
                        # the request-level deadline
                        # ttft_slo + (n-1)·tpot_slo
                        tokens = int((resp.get("usage") or {})
                                     .get("completion_tokens") or 0)
                        violated = None
                        if self.goodput.enabled:
                            violated = self.goodput.observe(
                                tokens=tokens,
                                total_s=time.monotonic() - t0,
                                trace_id=trace_id)
                        self._tenant_debit(tenant, tokens, violated)
                else:
                    # streaming: the SSE relay measures TTFT/TPOT on
                    # the wire and books the request (goodput + tenant
                    # debit) at stream close
                    if self.goodput.enabled:
                        resp._goodput_t0 = t0
                        resp._goodput_trace_id = trace_id
                    resp._tenant = tenant
            return status, resp
        finally:
            # streaming success: the span closes at headers-received —
            # the stream's lifetime belongs to the replica's api.chat
            # span; this one is the routing decision + connect
            span.end()

    def _route(self, body: dict, stream: bool,
               span) -> tuple[int, object]:
        with self._stats_lock:
            self.requests_total += 1
        group = body.get("model") or (self.router.groups() or ["default"])[0]

        if self.moderation is not None:
            for msg in body.get("messages", []):
                if msg.get("role") != "user":
                    continue
                flagged, categories = self.moderation(str(msg.get("content", "")))
                if flagged:
                    return 400, {"error": {
                        "message": "request blocked by content moderation",
                        "type": "moderation_blocked",
                        "categories": categories,
                    }}

        if self.cache is not None and not stream:
            with self.tracer.span("gateway.cache_lookup",
                                  parent=span) as cs:
                cached = self.cache.get(body)
                cs.set(hit=cached is not None)
            if cached is not None:
                resp = dict(cached)
                resp["cached"] = True
                return 200, resp

        # per-tenant quota admission — after the cache (a cached reply
        # costs no upstream tokens, so it is never charged or refused)
        if not self._tenant_admit(group):
            return 429, {"error": {
                "message": f"tenant {group!r} token quota exhausted "
                           "(retry after the bucket refills)",
                "type": "tenant_quota_exhausted",
            }}

        # weighted canary split — after admission (canary traffic is
        # still tenant-billed traffic) and OUTSIDE the retry chain: a
        # canary leg that errors falls through to the stable path
        # below, so the canary can never lose a request
        if self.canary_upstreams:
            got = self._canary_try(group, body, stream, span)
            if got is not None:
                return got

        # context-window fallback: if the estimate exceeds the group's
        # window, skip straight to the larger-context chain
        chain = self._chain(group)
        limit = self.max_context_tokens.get(group)
        if limit and self._estimate_tokens(body) > limit:
            cw = [g for g in self.context_window_fallbacks.get(group, [])]
            if cw:
                chain = cw + [g for g in chain if g not in cw]
                with self._stats_lock:
                    self.fallbacks_total += 1

        # disaggregated dispatch (DisaggRouter only): prefill the prompt
        # at the prefill pool first; the forwarded body then carries the
        # handoff id. Only the primary group gets it — a fallback group
        # is a different model whose KV namespace cannot use this entry.
        handoff_body = (self._disagg_prefill(group, body, parent=span)
                        if chain and chain[0] == group else body)

        last_status, last_detail = 502, {"error": {"message": "no upstream"}}
        for gi, g in enumerate(chain):
            if gi > 0:
                with self._stats_lock:
                    self.fallbacks_total += 1
            g_body = handoff_body if g == group else body
            tried: set[int] = set()
            retriable = True
            while True:
                try:
                    upstream = self.router.pick_for_request(
                        g, g_body, exclude=tried)
                except RouterError:
                    break
                tried.add(id(upstream))
                attempts = 0
                while True:
                    status, resp = self._forward(upstream, g_body,
                                                 stream=stream,
                                                 trace=span.context())
                    if status == 200:
                        upstream.record_success()
                        if stream:
                            return 200, resp  # open response; caller relays
                        resp["model"] = g
                        if self.cache is not None:
                            self.cache.put(body, resp)
                        return 200, resp
                    retriable = status in (0, 429) or status >= 500
                    if retriable:
                        upstream.record_failure(time.time())
                        with self._stats_lock:
                            self.failures_total += 1
                    last_status, last_detail = (status or 502), resp
                    max_r = self.retry_policy.retries_for(
                        None if status == 0 else status)
                    if not retriable or attempts >= max_r:
                        break
                    time.sleep(self.retry_policy.backoff_s * 2 ** attempts)
                    attempts += 1
                if not retriable:
                    # a 4xx from one upstream will 4xx everywhere; stop
                    return last_status, last_detail
        return last_status, last_detail

    # --- canary routing ------------------------------------------------------

    def _canary_pick(self) -> Upstream | None:
        """One uniform draw against the cumulative canary weights; None
        = the stable path. The draw serializes on _stats_lock (a shared
        ``random.Random`` is not thread-safe)."""
        with self._stats_lock:
            r = self._canary_rng.random()
        acc = 0.0
        for up in self.canary_upstreams:
            acc += self.canary_weights.get(up.base_url, 0.0)
            if r < acc:
                return up
        return None

    def _canary_try(self, group: str, body: dict, stream: bool,
                    span) -> tuple[int, object] | None:
        """Forward one sampled request to a canary leg. None = not
        sampled, or the leg failed — either way the caller runs the
        stable path. Canary responses are never written to the response
        cache: a regressed canary must not poison answers later served
        to stable traffic."""
        up = self._canary_pick()
        if up is None:
            return None
        cs = self.tracer.start_span("gateway.canary", parent=span.context(),
                                    upstream=up.base_url)
        try:
            status, resp = self._forward(up, body, stream=stream,
                                         trace=cs.context())
            ok = status == 200
            cs.set(status=status, ok=ok)
            with self._stats_lock:
                key = (up.base_url, "ok" if ok else "error")
                self._canary_requests[key] = (
                    self._canary_requests.get(key, 0) + 1)
            if not ok:
                return None
            if not stream and isinstance(resp, dict):
                resp["model"] = group
                self._canary_golden_shadow(group, body, resp, cs)
            return status, resp
        finally:
            cs.end()

    def _canary_golden_shadow(self, group: str, body: dict,
                              canary_resp: dict, span) -> None:
        """Golden-token comparison: re-run a sampled deterministic
        (``temperature == 0``) canary hit against a stable upstream and
        compare the answer texts. A mismatch is the hard half of the
        canary verdict — identical builds must produce identical greedy
        tokens, so ANY mismatch means the canary decodes differently.
        Only explicit temperature-0 requests compare (sampled decoding
        would mismatch by design); stable-side failures are simply not
        a sample, never a verdict signal."""
        if self.canary_golden_rate <= 0:
            return
        if body.get("temperature", 1) != 0:
            return
        with self._stats_lock:
            sampled = self._canary_rng.random() < self.canary_golden_rate
        if not sampled:
            return
        try:
            upstream = self.router.pick_for_request(group, body)
        except RouterError:
            return
        status, ref = self._forward(upstream, body, trace=span.context())
        if status != 200 or not isinstance(ref, dict):
            return

        def _text(r):
            try:
                return r["choices"][0]["message"]["content"]
            except (KeyError, IndexError, TypeError):
                return None

        result = ("match" if _text(canary_resp) == _text(ref)
                  else "mismatch")
        span.set(golden=result)
        with self._stats_lock:
            self._canary_golden[result] = (
                self._canary_golden.get(result, 0) + 1)

    def _canary_snapshot(self) -> tuple[dict, dict]:
        """Canary counters read under their lock — the one helper the
        scrape callbacks and fleet_payload go through."""
        with self._stats_lock:
            return dict(self._canary_requests), dict(self._canary_golden)

    def _counter_snapshot(self) -> dict:
        """Request-plane counters read under their lock — the one
        helper the scrape callbacks go through (each family is a single
        int; Prometheus never promises cross-family atomicity, so each
        callback snapshotting independently is fine — the lock is held
        per collect, a few uncontended acquisitions per scrape)."""
        with self._stats_lock:
            return {
                "requests": self.requests_total,
                "failures": self.failures_total,
                "fallbacks": self.fallbacks_total,
                "handoff": self.handoff_total,
                "handoff_failed": self.handoff_failed_total,
            }

    # --- tenant fairness -----------------------------------------------------

    def _tenant_capacity(self, tenant: str) -> float:
        return (self.tenant_quotas[tenant]
                * self.tenant_weights.get(tenant, 1.0))

    def _tenant_admit(self, tenant: str) -> bool:
        """Refill tenant's bucket and admit while the balance is
        positive (quota-less tenants always pass). The refill rate is
        capacity / window, so a weight-2 tenant both bursts deeper AND
        recovers faster — proportional share, not just burst."""
        quota = self.tenant_quotas.get(tenant)
        if quota is None:
            return True
        cap = self._tenant_capacity(tenant)
        with self._tenant_lock:
            now = time.monotonic()
            bal = self._tenant_balance.get(tenant, cap)
            t_last = self._tenant_refill_t.get(tenant, now)
            bal = min(cap, bal + (now - t_last) * cap
                      / self.tenant_quota_window_s)
            self._tenant_refill_t[tenant] = now
            self._tenant_balance[tenant] = bal
            if bal <= 0.0:
                self.tenant_rejections[tenant] = (
                    self.tenant_rejections.get(tenant, 0) + 1)
                return False
            return True

    def _tenant_debit(self, tenant: str, tokens: int,
                      violated: bool | None = None) -> None:
        """Book delivered output tokens against tenant's bucket and
        per-tenant counters. ``violated``: the goodput verdict for the
        request these tokens came from (None = accounting off)."""
        if not tenant:
            return
        with self._tenant_lock:
            self.tenant_tokens[tenant] = (
                self.tenant_tokens.get(tenant, 0) + tokens)
            if violated is not None:
                d = self.tenant_goodput.setdefault(
                    tenant, {"ok": 0, "violated": 0})
                d["violated" if violated else "ok"] += tokens
            if tenant in self.tenant_quotas:
                bal = self._tenant_balance.get(
                    tenant, self._tenant_capacity(tenant))
                self._tenant_balance[tenant] = bal - tokens

    def _tenant_snapshot(self) -> dict:
        """Per-tenant counters read under their lock — the one helper
        the scrape callbacks go through (mirrors _counter_snapshot)."""
        with self._tenant_lock:
            return {
                "tokens": dict(self.tenant_tokens),
                "rejections": dict(self.tenant_rejections),
                "goodput": {t: dict(d)
                            for t, d in self.tenant_goodput.items()},
                "balance": dict(self._tenant_balance),
            }

    # --- health checks -------------------------------------------------------

    def _health_loop(self):
        while not self._stop.wait(self.health_check_interval_s):
            for u in self.router.upstreams:
                try:
                    with urllib.request.urlopen(
                        f"{u.base_url}/health", timeout=5
                    ) as r:
                        ok = r.status == 200
                except OSError:
                    ok = False
                if ok:
                    # Reset the consecutive-fail count but DON'T clear an
                    # active cooldown: an upstream can pass /health while
                    # 429/500-ing completions, and clearing here would cap
                    # every cooldown at one health interval.
                    u.record_success()
                else:
                    u.record_failure(time.time())

    # --- HTTP ----------------------------------------------------------------

    def _build_registry(self) -> Registry:
        """Scrape-time families over the live gateway/router/cache
        counters. The per-upstream series now carry ``# TYPE`` headers
        (they were emitted bare, which strict Prometheus parsers reject
        — the bug the registry migration subsumes and the exposition
        tests pin); the label set/order is unchanged so existing
        dashboards keep matching."""
        reg = Registry()
        # build identity (obs/buildinfo.py): the same family on every
        # server in the stack — GET /fleet groups replicas by it
        from llm_in_practise_tpu.obs.buildinfo import register_build_info

        register_build_info(reg, {
            "server": "gateway",
            "router": type(self.router).__name__,
            "groups": self.router.groups(),
            "cache": type(self.cache).__name__ if self.cache else None,
            "ttft_slo_s": self.goodput.ttft_slo_s,
            "tpot_slo_s": self.goodput.tpot_slo_s,
            "canary": sorted(self.canary_weights),
        })
        reg.counter_func("gateway_requests_total",
                         lambda: self._counter_snapshot()["requests"],
                         "completions routed")
        reg.counter_func("gateway_upstream_failures_total",
                         lambda: self._counter_snapshot()["failures"],
                         "retriable upstream failures observed")
        reg.counter_func("gateway_fallbacks_total",
                         lambda: self._counter_snapshot()["fallbacks"],
                         "fallback-chain hops taken")
        if self.cache is not None:
            cache = self.cache
            reg.counter_func("gateway_cache_hits_total",
                             lambda: cache.hits)
            reg.counter_func("gateway_cache_semantic_hits_total",
                             lambda: cache.semantic_hits)
            reg.counter_func("gateway_cache_misses_total",
                             lambda: cache.misses)
            # remote caches additionally track lookups that never
            # reached the service (cooldown/transport) — without this
            # series an outage reads as zero cache traffic instead of
            # degraded
            if hasattr(cache, "skipped"):
                reg.counter_func("gateway_cache_skipped_total",
                                 lambda: cache.skipped)
        reg.counter_func("gateway_handoff_total",
                         lambda: self._counter_snapshot()["handoff"],
                         "prefill phases that published KV")
        reg.counter_func("gateway_handoff_failed_total",
                         lambda: self._counter_snapshot()["handoff_failed"],
                         "prefill phases that errored (degraded)")
        reg.counter_func(
            "gateway_disagg_degraded_total",
            lambda: getattr(self.router, "degraded_picks", 0),
            "picks served outside the role split")
        # SLO goodput: tokens/requests priced by whether the request
        # met its TTFT/TPOT SLOs, plus per-phase blame from the span
        # ring (docs/observability.md "Device plane"). All-zero until
        # thresholds are configured.
        from llm_in_practise_tpu.obs.meter import register_goodput

        register_goodput(reg, self.goodput,
                         subject="routed output tokens")

        def per_upstream(value_of):
            def collect():
                return [({"group": u.group, "url": u.base_url,
                          "role": u.role}, value_of(u))
                        for u in self.router.upstreams]
            return collect

        reg.gauge_func("gateway_upstream_pending",
                       per_upstream(lambda u: u.pending))
        reg.gauge_func(
            "gateway_upstream_available",
            per_upstream(lambda u: int(u.available(time.time()))))
        reg.counter_func("gateway_upstream_picks_total",
                         per_upstream(lambda u: u.picks))
        reg.counter_func("gateway_upstream_cooldowns_total",
                         per_upstream(lambda u: u.cooldowns))
        reg.counter_func("gateway_upstream_affinity_hits_total",
                         per_upstream(lambda u: u.affinity_hits))

        # session ring (HashRingRouter, ISSUE 17): registered
        # unconditionally — other router classes have no ring_snapshot,
        # so the families are present with no samples, and the
        # metric-docs census sees one stable set either way
        def _ring(read_one):
            def collect():
                snap = getattr(self.router, "ring_snapshot", None)
                return [] if snap is None else read_one(snap())
            return collect

        reg.counter_func(
            "gateway_ring_picks_total",
            _ring(lambda s: [({"choice": k}, v)
                             for k, v in sorted(s["picks"].items())]),
            "ring routing decisions (primary owner / bounded-load "
            "second choice / least-pending fallback)")
        reg.counter_func("gateway_ring_rebuilds_total",
                         _ring(lambda s: [({}, s["rebuilds"])]),
                         "ring rebuilds on upstream topology change")
        reg.counter_func("gateway_ring_remapped_total",
                         _ring(lambda s: [({}, s["remapped"])]),
                         "tracked keys whose owner changed between "
                         "consecutive picks (~1/N per join/leave)")
        reg.gauge_func("gateway_ring_sessions_tracked",
                       _ring(lambda s: [({}, s["tracked"])]),
                       "keys in the remap-accounting LRU window")

        # per-tenant fairness plane (multi-LoRA serving, ISSUE 15):
        # registered unconditionally — tenants appear as they first
        # route; without quotas the rejection/balance families render
        # no samples. All reads go through _tenant_snapshot (one lock
        # acquisition per family collect).
        def per_tenant(key):
            def collect():
                return [({"tenant": t}, v) for t, v in
                        sorted(self._tenant_snapshot()[key].items())]
            return collect

        reg.counter_func("gateway_tenant_tokens_total",
                         per_tenant("tokens"),
                         "completion tokens delivered per tenant "
                         "(streaming: wire-delta lower bound)")
        reg.counter_func("gateway_tenant_quota_rejections_total",
                         per_tenant("rejections"),
                         "requests 429'd at the tenant token bucket")
        reg.counter_func(
            "gateway_tenant_goodput_tokens_total",
            lambda: [({"tenant": t, "slo": slo}, d[slo])
                     for t, d in sorted(
                         self._tenant_snapshot()["goodput"].items())
                     for slo in ("ok", "violated")],
            "per-tenant output tokens by the SLO outcome of their "
            "request (empty until goodput thresholds are configured)")
        reg.gauge_func("gateway_tenant_quota_balance",
                       per_tenant("balance"),
                       "current token-bucket balance per quota'd "
                       "tenant (negative = overdrawn, refilling)")

        # canary plane (ISSUE 18): registered unconditionally — with no
        # --canary legs both families render no samples, and the
        # metric-docs census sees one stable set either way
        reg.counter_func(
            "gateway_canary_requests_total",
            lambda: [({"url": url, "outcome": outcome}, v)
                     for (url, outcome), v in
                     sorted(self._canary_snapshot()[0].items())],
            "requests sampled onto a canary leg by outcome (an 'error' "
            "fell back to the stable path — the request was not lost)")
        reg.counter_func(
            "gateway_canary_golden_total",
            lambda: [({"result": result}, v)
                     for result, v in
                     sorted(self._canary_snapshot()[1].items())],
            "golden-token comparisons of deterministic canary answers "
            "against a stable upstream (any mismatch => rollback)")
        return reg

    def metrics_text(self) -> str:
        return self.registry.render()

    def fleet_payload(self) -> dict:
        """``GET /fleet``: poll every upstream (stable pools + canary
        legs) through the reset-safe collector (obs/fleet.py) and
        return the fleet scoreboard plus a promotion/rollback verdict
        per distinct canary version. The collector persists across
        calls — that is what makes restarts visible (a reset is a
        *decrease between polls*; a fresh collector would see the
        post-restart counts as the first scrape and undercount)."""
        from llm_in_practise_tpu.obs.fleet import FleetCollector

        stable = sorted({u.base_url for u in self.router.upstreams})
        with self._fleet_lock:
            coll = self._fleet_collector
            if coll is None:
                coll = FleetCollector(
                    [], fetch=self._fleet_fetch,
                    timeout_s=min(self.timeout_s, 5.0))
                self._fleet_collector = coll
        # idempotent — picks up topology changes (autoscaler adds)
        for url in stable + sorted(self.canary_weights):
            coll.add_target(url)
        coll.poll()
        board = coll.scoreboard()
        requests_by_leg, golden_counts = self._canary_snapshot()
        by_url = {r["url"]: r for r in board["replicas"]}
        # the baseline is the majority version among STABLE upstreams —
        # a half-rolled fleet still compares against what most of the
        # pool runs
        stable_versions = [by_url[u]["version"] for u in stable
                           if u in by_url]
        baseline = (max(set(stable_versions), key=stable_versions.count)
                    if stable_versions else "unknown")
        golden = ({"samples": sum(golden_counts.values()),
                   "mismatches": golden_counts.get("mismatch", 0)}
                  if golden_counts else None)
        verdicts: dict[str, dict] = {}
        for url in sorted(self.canary_weights):
            version = by_url.get(url, {}).get("version", "unknown")
            if version not in verdicts:
                verdicts[version] = coll.canary_verdict(
                    baseline=baseline, canary=version, golden=golden)
        board["canary"] = {
            "weights": dict(self.canary_weights),
            "golden": dict(golden_counts),
            "requests": [{"url": url, "outcome": outcome, "count": n}
                         for (url, outcome), n in
                         sorted(requests_by_leg.items())],
            "baseline_version": baseline,
            "verdicts": verdicts,
        }
        return board

    def make_handler(self):
        gw = self

        class Handler(JsonHandler):
            def do_GET(self):
                if serve_obs_get(self, gw.metrics_text, gw.tracer):
                    return
                try:
                    if self.path == "/fleet":
                        return self._json(200, gw.fleet_payload())
                    if self.path == "/v1/models":
                        return self._json(200, {
                            "object": "list",
                            "data": [{"id": g, "object": "model"}
                                     for g in gw.router.groups()],
                        })
                except Exception as e:  # noqa: BLE001 — answer the
                    # client; never drop the connection on a GET fault
                    return self._json(500, {"error": {
                        "message": f"{type(e).__name__}: {e}",
                        "type": "internal_error"}})
                return self._json(404, {"error": {"message": "not found"}})

            def do_POST(self):
                if self.path not in ("/v1/chat/completions",
                                     "/debug/profile"):
                    return self._json(404, {"error": {"message": "not found"}})
                body, err = self._read_json()
                if err:
                    return self._json(400, err)
                if serve_obs_post(self, body):
                    return None
                stream = bool(body.get("stream"))
                # the session id rides INTO the body: one field serves
                # the ring key here AND the replica's SessionStore after
                # the forward (headers don't survive _forward; the body
                # does)
                sid = self.headers.get("X-Session-ID")
                if sid and not body.get("session_id"):
                    body["session_id"] = sid
                ctx = parse_traceparent(self.headers.get("traceparent"))
                try:
                    status, resp = gw.handle_completion(body, stream=stream,
                                                        trace=ctx)
                    if stream and status == 200 and not isinstance(resp, dict):
                        return self._relay_sse(resp)
                except Exception as e:  # noqa: BLE001
                    if self._responded:
                        return None
                    status, resp = 500, {"error": {
                        "message": f"{type(e).__name__}: {e}"}}
                return self._json(status, resp)

            def _relay_sse(self, upstream_resp):
                """Pipe the upstream SSE body through unchanged.

                When goodput accounting is on, the relay also measures
                the stream ON THE WIRE: time to the first content delta
                (client-visible TTFT) and the mean gap between deltas
                (TPOT, approximated at delta granularity — the server
                may merge tokens per SSE event, so the wire count is a
                lower bound on tokens and the gap an upper bound on
                TPOT: conservative in the SLO's favor)."""
                self._responded = True
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    upstream_resp.headers.get("Content-Type",
                                              "text/event-stream"),
                )
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                t0 = getattr(upstream_resp, "_goodput_t0", None)
                tenant = getattr(upstream_resp, "_tenant", None)
                count = t0 is not None or bool(tenant)
                first = last = None
                n_deltas = 0
                marker = b'"content"'
                tail = b""   # carry len(marker)-1 bytes across reads so
                # a marker straddling a 4096-byte read boundary still
                # counts (a missed FIRST delta would book one full
                # inter-token gap into TTFT — a false SLO violation)
                try:
                    while True:
                        chunk = upstream_resp.read(4096)
                        if not chunk:
                            break
                        if count:
                            hay = tail + chunk
                            hits = hay.count(marker)
                            tail = hay[-(len(marker) - 1):]
                            if hits:
                                now = time.monotonic()
                                if first is None:
                                    first = now
                                last = now
                                n_deltas += hits
                        self.wfile.write(chunk)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    upstream_resp.close()
                    violated = None
                    if t0 is not None and first is not None:
                        tpot = ((last - first) / (n_deltas - 1)
                                if n_deltas > 1 else None)
                        violated = gw.goodput.observe(
                            tokens=n_deltas, ttft_s=first - t0,
                            tpot_s=tpot,
                            trace_id=getattr(upstream_resp,
                                             "_goodput_trace_id", None))
                    if tenant:
                        # wire-delta count is a lower bound on tokens
                        # (the server may merge tokens per SSE event) —
                        # conservative in the tenant's favor
                        gw._tenant_debit(tenant, n_deltas, violated)

        return Handler

    def serve(self, host: str = "0.0.0.0", port: int = 4000, *,
              background: bool = False) -> int:
        self._httpd = ThreadingHTTPServer((host, port), self.make_handler())
        bound = self._httpd.server_address[1]
        if self.health_check_interval_s > 0:
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True)
            self._health_thread.start()
        if background:
            threading.Thread(
                target=self._httpd.serve_forever, daemon=True).start()
        else:
            self._httpd.serve_forever()
        return bound

    def shutdown(self):
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None

"""Serving packed quantized weights — the W4A16 inference path.

The reference serves its GPTQ/AWQ exports through vLLM
(``quantization="compressed-tensors"`` —
``Quantization/LLM-Compressor/GPTQ/eval_qwen3_4b_gptq.py:11-21``): weights
stay 4-bit in GPU memory and dequantize inside the matmul kernels. Here
:class:`QuantizedModel` gives the continuous-batching engine the same
property: it walks like a model (``apply`` / ``init_cache`` / ``config``)
but its "params" tree carries packed
Int4/AWQ/NF4 leaves, and every Dense runs through the fused Pallas
dequant-matmuls (:func:`~llm_in_practise_tpu.peft.fused.fused_quant_apply`)
— the bf16 weight copy never exists in HBM.

Usage::

    qtree, meta = quant_io.load_packed(dir)          # 4-bit on disk
    engine = InferenceEngine(QuantizedModel(model), qtree, ...)
"""

from __future__ import annotations

import jax.numpy as jnp

from llm_in_practise_tpu.peft.fused import fused_quant_apply


class QuantizedModel:
    """Model facade: ``apply({"params": qtree}, ...)`` serves the packed
    tree through the fused kernels; everything else delegates.

    ``mesh``: pass the serving mesh to run sharded (TP) — the packed tree
    should then be placed with
    :func:`~llm_in_practise_tpu.quant.sharding.shard_quant_tree` and the
    forward switches to the SPMD-partitionable XLA dequant path (Pallas
    custom calls are opaque to the partitioner). Matches vLLM's TP=2
    quantized serving (reference ``Fine-Tuning/README.md:345-349``).
    ``use_kernels`` overrides the automatic choice."""

    def __init__(self, model, *, compute_dtype=jnp.bfloat16, mesh=None,
                 use_kernels: bool | None = None):
        self.model = model
        self.compute_dtype = compute_dtype
        if use_kernels is None:
            use_kernels = mesh is None or all(
                mesh.shape[n] == 1 for n in mesh.shape
                if n not in ("data",)
            )
        self.use_kernels = use_kernels

    @property
    def config(self):
        return self.model.config

    @property
    def cache_slot_axis(self) -> int:
        return getattr(self.model, "cache_slot_axis", 0)

    def init_cache(self, *args, **kwargs):
        return self.model.init_cache(*args, **kwargs)

    def apply(self, variables, *args, **kwargs):
        return fused_quant_apply(
            self.model, variables["params"], *args,
            compute_dtype=self.compute_dtype,
            use_kernels=self.use_kernels, **kwargs,
        )

"""Disaggregated prefill/decode serving — role-split replicas with KV
handoff over the tiered pool.

The reference platform's llm-d stage (``LLM_on_Kubernetes/
Inference_Platfrom/08-LLM-Router``) splits serving into a **prefill pool**
and a **decode pool**: prefill is compute-bound, decode is bandwidth-bound
("Dissecting the Runtime Performance of … LLMs", arxiv 2311.03687), so
co-locating them trades TTFT against TPOT no matter how well one engine
fuses the two (PR 1 removed the per-step dispatch tax; the *cross-request*
interference — a 1,700 ms cold prefill stalling every decoder's block —
remains structural). Here:

- a **prefill replica** (``--role prefill``) runs chunked prefill only.
  On completion it publishes the full prompt KV as a pinned
  :class:`~.kv_pool.HostEntry` in the handoff namespace of the shared
  pool (``KVPoolServer`` ``hput``/``hclaim`` — pin-until-claimed, so LRU
  eviction can never race the claim; TTL-reclaimed if the decode side
  dies), then finishes the request with ``finish_reason="handoff"``.
- a **decode replica** (``--role decode``) claims the entry and admits
  the request through the engine's full-prefix-hit direct-insert path:
  the slot starts at ``index == len(prompt)`` with zero mid-prefill rows,
  so decode blocks never share a dispatch with somebody else's prefill
  chunk (``llm_mixed_blocks_total`` stays 0 by construction).
- the :class:`~.gateway.DisaggRouter` sequences the two calls and
  degrades gracefully: an empty pool or a lost handoff entry means the
  serving replica re-prefills locally (logged + counted) — correctness
  never depends on the handoff succeeding.

This module holds the handoff stores the roles speak through:
:class:`LocalHandoff` (in-process — tests, single-host multi-engine) and
:class:`RemoteHandoff` (the shared :class:`~.kv_pool.KVPoolServer`).
Both expose ``publish``/``claim`` with the same lost-entry semantics.

Observability (docs/observability.md): the gateway's two-phase dispatch
rides the request's trace id through ``kv_transfer_params`` — alongside
``handoff_id`` and ``model`` it carries ``trace`` (a traceparent-format
string), so the decode replica's ``handoff.claim`` span joins the same
trace as the prefill replica's ``handoff.publish`` span even when an
intermediary strips HTTP headers. The pool server's handoff counters
(pins/claims/TTL-reclaims/bytes) export at its ``--metrics-port``.
"""

from __future__ import annotations

import threading
import time
import uuid

from llm_in_practise_tpu.obs.logging import get_logger
from llm_in_practise_tpu.serve.kv_pool import (
    HandoffRejected,
    HostEntry,
    RemoteKVClient,
)

ROLES = ("prefill", "decode", "both")

# reserved namespace prefix for handoff entries on a shared pool server:
# they must never collide with the model's ordinary prefix-cache
# namespace (a handoff entry is pinned and claim-once; a prefix entry is
# LRU'd and shared)
HANDOFF_NS_PREFIX = "__handoff__/"


def new_handoff_id() -> str:
    return uuid.uuid4().hex


def validate_roles(role: str) -> str:
    if role not in ROLES:
        raise ValueError(f"role must be one of {ROLES}, got {role!r}")
    return role


#: default ngram proposal length for decode replicas (ISSUE 9 /
#: ROADMAP item 4): decode is bandwidth-bound, a decode replica never
#: prefills by design, and the fused spec round is greedy-lossless —
#: so speculation is the production default there, not an opt-in.
DECODE_DEFAULT_SPEC_K = 4


def default_speculative_k(role: str, requested: int | None) -> int | None:
    """Resolve the serving CLI's ``--speculative`` value for ``role``.

    ``--role decode`` replicas default speculation ON
    (:data:`DECODE_DEFAULT_SPEC_K`, the ngram proposer — no extra
    weights, lossless under greedy, and the fused verify rides the
    multi-step dispatch so it composes with ``--decode-steps``).
    An explicit ``--speculative 0`` opts out; any positive value is
    passed through; other roles keep speculation opt-in.
    """
    if requested == 0:
        return None
    if requested is None and role == "decode":
        return DECODE_DEFAULT_SPEC_K
    return requested


class LocalHandoff:
    """In-process handoff store: pin-until-claimed dict with TTL reclaim.

    Semantics match the pool server's handoff namespace exactly — tests
    and single-process multi-engine setups (chip sharing) use this so
    the role split is exercisable without a TCP pool."""

    def __init__(self, *, ttl_s: float = 120.0, clock=None):
        self.ttl_s = ttl_s
        self._clock = clock or time.monotonic
        self._entries: dict[str, tuple[float, HostEntry]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.published = 0
        self.claimed = 0
        self.expired = 0

    def _sweep_locked(self, now: float) -> None:
        dead = [k for k, (exp, _) in self._entries.items() if exp <= now]
        for k in dead:
            del self._entries[k]
            self.expired += 1

    def publish(self, handoff_id: str, host: HostEntry) -> None:
        now = self._clock()
        with self._lock:
            self._sweep_locked(now)
            self._entries[handoff_id] = (now + self.ttl_s, host)
            self.published += 1

    def claim(self, handoff_id: str) -> HostEntry | None:
        now = self._clock()
        with self._lock:
            self._sweep_locked(now)
            found = self._entries.pop(handoff_id, None)
            if found is None:
                return None
            self.claimed += 1
            return found[1]

    def pending(self) -> int:
        with self._lock:
            return len(self._entries)


class RemoteHandoff:
    """Handoff store over a shared :class:`~.kv_pool.KVPoolServer`.

    ``namespace`` is the served model's identity (the same string the
    model's :class:`~.kv_pool.RemoteKVClient` uses) — the handoff keys
    get the reserved ``__handoff__/`` prefix on top, so prefix-cache
    traffic and handoff traffic of one model never collide, and two
    models' handoffs are isolated exactly like their KV."""

    def __init__(self, address, *, namespace: str = "",
                 timeout: float = 5.0):
        self._client = RemoteKVClient(
            tuple(address), timeout=timeout,
            namespace=HANDOFF_NS_PREFIX + namespace)
        self._log = get_logger("serve.disagg")
        # publishes run on the engine's publisher POOL and claims on
        # concurrent HTTP handler threads — bare `+= 1` across those
        # loses counts (read-modify-write is not GIL-atomic)
        self._lock = threading.Lock()
        self.published = 0        # guarded-by: _lock
        self.publish_errors = 0   # guarded-by: _lock
        self.claimed = 0          # guarded-by: _lock
        self.claim_errors = 0     # guarded-by: _lock

    @property
    def address(self):
        return self._client.address

    def publish(self, handoff_id: str, host: HostEntry) -> None:
        """Raises on failure (transport OR pool refusal): the caller is
        about to advertise this id to a decode replica, so a silent drop
        would turn into a guaranteed lost-claim later."""
        try:
            self._client.handoff_put(handoff_id, host)
        except (OSError, HandoffRejected):
            with self._lock:
                self.publish_errors += 1
            raise
        with self._lock:
            self.published += 1

    def claim(self, handoff_id: str) -> HostEntry | None:
        """``None`` = lost (expired / never published / already claimed /
        pool unreachable / reply undecodable) — the caller re-prefills
        locally. Transport AND decode faults are folded into "lost": a
        version-skewed pool returning a garbage manifest must degrade
        the request, not 5xx it."""
        import struct

        try:
            host = self._client.handoff_claim(handoff_id)
        except (OSError, ValueError, KeyError, struct.error) as e:
            with self._lock:
                self.claim_errors += 1
            self._log.warning("handoff claim %s failed (%s: %s) — "
                              "degrading to local prefill",
                              handoff_id, type(e).__name__, e)
            return None
        if host is not None:
            with self._lock:
                self.claimed += 1
        return host


def usable_for_engine(host: HostEntry, prompt_ids, engine) -> str | None:
    """Why a claimed handoff entry can NOT seed ``engine``'s slot for
    ``prompt_ids`` (``None`` = usable). The checks mirror the engine's
    ``_lookup_prefix`` usable() filter plus the full-length requirement
    of the direct-insert path — a mismatched entry (replica configured
    with a different cache layout / cache_len, or a tokenizer drift
    between replicas) degrades to local prefill instead of scattering
    garbage KV."""
    plen = len(prompt_ids)
    if host.length != plen:
        return (f"length mismatch: entry {host.length} vs prompt {plen} "
                "(tokenizer/crop drift between replicas?)")
    if getattr(host, "slot_axis", 0) != engine._sax:
        return (f"cache layout mismatch: entry slot_axis "
                f"{getattr(host, 'slot_axis', 0)} vs engine {engine._sax}")
    if getattr(engine, "paged", None) is None:
        # a contiguous consumer inserts the FULL (post-pow2-padding)
        # bucket width — bound that, or the scatter clamps and corrupts
        # the slot. A PAGED consumer only scatters the first `length`
        # positions, so any wire width is fine there.
        from llm_in_practise_tpu.serve.kv_pool import effective_bucket

        eff = effective_bucket(host)
        if eff > engine.cache_len:
            return (f"entry width {eff} (wire {host.bucket}, pow2-"
                    f"padded for the contiguous insert) exceeds engine "
                    f"cache_len {engine.cache_len}")
    return None

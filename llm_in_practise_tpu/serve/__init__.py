"""Serving: continuous-batching engine + OpenAI-compatible HTTP API.

The TPU-native replacement for the reference's serving ladder — hand-rolled
FastAPI server (``Scripts/inference/07-deepseek1.5b-api-infr.py``), vLLM, and
Ray Serve LLM apps (``Deployment/``): one in-tree engine
(:class:`~llm_in_practise_tpu.serve.engine.InferenceEngine`) with slot-based
continuous batching over a static-shape KV cache, and a dependency-free HTTP
layer (:class:`~llm_in_practise_tpu.serve.api.OpenAIServer`) with streaming
and Prometheus metrics.
"""

from llm_in_practise_tpu.serve.engine import (  # noqa: F401
    InferenceEngine,
    Request,
    SamplingParams,
    shard_params_for_serving,
)
from llm_in_practise_tpu.serve.constrain import (  # noqa: F401
    ConstraintError,
    TokenAutomaton,
    compile_request_constraint,
    compile_schema,
    validate_instance,
)
from llm_in_practise_tpu.serve.arrivals import (  # noqa: F401
    Arrival,
    synthesize as synthesize_arrivals,
)
from llm_in_practise_tpu.serve.api import OpenAIServer, build_prompt  # noqa: F401
from llm_in_practise_tpu.serve.adapters import (  # noqa: F401
    build_adapter_engines,
    load_adapter,
    parse_lora_modules,
)
from llm_in_practise_tpu.serve.gateway import (  # noqa: F401
    DisaggRouter,
    Gateway,
    PrefixAffinityRouter,
    ResponseCache,
    RetryPolicy,
    Router,
    Upstream,
)
from llm_in_practise_tpu.serve.disagg import (  # noqa: F401
    LocalHandoff,
    RemoteHandoff,
    new_handoff_id,
)
from llm_in_practise_tpu.serve.prefix_cache import (  # noqa: F401
    PagedPrefixIndex,
    PrefixCache,
)
from llm_in_practise_tpu.serve.paged_kv import (  # noqa: F401
    PagedKV,
    PagePool,
    pages_for,
)
from llm_in_practise_tpu.serve.kv_pool import (  # noqa: F401
    HostKVPool,
    KVPoolServer,
    RemoteKVClient,
    TieredKV,
)
from llm_in_practise_tpu.serve.autoscale import (  # noqa: F401
    AutoscaleConfig,
    ReplicaAutoscaler,
)
from llm_in_practise_tpu.serve.moderation import (  # noqa: F401
    ModerationService,
    gateway_hook,
    rule_classifier,
)

"""Tiered KV pool — the reference platform's LMCache stage, TPU-native.

The reference extends vLLM's in-HBM prefix cache with LMCache
(``LLM_on_Kubernetes/Inference_Platfrom/07-L1-Cache/LMCache/
vllm-statefulset-lmcache.yaml:65-111``): KV blocks stream to CPU memory
(``LMCACHE_LOCAL_CPU``) and to a remote ``lm://`` pool server
(``lmcache-deployment.yaml``) so a prefix computed by one replica warms
every other replica.

Here the same three tiers fit the slot engine's prefix entries:

- **L1** — :class:`~llm_in_practise_tpu.serve.prefix_cache.PrefixCache`:
  device (HBM) KV rows, in-engine, budget is HBM.
- **L2** — :class:`HostKVPool`: the same entries as host ``numpy`` arrays
  (bfloat16 via ``ml_dtypes``), budget is host RAM — orders of magnitude
  larger. Entries arrive by write-through on prefill and by eviction
  from L1; a lookup re-uploads with ``jax.device_put`` and re-promotes
  into L1.
- **L3** — :class:`KVPoolServer` / :class:`RemoteKVClient`: a stdlib TCP
  pool server (the ``lm://`` analog) holding the serialized entries, so
  multiple engine replicas share one warm-prefix namespace. Transport is
  a length-prefixed JSON header + raw array bytes — no pickle, no
  third-party wire format.

The engine talks to one :class:`TieredKV` facade; lookups cascade
L1 → L2 → L3 and every hit is promoted upward, so the hot set migrates
toward HBM exactly as in the reference's cache hierarchy.
"""

from __future__ import annotations

import dataclasses
import json
import queue
import socket
import socketserver
import struct
import threading
import time

import numpy as np

from llm_in_practise_tpu.obs.logging import get_logger
from llm_in_practise_tpu.serve.prefix_cache import PrefixLRU

try:  # ml_dtypes ships with jax; it provides the numpy bfloat16 scalar type
    import ml_dtypes

    _NAMED_DTYPES = {
        "bfloat16": np.dtype(ml_dtypes.bfloat16),
        "float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
        "float8_e5m2": np.dtype(ml_dtypes.float8_e5m2),
    }
except ImportError:  # pragma: no cover
    _NAMED_DTYPES = {}


def _dtype(name: str) -> np.dtype:
    return _NAMED_DTYPES.get(name) or np.dtype(name)


# --- host-side entry & (de)serialization -----------------------------------


@dataclasses.dataclass
class HostEntry:
    """A prefix-cache entry with every buffer on host as numpy."""

    length: int                 # true token count of the cached prefix
    bucket: int                 # padded length of the stored rows
    rows: list                  # per-layer {name: np.ndarray}
    last_logits: np.ndarray     # (1, vocab) logits at the final position
    slot_axis: int = 0          # cache layout of the rows (PrefixEntry)
    # page-wise entries (kv_layout="paged" producers): rows span
    # ceil(length / page_size) * page_size positions — only live pages
    # travel, not a pow2 bucket (a 200-token prompt ships 208 rows at
    # page_size 16 where the bucket path shipped 256). 0 = legacy
    # bucket-width entry. Consumers of either layout accept both.
    page_size: int = 0
    # Session-handoff entries carry the token ids the rows were computed
    # for: a claiming engine has only a session id, not the producer's
    # prompt, so prefix validation on the consumer side needs the tokens
    # on the wire. None = legacy entry (prefix-keyed pools key by token
    # tuple already, so the field would be redundant there).
    token_ids: list | None = None

    @property
    def pages(self) -> int:
        """Live pages this entry spans (0 for legacy bucket entries)."""
        if self.page_size <= 0:
            return 0
        return -(-self.length // self.page_size)


def entry_to_host(entry) -> HostEntry:
    """Device ``PrefixEntry`` -> :class:`HostEntry` (one transfer per buffer)."""
    import jax

    rows = [{k: np.asarray(jax.device_get(v)) for k, v in layer.items()}
            for layer in entry.rows]
    return HostEntry(
        length=entry.length,
        bucket=entry.bucket,
        rows=rows,
        last_logits=np.asarray(jax.device_get(entry.last_logits)),
        slot_axis=getattr(entry, "slot_axis", 0),
        page_size=getattr(entry, "page_size", 0),
    )


def effective_bucket(entry) -> int:
    """The row width a CONTIGUOUS consumer ends up holding for
    ``entry``: page-aligned (non-pow2) widths are pow2-padded by
    :func:`entry_to_device`, so every cache-fit filter on the consumer
    side must bound THIS width, not the wire width."""
    b = entry.bucket
    if getattr(entry, "page_size", 0) > 0 and b & (b - 1):
        return 1 << (b - 1).bit_length()
    return b


def entry_to_device(host: HostEntry):
    """:class:`HostEntry` -> device ``PrefixEntry`` (replicated placement;
    a TP engine's jitted programs reshard on first use).

    Page-aligned entries (paged producers) are PADDED to the next pow2
    width here, on host, before the upload: a contiguous consumer's
    insert/suffix programs jit on the rows' width, and per-page-count
    widths (208, 224, …) would each be a fresh XLA compile on the
    serving path — pow2 padding restores the bounded
    log2-variants compile set the bucket era had, at a few zero rows of
    transfer. (Paged consumers never call this: they keep entries
    host-side and page-scatter positions.)"""
    import jax

    from llm_in_practise_tpu.serve.prefix_cache import PrefixEntry

    bucket = host.bucket
    rows = host.rows
    padded = effective_bucket(host)
    if padded != bucket:
        seq_axis = host.slot_axis + 1
        rows = []
        for layer in host.rows:
            d = {}
            for k, v in layer.items():
                widths = [(0, 0)] * v.ndim
                widths[seq_axis] = (0, padded - v.shape[seq_axis])
                d[k] = np.pad(v, widths)
            rows.append(d)
        bucket = padded
    rows = [{k: jax.device_put(v) for k, v in layer.items()}
            for layer in rows]
    return PrefixEntry(
        length=host.length,
        bucket=bucket,
        rows=rows,
        last_logits=jax.device_put(host.last_logits),
        slot_axis=host.slot_axis,
        page_size=getattr(host, "page_size", 0),
    )


def encode_entry(host: HostEntry) -> bytes:
    """Self-describing binary blob: JSON manifest + concatenated raw bytes."""
    arrays: list[np.ndarray] = []
    manifest_rows = []
    for layer in host.rows:
        layer_meta = {}
        for name in sorted(layer):
            arr = np.ascontiguousarray(layer[name])
            layer_meta[name] = {"shape": list(arr.shape),
                                "dtype": arr.dtype.name}
            arrays.append(arr)
        manifest_rows.append(layer_meta)
    # session-published entries are page-aligned partials WITHOUT final
    # logits (the consumer recomputes the last position) — a null
    # manifest slot, not a zero-length array
    logits = (None if host.last_logits is None
              else np.ascontiguousarray(host.last_logits))
    manifest = {
        "length": host.length,
        "bucket": host.bucket,
        "slot_axis": host.slot_axis,
        "page_size": host.page_size,
        "rows": manifest_rows,
        "last_logits": None if logits is None else
        {"shape": list(logits.shape), "dtype": logits.dtype.name},
    }
    if host.token_ids is not None:
        # optional key: absent for legacy entries, so old decoders (and
        # old blobs through new decoders) interop unchanged
        manifest["token_ids"] = [int(t) for t in host.token_ids]
    if logits is not None:
        arrays.append(logits)
    head = json.dumps(manifest).encode()
    return b"".join([struct.pack("<I", len(head)), head,
                     *(a.tobytes() for a in arrays)])


def decode_entry(blob: bytes) -> HostEntry:
    (hlen,) = struct.unpack_from("<I", blob, 0)
    manifest = json.loads(blob[4: 4 + hlen].decode())
    off = 4 + hlen

    def take(meta) -> np.ndarray:
        nonlocal off
        dt = _dtype(meta["dtype"])
        n = int(np.prod(meta["shape"])) * dt.itemsize
        arr = np.frombuffer(blob, dtype=dt, count=int(np.prod(meta["shape"])),
                            offset=off).reshape(meta["shape"])
        off += n
        return arr

    rows = [{name: take(meta) for name, meta in sorted(layer.items())}
            for layer in manifest["rows"]]
    lmeta = manifest["last_logits"]
    return HostEntry(length=manifest["length"], bucket=manifest["bucket"],
                     slot_axis=int(manifest.get("slot_axis", 0)),
                     page_size=int(manifest.get("page_size", 0)),
                     rows=rows,
                     last_logits=take(lmeta) if lmeta is not None else None,
                     token_ids=manifest.get("token_ids"))


# --- L2: host-RAM pool ------------------------------------------------------


class HostKVPool(PrefixLRU):
    """LRU of host-resident prefix entries — the shared
    :class:`~.prefix_cache.PrefixLRU` store with :class:`HostEntry`
    values. The budget is host RAM, counted in cached tokens (LMCache's
    ``LMCACHE_MAX_LOCAL_CPU_SIZE`` knob)."""

    def __init__(self, *, max_tokens: int = 1 << 20, min_prefix: int = 16):
        super().__init__(max_tokens=max_tokens, min_prefix=min_prefix)


# --- L3: remote pool server (the ``lm://`` analog) --------------------------


# Framing caps: the wire header declares 32-bit lengths, so an untrusted
# peer could demand ~4 GiB allocations per message. Cap both fields before
# allocating — a violation desyncs the stream, so the connection is closed.
MAX_HEADER_BYTES = 1 << 20          # JSON manifest: token keys only
MAX_PAYLOAD_BYTES = 1 << 30         # one serialized prefix entry


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("kv pool peer closed mid-message")
        buf += chunk
    return bytes(buf)


def _recv_prelude(sock: socket.socket) -> bytes | None:
    """The 8-byte length prelude, or ``None`` on a clean close (EOF at a
    message boundary — a client hanging up between requests is normal
    connection lifecycle, not a protocol fault)."""
    first = sock.recv(1)
    if not first:
        return None
    return first + _recv_exact(sock, 7)


def _send_msg(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    head = json.dumps(header).encode()
    sock.sendall(struct.pack("<II", len(head), len(payload)) + head + payload)


def _recv_msg(
    sock: socket.socket, *,
    max_header: int = MAX_HEADER_BYTES,
    max_payload: int = MAX_PAYLOAD_BYTES,
    prelude: bytes | None = None,
) -> tuple[dict, bytes]:
    hlen, plen = struct.unpack(
        "<II", prelude if prelude is not None else _recv_exact(sock, 8))
    if hlen > max_header or plen > max_payload:
        raise ConnectionError(
            f"kv pool message exceeds caps (header {hlen} > {max_header} or "
            f"payload {plen} > {max_payload}) — closing connection"
        )
    header = json.loads(_recv_exact(sock, hlen).decode())
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


class KVPoolServer:
    """Shared prefix-KV pool over TCP (reference: the LMCache server the
    statefulset points ``LMCACHE_REMOTE_URL: lm://...`` at).

    Keys are token tuples **namespaced by model identity** (the ``ns``
    header) — KV rows are only valid under the weights that produced
    them, so a base model and its LoRA adapters, or two different served
    models, must never cross-hit (LMCache namespaces the same way).
    ``get`` performs the longest-strict-prefix match server-side so
    clients need one round-trip.

    Budgets are **global**, not per-namespace: one LRU spans every
    namespace (the namespace rides as the first key element, so prefix
    matching stays exact and namespaces can never cross-hit), bounded by
    ``max_tokens`` AND ``max_bytes`` (blob sizes are known at put time —
    size ``max_bytes`` to the pod's memory). The namespace set itself is
    bounded (``max_namespaces``): a peer inventing namespaces is refused
    rather than allocating, and lookups against unknown namespaces only
    count a miss.

    Trust boundary: the wire protocol is unauthenticated — bind to
    loopback (the default) or an in-cluster ClusterIP service reachable
    only by the serving pods; framing caps (:func:`_recv_msg`) bound the
    per-message allocation an untrusted peer can demand."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 max_tokens: int = 1 << 22, min_prefix: int = 16,
                 max_bytes: int = 4 << 30, max_namespaces: int = 64,
                 max_payload: int = MAX_PAYLOAD_BYTES,
                 handoff_ttl_s: float = 120.0,
                 max_handoff_bytes: int = 1 << 30, clock=None):
        self.min_prefix = min_prefix
        self.max_tokens = max_tokens
        self.max_bytes = max_bytes
        self.max_namespaces = max_namespaces
        self.max_payload = min(max_payload, max_bytes)
        self.rejected = 0             # puts refused (ns budget / size caps)
        self.evictions = 0            # LRU entries dropped (token/byte caps)
        self._unknown_ns_misses = 0   # gets for namespaces never put to
        # per-connection fault containment: protocol/transport faults are
        # logged and counted, and tear down THAT connection only — the
        # handler thread must never unwind silently (a fleet of serving
        # pods debugging "the pool sometimes loses entries" deserves a
        # counter and a log line, not a vanished thread)
        self.conn_errors = 0
        self._log = get_logger("serve.kv_pool")
        # --- handoff store (disaggregated prefill→decode KV transfer) ---
        # Entries here are PINNED: they live outside the LRU store, so no
        # amount of put pressure can evict one before the decode replica
        # claims it (the claim race the pin exists to close). The bound
        # is instead temporal + byte-budget: unclaimed entries expire
        # after handoff_ttl_s (the decode side treats a miss as "lost"
        # and re-prefills), and puts beyond max_handoff_bytes are
        # refused so a crashed decode pool cannot pin unbounded RAM.
        self.handoff_ttl_s = handoff_ttl_s
        self.max_handoff_bytes = max_handoff_bytes
        self._clock = clock or time.monotonic
        # (ns, id) -> (expires_at, length, bucket, blob, pages)
        self._handoff: dict[tuple[str, str], tuple[float, int, int, bytes, int]] = {}  # guarded-by: _acct_lock
        self._handoff_bytes = 0  # guarded-by: _acct_lock
        # page-wise accounting (paged producers): pinned live pages and
        # their mean byte weight — the ``hput`` header carries the
        # entry's page count, so budgets and TTL reclaim are attributable
        # per page, not just per opaque blob
        self._handoff_pages = 0  # guarded-by: _acct_lock
        self.handoff_puts = 0
        self.handoff_claims = 0
        self.handoff_expired = 0
        self.handoff_rejected = 0
        # per-op wire+serialize latency of the handoff data plane
        # (hput = prefill publish, hclaim = decode claim) — the
        # server-side cross-check of the engine's per-request
        # `handoff_wire` critical-path segment (ISSUE 11 satellite).
        # HistogramAccumulators carry their own locks (handler threads
        # observe, the scrape thread snapshots).
        from llm_in_practise_tpu.obs.registry import HistogramAccumulator

        self.handoff_wire = {"hput": HistogramAccumulator(),
                             "hclaim": HistogramAccumulator()}
        self._namespaces: set[str] = set()  # guarded-by: _acct_lock
        # live entries per namespace: a namespace whose last entry is
        # evicted releases its slot (rolling model redeploys would
        # otherwise exhaust max_namespaces forever)
        self._ns_counts: dict[str, int] = {}  # guarded-by: _acct_lock
        self._total_bytes = 0  # guarded-by: _acct_lock
        # RLock: _put holds it across peek/account/store.put so concurrent
        # puts of the same key cannot double-count, and the store's
        # on_evict (which re-enters for the byte decrement) fires on the
        # same thread inside that region
        self._acct_lock = threading.RLock()
        # One global store. Keys are (ns, tok0, tok1, ...); values are
        # (key_len, bucket, blob, token_length) where key_len counts the
        # ns element, so PrefixLRU's length/prefix logic applies unchanged.
        self._store = PrefixLRU(
            max_tokens=max_tokens, min_prefix=min_prefix + 1,
            length_of=lambda v: v[0], on_evict=self._on_evict)
        pool = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        prelude = _recv_prelude(self.request)
                    except (ConnectionError, OSError) as e:
                        # reset mid-prelude: a transport fault, not a
                        # clean between-messages hangup
                        pool._conn_fault(self.client_address, e)
                        return
                    if prelude is None:
                        return            # clean close between messages
                    try:
                        # wire+serialize timing for the handoff ops
                        # (kvpool_handoff_wire_seconds): prelude-seen →
                        # response-sent covers the payload recv (wire),
                        # the store work, and the reply — the
                        # server-side cross-check of the per-request
                        # handoff_wire critical-path segment
                        t0 = time.perf_counter()
                        header, payload = _recv_msg(
                            self.request, max_payload=pool.max_payload,
                            prelude=prelude)
                        pool._dispatch(self.request, header, payload)
                        acc = pool.handoff_wire.get(header.get("op"))
                        if acc is not None:
                            acc.observe(time.perf_counter() - t0)
                    except Exception as e:  # noqa: BLE001 — malformed
                        # header, over-cap frame, mid-read EOF, bad op
                        # args: contain the fault to THIS connection
                        pool._conn_fault(self.client_address, e)
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        # Prometheus sidecar endpoint: the binary TCP protocol above is
        # the data plane; this registry/HTTP pair is the scrape plane —
        # without it the platform's shared-cache tier was invisible to
        # Prometheus (counters reachable only via the `stats` op).
        from llm_in_practise_tpu.obs.registry import Registry

        self.registry = self._build_registry(Registry())
        self._metrics_httpd = None

    def start(self) -> "KVPoolServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._metrics_httpd is not None:
            self._metrics_httpd.shutdown()
            self._metrics_httpd.server_close()
            self._metrics_httpd = None

    # -- metrics exposition ---------------------------------------------------

    def _build_registry(self, reg):
        # build identity (obs/buildinfo.py): the fleet collector joins
        # every server's series on these labels
        from llm_in_practise_tpu.obs.buildinfo import register_build_info

        register_build_info(reg, {
            "server": "kv_pool",
            "max_tokens": self.max_tokens,
            "max_bytes": self.max_bytes,
            "max_namespaces": self.max_namespaces,
            "min_prefix": self.min_prefix,
        })
        reg.counter_func("kvpool_hits_total", lambda: self.hits,
                         "prefix lookups served from the pool")
        reg.counter_func("kvpool_misses_total", lambda: self.misses,
                         "prefix lookups that found nothing "
                         "(incl. unknown namespaces)")
        reg.counter_func("kvpool_evictions_total", lambda: self.evictions,
                         "LRU entries dropped under token/byte pressure")
        reg.counter_func("kvpool_rejected_total", lambda: self.rejected,
                         "puts refused (namespace budget / size caps)")
        reg.counter_func("kvpool_conn_errors_total",
                         lambda: self.conn_errors,
                         "connections torn down on protocol/transport "
                         "faults")
        reg.gauge_func("kvpool_entries", lambda: self._store.n_entries)
        reg.gauge_func("kvpool_cached_tokens",
                       lambda: (self._store.cached_tokens
                                - self._store.n_entries))
        reg.gauge_func("kvpool_cached_bytes", lambda: self.cached_bytes,
                       "bytes pinned by LRU entries (RAM in use)")
        reg.gauge_func("kvpool_namespaces",
                       lambda: self.n_namespaces)
        reg.counter_func(
            "kvpool_handoff_total",
            lambda: [({"event": "pinned"}, self.handoff_puts),
                     ({"event": "claimed"}, self.handoff_claims),
                     ({"event": "ttl_reclaimed"}, self.handoff_expired),
                     ({"event": "rejected"}, self.handoff_rejected)],
            "disaggregated handoff pins/claims/TTL-reclaims/refusals")
        reg.gauge_func("kvpool_handoff_pending",
                       lambda: self.handoff_pending)
        reg.gauge_func("kvpool_handoff_bytes",
                       lambda: self.handoff_bytes,
                       "bytes pinned by unclaimed handoff entries")
        reg.gauge_func("kvpool_handoff_pages",
                       lambda: self.handoff_pages,
                       "live KV pages pinned by unclaimed page-wise "
                       "handoff entries (0 for bucket-width producers)")
        reg.histogram_func(
            "kvpool_handoff_wire_seconds",
            lambda: [({"op": op}, acc)
                     for op, acc in sorted(self.handoff_wire.items())],
            "handoff op wire+serialize time, prelude-seen to "
            "response-sent (hput = publish, hclaim = claim)")
        return reg

    def metrics_text(self) -> str:
        return self.registry.render()

    def serve_metrics(self, host: str = "0.0.0.0", port: int = 8101) -> int:
        """Start the HTTP ``/metrics`` (+``/health``) endpoint next to
        the TCP data plane; returns the bound port. Idempotent-ish:
        call once, from the owner."""
        import http.server

        from llm_in_practise_tpu.serve.http_util import (
            JsonHandler, serve_obs_get, serve_obs_post,
        )

        pool = self

        class Handler(JsonHandler):
            def do_GET(self):
                # the pool process records no spans of its own yet, but
                # /debug/traces is part of every server's contract —
                # and colocated stacks DO share the process tracer
                if not serve_obs_get(self, pool.metrics_text):
                    self._json(404, {"error": {"message": "not found"}})

            def do_POST(self):
                # POST /debug/profile — same contract as the rest of
                # the stack (colocated engines show up in the capture)
                body, err = self._read_json()
                if err:
                    return self._json(400, err)
                if not serve_obs_post(self, body):
                    self._json(404, {"error": {"message": "not found"}})

        class Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        if self._metrics_httpd is not None:  # re-serve: don't leak the
            # prior listener (its thread would keep the old port bound)
            self._metrics_httpd.shutdown()
            self._metrics_httpd.server_close()
            self._metrics_httpd = None
        self._metrics_httpd = Server((host, port), Handler)
        bound = self._metrics_httpd.server_address[1]
        threading.Thread(target=self._metrics_httpd.serve_forever,
                         daemon=True).start()
        return bound

    # -- ops ----------------------------------------------------------------

    def _conn_fault(self, peer, exc) -> None:
        self.conn_errors += 1
        self._log.warning(
            "kv pool connection from %s closed on fault #%d: %s: %s",
            peer, self.conn_errors, type(exc).__name__, exc)

    def _on_evict(self, key, value) -> None:
        with self._acct_lock:
            self.evictions += 1
            self._total_bytes -= len(value[2])
            ns = key[0]
            n = self._ns_counts.get(ns, 0) - 1
            if n <= 0:
                self._ns_counts.pop(ns, None)
                self._namespaces.discard(ns)   # slot freed for reuse
            else:
                self._ns_counts[ns] = n

    def _dispatch(self, sock, header: dict, payload: bytes) -> None:
        op = header.get("op")
        ns = str(header.get("ns", ""))
        if op == "put":
            ok, why = self._put(ns, tuple(header["key"]),
                                int(header["length"]),
                                int(header["bucket"]), payload)
            _send_msg(sock, {"ok": ok} if ok else {"ok": False, "error": why})
        elif op == "get":
            found = self._get(ns, tuple(header["prompt"]))
            if found is None:
                _send_msg(sock, {"found": False})
            else:
                length, bucket, blob = found
                _send_msg(sock, {"found": True, "length": length,
                                 "bucket": bucket}, blob)
        elif op == "hput":
            ok, why = self._handoff_put(ns, str(header["id"]),
                                        int(header["length"]),
                                        int(header["bucket"]), payload,
                                        pages=int(header.get("pages", 0)))
            _send_msg(sock, {"ok": ok} if ok else {"ok": False, "error": why})
        elif op == "hclaim":
            found = self._handoff_claim(ns, str(header["id"]))
            if found is None:
                _send_msg(sock, {"found": False})
            else:
                length, bucket, blob = found
                _send_msg(sock, {"found": True, "length": length,
                                 "bucket": bucket}, blob)
        elif op == "stats":
            with self._acct_lock:
                total_bytes = self._total_bytes
                n_ns = len(self._namespaces)
                handoff_pending = len(self._handoff)
                handoff_bytes = self._handoff_bytes
            _send_msg(sock, {
                "entries": self._store.n_entries,
                # ns key element is bookkeeping, not a cached token
                "cached_tokens":
                    self._store.cached_tokens - self._store.n_entries,
                "cached_bytes": total_bytes,
                "hits": self.hits, "misses": self.misses,
                "namespaces": n_ns, "rejected": self.rejected,
                "conn_errors": self.conn_errors,
                "handoff_pending": handoff_pending,
                "handoff_bytes": handoff_bytes,
                "handoff_pages": self.handoff_pages,
                "handoff_puts": self.handoff_puts,
                "handoff_claims": self.handoff_claims,
                "handoff_expired": self.handoff_expired,
                "handoff_rejected": self.handoff_rejected,
            })
        else:
            _send_msg(sock, {"ok": False, "error": f"unknown op {op!r}"})

    @property
    def hits(self) -> int:
        return self._store.hits

    @property
    def misses(self) -> int:
        return self._unknown_ns_misses + self._store.misses

    @property
    def cached_bytes(self) -> int:
        with self._acct_lock:
            return self._total_bytes

    # scrape-plane reads of _acct_lock-guarded state go through these
    # locked properties — a /metrics collect must never see a handoff
    # byte total mid-update (the scrape-callback-vs-writer torn read
    # graftlint's guarded-by pass flags)

    @property
    def handoff_bytes(self) -> int:
        with self._acct_lock:
            return self._handoff_bytes

    @property
    def handoff_pages(self) -> int:
        with self._acct_lock:
            return self._handoff_pages

    @property
    def handoff_pending(self) -> int:
        with self._acct_lock:
            return len(self._handoff)

    @property
    def n_namespaces(self) -> int:
        with self._acct_lock:
            return len(self._namespaces)

    @property
    def _entries(self):
        """Aggregated view (tests/introspection only): {(ns, key): value}."""
        with self._store._lock:
            return {(k[0], k[1:]): v for k, v in self._store._entries.items()}

    def _put(self, ns: str, key: tuple, length: int, bucket: int,
             blob: bytes) -> tuple[bool, str]:
        # validate BEFORE consuming any budget: a rejected or silently
        # dropped put must neither burn a namespace slot nor leak bytes
        # into the accounting (PrefixLRU.put drops sub-min_prefix entries)
        if length < self.min_prefix:
            self.rejected += 1
            return False, f"prefix shorter than min_prefix={self.min_prefix}"
        if len(blob) > self.max_payload:
            self.rejected += 1
            return False, "entry larger than max_payload"
        full_key = (ns,) + tuple(key[:length])
        with self._acct_lock:
            if ns not in self._namespaces:
                if len(self._namespaces) >= self.max_namespaces:
                    self.rejected += 1
                    return False, "namespace budget exhausted"
                self._namespaces.add(ns)
            old = self._store.peek(full_key)
            if old is not None:
                self._total_bytes -= len(old[2])
            else:
                self._ns_counts[ns] = self._ns_counts.get(ns, 0) + 1
            self._total_bytes += len(blob)
            self._store.put(full_key, (length + 1, bucket, blob))
            # byte budget: evict globally-LRU entries (any namespace);
            # pop_lru -> on_evict re-enters the RLock for the decrement
            while self._total_bytes > self.max_bytes:
                if self._store.pop_lru() is None:
                    break
        return True, ""

    def _get(self, ns: str, prompt: tuple):
        with self._acct_lock:
            known = ns in self._namespaces
        if not known:
            # ns is client-controlled: unknown namespaces only count a miss
            self._unknown_ns_misses += 1
            return None
        found = self._store.lookup((ns,) + prompt)
        if found is None:
            return None
        key_len, bucket, blob = found
        return key_len - 1, bucket, blob

    # -- handoff (disaggregated serving) --------------------------------------

    def _sweep_handoff_locked(self, now: float) -> None:
        """Reclaim expired handoff entries — the TTL is the only eviction
        pressure pinned entries feel. Caller holds ``_acct_lock``.
        Reclaim is attributed per page as well as per blob: the pages
        counter drops by exactly the expired entries' page counts."""
        dead = [k for k, v in self._handoff.items() if v[0] <= now]
        for k in dead:
            entry = self._handoff.pop(k)
            self._handoff_bytes -= len(entry[3])
            self._handoff_pages -= entry[4]
            self.handoff_expired += 1

    def _handoff_put(self, ns: str, hid: str, length: int, bucket: int,
                     blob: bytes, pages: int = 0) -> tuple[bool, str]:
        # per-entry size is already bounded at the framing layer
        # (_recv_msg caps payloads at max_payload before dispatch);
        # the budget below is the only handoff-specific bound
        now = self._clock()
        with self._acct_lock:
            self._sweep_handoff_locked(now)
            old = self._handoff.get((ns, hid))
            freed = len(old[3]) if old is not None else 0
            if (self._handoff_bytes - freed + len(blob)
                    > self.max_handoff_bytes):
                # refuse, don't evict: every pinned entry has a decode
                # replica about to claim it — dropping one to admit
                # another just moves the re-prefill around. The refusal
                # surfaces at the prefill replica as a publish failure
                # and the request degrades to local prefill.
                self.handoff_rejected += 1
                return False, "handoff byte budget exhausted"
            self._handoff_bytes += len(blob) - freed
            self._handoff_pages += pages - (old[4] if old else 0)
            self._handoff[(ns, hid)] = (
                now + self.handoff_ttl_s, length, bucket, blob, pages)
            self.handoff_puts += 1
        return True, ""

    def _handoff_claim(self, ns: str, hid: str):
        now = self._clock()
        with self._acct_lock:
            self._sweep_handoff_locked(now)
            found = self._handoff.pop((ns, hid), None)
            if found is None:
                return None
            _, length, bucket, blob, pages = found
            self._handoff_bytes -= len(blob)
            self._handoff_pages -= pages
            self.handoff_claims += 1
        return length, bucket, blob


class HandoffRejected(RuntimeError):
    """The pool refused to pin a handoff entry (size/budget caps)."""


class RemoteKVClient:
    """One engine's handle on a :class:`KVPoolServer` (connection per call —
    the pool is hit only on L1+L2 misses and on offload).

    ``namespace`` identifies the weights the KV was computed under —
    every distinct served model (base vs each LoRA adapter, different
    checkpoints) must use a distinct namespace or cross-model KV rows
    would be served interchangeably."""

    def __init__(self, address: tuple[str, int], *, timeout: float = 5.0,
                 namespace: str = ""):
        self.address = tuple(address)
        self.timeout = timeout
        self.namespace = namespace

    def _call(self, header: dict, payload: bytes = b"",
              timeout: float | None = None) -> tuple[dict, bytes]:
        with socket.create_connection(
            self.address, timeout=timeout if timeout is not None
            else self.timeout
        ) as s:
            _send_msg(s, header, payload)
            return _recv_msg(s)

    def put(self, prompt_ids, host: HostEntry) -> None:
        key = list(prompt_ids[: host.length])
        self._call({"op": "put", "ns": self.namespace, "key": key,
                    "length": host.length, "bucket": host.bucket},
                   encode_entry(host))

    def get(self, prompt_ids,
            timeout: float | None = None) -> HostEntry | None:
        header, payload = self._call(
            {"op": "get", "ns": self.namespace, "prompt": list(prompt_ids)},
            timeout=timeout)
        if not header.get("found"):
            return None
        return decode_entry(payload)

    def stats(self) -> dict:
        header, _ = self._call({"op": "stats"})
        return header

    # -- handoff (disaggregated serving) --------------------------------------

    def handoff_put(self, handoff_id: str, host: HostEntry) -> None:
        """Pin ``host`` under ``handoff_id`` until a decode replica claims
        it (or the pool's TTL reclaims it). Raises :class:`HandoffRejected`
        when the pool refuses the pin — unlike :meth:`put`, the caller
        MUST know, because a router is about to point a decode replica at
        this entry."""
        header, _ = self._call(
            {"op": "hput", "ns": self.namespace, "id": handoff_id,
             "length": host.length, "bucket": host.bucket,
             "pages": host.pages},
            encode_entry(host))
        if not header.get("ok"):
            raise HandoffRejected(header.get("error", "handoff put refused"))

    def handoff_claim(self, handoff_id: str,
                      timeout: float | None = None) -> HostEntry | None:
        """Claim-and-remove a pinned handoff entry; ``None`` = lost
        (expired, never published, or already claimed) — the caller
        re-prefills locally."""
        header, payload = self._call(
            {"op": "hclaim", "ns": self.namespace, "id": handoff_id},
            timeout=timeout)
        if not header.get("found"):
            return None
        return decode_entry(payload)


# --- the facade the engine holds -------------------------------------------


class TieredKV:
    """L2 (+optional L3) behind one lookup/offload surface.

    One TieredKV per served model: KV rows are only meaningful under the
    weights that produced them, so the host pool must not be shared
    across models, and the remote client must carry that model's
    ``namespace``.

    ``offload_on_put=True`` (LMCache's streaming write-through) copies
    every finished prefill's entry down the tiers, so a restarting or
    sibling engine starts warm; ``False`` offloads only on L1 eviction.

    The device→host copy and the host-pool insert run synchronously in
    :meth:`offload` (freeing the HBM the eviction was for); only the
    remote TCP put runs on a background worker by default
    (``async_offload``) — a dead pool server must not stall the engine's
    decode loop for the connect timeout. The queue is bounded and holds
    host arrays only; overflow drops the remote copy (counted in
    ``dropped``) rather than applying backpressure to serving.
    ``flush()`` drains the queue — tests and orderly shutdown use it."""

    def __init__(self, host_pool: HostKVPool | None = None,
                 remote: RemoteKVClient | None = None, *,
                 offload_on_put: bool = True, async_offload: bool = True,
                 queue_size: int = 64, remote_cooldown_s: float = 30.0,
                 lookup_timeout_s: float = 0.75, clock=None):
        self.host_pool = host_pool if host_pool is not None else HostKVPool()
        self.remote = remote
        self.offload_on_put = offload_on_put
        self.remote_errors = 0
        self.dropped = 0
        # circuit breaker: lookups run on the engine loop thread, so a
        # dead pool server must not cost a connect timeout per admission —
        # after one failure the remote sits out remote_cooldown_s
        self.remote_cooldown_s = remote_cooldown_s
        # lookups get their own (short) deadline — the client's default
        # timeout is sized for puts of large blobs, and a slow-but-alive
        # pool server must not stall decode for every active slot
        self.lookup_timeout_s = lookup_timeout_s
        self.slow_trips = 0
        self._remote_down_until = 0.0
        self._clock = clock or __import__("time").monotonic
        self._queue: "queue.Queue | None" = (
            queue.Queue(maxsize=queue_size) if async_offload else None)
        self._worker: threading.Thread | None = None

    def _remote_ok(self) -> bool:
        return (self.remote is not None
                and self._clock() >= self._remote_down_until)

    def _remote_failed(self) -> None:
        self.remote_errors += 1
        self._remote_down_until = self._clock() + self.remote_cooldown_s

    # -- offload path ---------------------------------------------------------

    def _remote_put(self, prompt_ids, host: HostEntry) -> None:
        if self._remote_ok():
            try:
                self.remote.put(prompt_ids, host)
            except OSError:
                self._remote_failed()

    def _run_worker(self) -> None:
        while True:
            prompt_ids, host = self._queue.get()
            try:
                self._remote_put(prompt_ids, host)
            except Exception:
                self.remote_errors += 1
            finally:
                self._queue.task_done()

    def offload(self, prompt_ids, entry) -> None:
        """Device ``PrefixEntry`` -> host pool (+ remote, best-effort).

        The device arrays are copied to host here, on the caller's
        thread — queueing them instead would pin the "evicted" HBM until
        the worker drained."""
        host = entry_to_host(entry)
        self.host_pool.put(prompt_ids, host)
        if self.remote is None:
            return
        if self._queue is None:
            self._remote_put(prompt_ids, host)
            return
        if self._worker is None:
            self._worker = threading.Thread(target=self._run_worker,
                                            daemon=True)
            self._worker.start()
        try:
            self._queue.put_nowait((list(prompt_ids), host))
        except queue.Full:
            self.dropped += 1

    def flush(self) -> None:
        """Block until every queued offload has landed in the tiers."""
        if self._queue is not None and self._worker is not None:
            self._queue.join()

    # -- lookup path ----------------------------------------------------------

    def lookup(self, prompt_ids, usable=None, *, device: bool = True):
        """Longest host/remote prefix as a device ``PrefixEntry`` (or None).

        ``usable(entry)`` may read ``entry.length``/``entry.bucket`` only
        (it sees :class:`HostEntry` here, device entries at L1) — applied
        *before* the device upload, and before promoting a remote hit
        into the host pool, so unusable prefixes cost no transfers.

        ``device=False`` returns the :class:`HostEntry` itself (no
        upload): paged engines scatter the rows page-by-page into the
        slot's block table, so a whole-entry device buffer would be a
        wasted transfer."""
        host = self.host_pool.lookup(prompt_ids, usable=usable)
        if host is None and self._remote_ok():
            t0 = self._clock()
            try:
                host = self.remote.get(prompt_ids,
                                       timeout=self.lookup_timeout_s)
            except OSError:
                self._remote_failed()
                host = None
            else:
                # slow-but-responsive server: keep the result but trip the
                # cooldown so the next misses don't pay the same stall
                if self._clock() - t0 > self.lookup_timeout_s:
                    self.slow_trips += 1
                    self._remote_down_until = (
                        self._clock() + self.remote_cooldown_s)
            if host is not None and usable is not None and not usable(host):
                host = None
            if host is not None:
                self.host_pool.put(prompt_ids, host)
        if host is None:
            return None
        return host if not device else entry_to_device(host)


def main() -> None:
    """Run a standalone pool server — the reference's LMCache server
    Deployment (``07-L1-Cache/LMCache/lmcache-deployment.yaml``)."""
    import argparse

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address; the protocol is unauthenticated — "
                        "use 0.0.0.0 only behind an in-cluster ClusterIP "
                        "reachable solely by the serving pods")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--max-tokens", type=int, default=1 << 22,
                   help="global pool budget in cached prefix tokens")
    p.add_argument("--max-bytes", type=int, default=4 << 30,
                   help="global pool budget in blob bytes — size this to "
                        "the pod's memory limit minus headroom")
    p.add_argument("--max-namespaces", type=int, default=64)
    p.add_argument("--metrics-port", type=int, default=8101,
                   help="HTTP port for Prometheus /metrics (+/health) "
                        "next to the TCP data plane; 0 disables")
    args = p.parse_args()
    server = KVPoolServer(args.host, args.port, max_tokens=args.max_tokens,
                          max_bytes=args.max_bytes,
                          max_namespaces=args.max_namespaces)
    server.start()
    if args.metrics_port:
        try:
            mport = server.serve_metrics(args.host, args.metrics_port)
            print(f"kv pool metrics on {args.host}:{mport}/metrics")
        except OSError as e:
            # a second pool on the host collides on the default 8101 —
            # the data plane (already up) must survive with metrics
            # disabled, not crash a previously-working topology
            print(f"kv pool metrics DISABLED: cannot bind "
                  f"{args.host}:{args.metrics_port} ({e})")
    print(f"kv pool server on {server.address[0]}:{server.address[1]} "
          f"(budget {args.max_tokens} tokens / {args.max_bytes} bytes)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()

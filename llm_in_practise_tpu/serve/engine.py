"""Continuous-batching inference engine — the TPU answer to vLLM's core loop.

The reference serves models three ways: raw HF ``generate`` behind FastAPI
(``Scripts/inference/07-deepseek1.5b-api-infr.py:122-130``, one request at a
time), vLLM (continuous batching + paged KV, CUDA), and Ray Serve replicas of
vLLM. This engine is the from-scratch TPU equivalent of the vLLM loop:

- **Slot-based static KV cache**: a ``(max_slots, cache_len, …)`` buffer per
  layer. Requests are admitted into free slots mid-flight; every jitted step
  decodes ALL slots in one batched forward — no retrace, no dynamic shapes.
  (vLLM pages the cache; here the slot dimension is the batching unit and
  XLA keeps the buffer resident in HBM. Paged/prefix reuse is layered on in
  :mod:`llm_in_practise_tpu.serve.prefix_cache`.)
- **Per-slot positions**: each cache entry carries a ``(max_slots,)`` index
  vector; writes scatter per slot (``models.layers.cache_update``) and the
  causal mask uses per-slot offsets, so slot 0 can be 900 tokens deep while
  slot 1 is prefilling.
- **Per-slot sampling params** via
  :func:`llm_in_practise_tpu.infer.sampling.sample_token_batched`.
- **Bucketed prefill**: prompts are right-padded to a few bucket lengths so
  prefill compiles once per bucket, then cache rows are scattered into the
  slot (chunked-prefill analog — vLLM ``enable_chunked_prefill``,
  ``Deployment/Ray/serve_run_examples/deepseek.py:33``).

Threading: HTTP handler threads call :meth:`InferenceEngine.submit`; one
background thread runs :meth:`step` forever. Tokens stream to per-request
queues — the producer/consumer shape of the reference's
``TextIteratorStreamer`` + generation thread
(``Scripts/inference/06-…-streaming-infr.py:52-75``).
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from collections import deque
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from llm_in_practise_tpu.infer.generate import max_positions
from llm_in_practise_tpu.infer.sampling import sample_token_batched
from llm_in_practise_tpu.obs.cost import CostModel, tree_bytes
from llm_in_practise_tpu.obs.hbm import get_ledger, host_entry_bytes
from llm_in_practise_tpu.obs.logging import get_logger
from llm_in_practise_tpu.obs.meter import DispatchMeter, GoodputMeter
from llm_in_practise_tpu.obs.prof import CompileMeter
from llm_in_practise_tpu.obs.registry import HistogramAccumulator
from llm_in_practise_tpu.obs.steptrace import StepTrace
from llm_in_practise_tpu.obs.trace import get_tracer
from llm_in_practise_tpu.serve.mixed_step import (
    batched_chunk,
    decode_scan,
    make_masked_mixed_step,
    make_mixed_step,
    pin_index,
    plan_decode_block,
    plan_spec_extension,
    spec_verify_block,
)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (OpenAI request fields)."""

    temperature: float = 1.0
    top_k: int = 0          # 0 = disabled
    top_p: float = 1.0      # >= 1.0 = disabled
    greedy: bool = False
    max_tokens: int = 128
    # Constrained decoding (serve/constrain.py, ISSUE 12): a compiled
    # TokenAutomaton (shared, reusable across requests with the same
    # schema) — the engine mints a per-request cursor at activation and
    # adds the cursor state's vocab-width logit mask inside the jitted
    # dispatch. None = unconstrained (the exact pre-constraint
    # programs run; golden tokens are bit-identical).
    constraint: Any = None


_FINISH = object()  # sentinel closing a request's token queue

# Per-request critical-path segments (ISSUE 11): every finished
# request's wall time decomposes into these bins — surfaced per request
# at GET /debug/requests and aggregated into
# llm_request_critical_path_seconds_total{segment=…}. ``host_gap`` is
# the residual none of the attributed segments claim (the
# between-dispatch host time the steptrace recorder measures per step);
# ``stream_flush`` is the API-side SSE write tail, measured on the
# handler thread CONCURRENTLY with decode, so it is reported alongside
# the engine segments but excluded from the wall-clock partition.
CP_SEGMENTS = ("queue_wait", "admission", "prefill_dispatch",
               "decode_dispatch", "host_gap", "handoff_wire",
               "preempt_recompute", "stream_flush")
# re-admission after a page-pool preemption re-pays these segments; the
# re-pay is charged to preempt_recompute so a preempted request's
# breakdown says "recompute", not "a second mysterious prefill"
_CP_RECOMPUTE_SEGS = frozenset(
    ("queue_wait", "admission", "prefill_dispatch"))


class EngineDeadError(RuntimeError):
    """The engine loop died while a request waited on its token queue."""


@dataclasses.dataclass
class Request:
    """A submitted generation request and its streaming output channel."""

    uid: int
    prompt_ids: list[int]
    params: SamplingParams
    tokens: "queue.Queue[Any]" = dataclasses.field(default_factory=queue.Queue)
    submit_time: float = dataclasses.field(default_factory=time.monotonic)
    first_token_time: float | None = None
    finish_time: float | None = None
    finish_reason: str | None = None
    n_generated: int = 0
    # set by submit(); lets every queue consumer bound its wait with a
    # liveness check instead of blocking forever on a dead engine
    engine: "InferenceEngine | None" = dataclasses.field(
        default=None, repr=False, compare=False)
    # Disaggregated serving (serve/disagg.py): ``kv_entry`` is a device
    # PrefixEntry claimed from a handoff store — admission seeds the slot
    # via the full-prefix direct-insert path, zero prefill work here.
    # ``handoff_id`` marks a prefill-role request: the engine publishes
    # the prompt KV under this id when prefill completes and finishes the
    # request (finish_reason "handoff") instead of decoding.
    kv_entry: object | None = dataclasses.field(
        default=None, repr=False, compare=False)
    handoff_id: str | None = None
    # Paged-KV preemption (serve/paged_kv.py): when the page pool
    # exhausts mid-decode, the youngest slot is preempted BY RECOMPUTE —
    # its request re-enters the queue with ``prompt_ids`` extended to
    # everything already emitted, ``resume_last`` holding the one token
    # whose KV is not yet written, and ``resume_budget`` the remaining
    # token budget. Re-admission prefills the extended prompt (usually a
    # page-index hit — the preempted pages were registered) and resumes
    # decoding WITHOUT emitting or re-sampling; the client stream never
    # notices beyond the latency bubble.
    resume_last: int | None = dataclasses.field(
        default=None, repr=False, compare=False)
    resume_budget: int = dataclasses.field(
        default=0, repr=False, compare=False)
    # request tracing (obs/trace.py): the TraceContext the API layer
    # minted for this request — the engine parents its queue-wait /
    # admission / prefill-chunk / decode / handoff-publish spans here,
    # so one trace id covers the request across every hop. ``None``
    # (untraced submit paths: benches, direct engine use) records
    # nothing.
    trace: object | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # critical-path breakdown (GET /debug/requests): per-segment
    # seconds of this request's wall clock, accumulated where the
    # engine knows them (see CP_SEGMENTS). Writers are phase-exclusive
    # — the HTTP thread at submit, the engine thread while slotted, the
    # publisher thread at publish, the API thread after the stream
    # closes — so no lock is needed.
    cp: dict = dataclasses.field(default_factory=dict, repr=False,
                                 compare=False)
    # warm-vs-cold TTFT attribution: the prefix-/handoff-hit outcome at
    # FIRST admission ("hit" | "partial" | "cold"); labels the
    # llm_ttft_seconds histogram with cache=…
    cache_outcome: str | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # stamped by the paged preempt path so the re-admission's queue
    # wait is charged to preempt_recompute, not queue_wait
    requeue_time: float | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # origin of the NEXT queue_wait interval: re-armed at every queue
    # pop and re-stamped by preempt, so a request requeued N times
    # (admit-blocked on a dry page pool, or preempted) books N disjoint
    # wait intervals instead of N overlapping ones from submit_time
    cp_queue_origin: float | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # constrained decoding (serve/constrain.py): this request's live
    # grammar cursor, minted from params.constraint at first
    # activation. It RIDES the request through preempt-by-recompute
    # requeues — the resumed stream continues from the exact grammar
    # position, nothing is replayed (the byte-identical-stream
    # guarantee extends to constrained requests).
    constraint_state: object | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # batched multi-LoRA (serve/multi_lora.py, ISSUE 15): the adapter
    # name this request decodes under (None = base model). Admission
    # stamps it into ``slot_adapter``; the registry holds a refcount
    # from submit until _record_finished so the adapter can't be
    # evicted mid-request. Rides preempt-by-recompute requeues — the
    # ref stays held, the resumed slot re-stamps the same adapter.
    adapter: str | None = None
    # True while this request holds a registry refcount (set by submit,
    # cleared by _record_finished) — release must never run for a
    # request whose acquire never did (the too_large fast-reject)
    adapter_ref: bool = dataclasses.field(
        default=False, repr=False, compare=False)
    # Session-native serving (serve/sessions.py, ISSUE 17): the client's
    # conversation handle. On finish the slot's full KV pages are pinned
    # under it in the engine's SessionStore (and published to the fleet
    # handoff namespace when one is wired) so the next turn starts warm;
    # admission consults the store's pending fleet pulls under this id.
    session_id: str | None = None

    def cp_add(self, seg: str, dt: float) -> None:
        """Accumulate ``dt`` seconds into critical-path segment ``seg``.
        Once a request has been preempted, the re-paid admission
        segments redirect into ``preempt_recompute``. Keyed on
        ``requeue_time`` (stamped by every preempt) rather than
        ``resume_last``: a MID-PREFILL preempt emitted nothing, so it
        has no resume token, but its second prefill is recompute all
        the same."""
        if self.requeue_time is not None and seg in _CP_RECOMPUTE_SEGS:
            seg = "preempt_recompute"
        self.cp[seg] = self.cp.get(seg, 0.0) + float(dt)

    def next_item(self, poll_s: float = 1.0):
        """Next queue item — a token id or the internal finish sentinel
        (compare with ``is`` against ``_FINISH``). The wait is BOUNDED:
        between ``poll_s`` polls the engine's liveness is checked, so a
        crashed/stopped engine raises :class:`EngineDeadError` instead
        of freezing the consumer thread (the API layer maps it to a
        5xx; benches/scripts see the exception)."""
        while True:
            try:
                return self.tokens.get(timeout=poll_s)
            except queue.Empty:
                if self.engine is not None and not self.engine.is_alive():
                    raise EngineDeadError(
                        "engine loop is not running; request "
                        f"{self.uid} will never finish")

    def __iter__(self):
        """Yield generated token ids until the request finishes."""
        while True:
            item = self.next_item()
            if item is _FINISH:
                return
            yield item

    def result(self) -> list[int]:
        return list(self)

    @property
    def ttft_s(self) -> float | None:
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time

    @property
    def tpot_s(self) -> float | None:
        """Mean time per output token after the first."""
        if self.finish_time is None or self.n_generated < 2:
            return None
        return (self.finish_time - self.first_token_time) / (self.n_generated - 1)


class EngineStats:
    """Counters/histograms surfaced at /metrics (SURVEY §5.5 PromQL table).

    TTFT/TPOT are fixed-bucket :class:`HistogramAccumulator`s — O(1)
    memory however long the server runs. (They were plain lists growing
    one float per request forever; a week of sustained load leaked the
    whole latency history into RAM just to answer a quantile query.)
    """

    def __init__(self):
        self.lock = threading.Lock()
        self.requests_total = 0         # guarded-by: lock
        self.tokens_generated_total = 0  # guarded-by: lock
        self.ttft = HistogramAccumulator()
        self.tpot = HistogramAccumulator()
        self.queue_depth = 0            # guarded-by: lock
        self.active_slots = 0           # guarded-by: lock
        self.requests_shed = 0          # guarded-by: lock
        # warm-vs-cold TTFT attribution (ISSUE 11 satellite / ROADMAP
        # item 1's metric ask): the same TTFT observations, split by the
        # prefix-/handoff-hit outcome at admission — rendered as
        # llm_ttft_seconds{cache="hit"|"partial"|"cold"} next to the
        # plain series, so "is the cache working fleet-wide" is one
        # PromQL ratio instead of a bench run
        self.ttft_by_cache = {k: HistogramAccumulator()
                              for k in ("hit", "partial", "cold")}
        # per-segment request critical-path aggregate
        # (llm_request_critical_path_seconds_total{segment=…}); written
        # from the engine thread (finish) AND the publisher/API threads
        # (handoff, stream flush), hence under the lock
        self.critical_path = {seg: 0.0 for seg in CP_SEGMENTS}  # guarded-by: lock
        # SLO goodput (obs/meter.py): inactive until thresholds are
        # configured (engine ttft_slo_s/tpot_slo_s kwargs, or the serve
        # benches post-warmup) — then every finished request's tokens
        # land in llm_goodput_tokens_total{slo=ok|violated}
        self.goodput = GoodputMeter()

    def note_stream_flush(self, dt: float) -> None:
        """Book a stream's SSE write tail (API handler thread) into the
        critical-path aggregate — it arrives after the engine finished
        the request, so it cannot ride ``observe_finished``."""
        with self.lock:
            self.critical_path["stream_flush"] += float(dt)

    def critical_path_snapshot(self) -> dict:
        with self.lock:
            return dict(self.critical_path)

    def observe_finished(self, req: Request):
        with self.lock:
            self.tokens_generated_total += req.n_generated
        # the accumulators carry their own locks — keep the observe
        # outside stats.lock so a scrape-time snapshot never serializes
        # against the engine thread's finish path
        if req.ttft_s is not None:
            self.ttft.observe(req.ttft_s)
            acc = self.ttft_by_cache.get(req.cache_outcome or "cold")
            (acc or self.ttft_by_cache["cold"]).observe(req.ttft_s)
        if req.tpot_s is not None:
            self.tpot.observe(req.tpot_s)
        if self.goodput.enabled and req.finish_reason != "queue_full":
            # sheds are already counted (requests_shed / 429s); goodput
            # prices the tokens the engine actually produced
            self.goodput.observe(
                tokens=req.n_generated, ttft_s=req.ttft_s,
                tpot_s=req.tpot_s,
                trace_id=getattr(req.trace, "trace_id", None))


def _default_buckets(cache_len: int) -> tuple[int, ...]:
    out, b = [], 16
    while b < cache_len:
        out.append(b)
        b *= 2
    return tuple(out) or (cache_len,)


class InferenceEngine:
    """Continuous-batching decode loop over a slot-structured KV cache.

    ``model`` must expose ``init_cache(batch, max_len, dtype=...)`` and a
    flax ``apply`` taking ``(idx, deterministic=..., cache=...)`` and
    returning ``(logits, cache)`` — true of every model family in-tree.
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_slots: int = 8,
        cache_len: int = 512,
        eos_id: int | None = None,
        cache_dtype=jnp.bfloat16,
        prefill_buckets: tuple[int, ...] | None = None,
        rng: jax.Array | None = None,
        prefix_cache: "PrefixCache | bool | None" = None,
        chunked_prefill: int | None = None,
        mesh=None,
        kv_pool=None,
        speculative_k: int | None = None,
        speculative_ngram: int = 3,
        decode_steps: int = 1,
        prefill_budget: int = 1,
        mixed_step: bool = True,
        max_queue: int | None = None,
        queue_timeout_s: float | None = None,
        draft_model=None,
        draft_params=None,
        role: str = "both",
        handoff=None,
        tracer=None,
        ttft_slo_s: float | None = None,
        tpot_slo_s: float | None = None,
        kv_layout: str = "contiguous",
        kv_page_size: int = 16,
        kv_pool_tokens: int | None = None,
        steptrace: StepTrace | None = None,
        adapter_registry=None,
        session_store=None,
    ):
        # Engine warmup is compile-bound (a 14B engine compiles ~4.5 min
        # of programs through the remote-compile path, round 4); the
        # persistent cache turns every restart after the first into
        # cache loads. Idempotent; LLM_TPU_COMPILE_CACHE=off disables.
        from llm_in_practise_tpu.core.compile_cache import (
            enable_compilation_cache,
        )

        enable_compilation_cache()
        # Batched multi-LoRA (serve/multi_lora.py, ISSUE 15): wrap the
        # model in the gathered-BGMV facade BEFORE anything below closes
        # over it (mixed-step builders, PagedKV, init_cache, the cost
        # model all take the LOCAL ``model``). The facade delegates
        # untouched while no lora context is set, so every base program
        # traces the exact pre-LoRA computation; only the *_lora twins
        # push a context.
        self.adapter_registry = adapter_registry
        if adapter_registry is not None:
            from llm_in_practise_tpu.serve.multi_lora import (
                LoRAServingModel,
            )

            model = LoRAServingModel(model)
        self.model = model
        self.params = params
        # Cache layout: which axis of each KV buffer indexes the slot.
        # 0 = unrolled per-layer dicts (GPT/DeepSeek/unrolled Qwen3);
        # 1 = stacked scan layout (axis 0 is the layer — Qwen3
        # ``scan_layers``, whose init_cache wraps the stacked dict in a
        # one-element list so both layouts iterate identically here).
        # Width (sequence) axis is always slot_axis + 1.
        self._sax = int(getattr(model, "cache_slot_axis", 0))
        self._wax = self._sax + 1
        # Tensor-parallel serving (vLLM --tensor-parallel-size parity):
        # pass a mesh and params already placed by
        # :func:`shard_params_for_serving`; the KV cache shards its heads
        # dim over the mesh's ``model`` axis and XLA compiles the
        # activation collectives into the same decode/prefill programs.
        # ``tp`` (the model-axis extent) scales the device plane's peaks
        # so MFU/BW utilizations attribute PER CHIP, and prices the
        # per-dispatch activation collectives (docs/serving-tp.md).
        self.mesh = mesh
        self.tp = int(mesh.shape.get("model", 1)) if mesh is not None else 1
        self.max_slots = max_slots
        limit = max_positions(getattr(model, "config", None))
        self.cache_len = min(cache_len, limit) if limit else cache_len
        self.eos_id = eos_id
        self.cache_dtype = cache_dtype
        self.buckets = tuple(
            b for b in (prefill_buckets or _default_buckets(self.cache_len))
            if b <= self.cache_len
        )
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)

        # KV layout (ROADMAP item 2 / docs/paged-kv.md): "contiguous" is
        # the original slot-owns-a-cache_len-region buffer; "paged"
        # carves one flat pool into fixed-size pages behind per-slot
        # block tables (vLLM PagedAttention idiom) — admission reserves
        # actual pages instead of worst-case context, prefixes share
        # refcounted pages, and handoff/tiering move page-aligned rows.
        # Golden tokens are layout-invariant (tests/test_paged_kv.py);
        # "contiguous" remains the fallback for one release.
        if kv_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'contiguous', got "
                f"{kv_layout!r}")
        self.paged = None
        self.draft_kv_reserved_tokens = 0
        if kv_layout == "paged":
            from llm_in_practise_tpu.serve.paged_kv import (
                PagedKV,
                kv_row_bytes,
            )

            pool_request = (kv_pool_tokens if kv_pool_tokens is not None
                            else max_slots * self.cache_len)
            if draft_model is not None and kv_pool_tokens is not None:
                # The draft cache is a CONTIGUOUS max_slots x cache_len
                # reservation living NEXT TO the page pool. An explicit
                # --kv-pool-tokens models the operator's KV byte budget,
                # so the draft's bytes come out of it (token-equivalent
                # at the target's bytes/row) — a paged engine with a
                # draft model must not over-admit against memory the
                # draft cache already spent. The default pool size keeps
                # worst-case reservation semantics (over-admission is
                # impossible there), so nothing is deducted.
                drow = kv_row_bytes(draft_model, cache_dtype)
                trow = kv_row_bytes(model, cache_dtype)
                self.draft_kv_reserved_tokens = -(
                    -max_slots * self.cache_len * drow // trow)
                pool_request -= self.draft_kv_reserved_tokens
                if pool_request < 2 * kv_page_size:
                    raise ValueError(
                        f"kv_pool_tokens={kv_pool_tokens} leaves only "
                        f"{pool_request} tokens after the draft cache's "
                        f"{self.draft_kv_reserved_tokens}-token "
                        "equivalent reservation — raise the pool budget "
                        "or drop the draft model")
            self.paged = PagedKV(
                model, max_slots=max_slots, cache_len=self.cache_len,
                page_size=kv_page_size,
                pool_tokens=pool_request,
                dtype=cache_dtype, mesh=mesh)
            # no contiguous engine cache exists in this layout; the
            # jitted paged programs gather transient views from the pool
            self.cache = None
        else:
            self.cache = model.init_cache(max_slots, self.cache_len,
                                          dtype=cache_dtype)
            self._vectorize_cache_index()
            if mesh is not None:
                self.cache = jax.device_put(self.cache,
                                            self._cache_shardings())
        self.preemptions = 0            # paged pool-pressure preemptions
        self.rejected_too_large = 0     # prompts that can NEVER fit the pool
        self._paged_admit_blocked = False

        # Host-side slot table (slot_len mirrors the device cache index so
        # finish checks never force a device sync).
        self.slot_req: list[Request | None] = [None] * max_slots
        self.slot_ready = np.zeros((max_slots,), bool)
        # chunked prefill (vLLM enable_chunked_prefill parity): prompts
        # longer than this many tokens prefill one chunk per engine step,
        # interleaved with decode so long prompts don't stall active slots.
        if chunked_prefill is not None and chunked_prefill < 1:
            raise ValueError(
                f"chunked_prefill must be >= 1, got {chunked_prefill}"
            )
        self.chunked_prefill = chunked_prefill
        self.slot_prefill: dict[int, dict] = {}
        self.slot_last_token = np.zeros((max_slots,), np.int32)
        self.slot_len = np.zeros((max_slots,), np.int64)
        self.slot_budget = np.zeros((max_slots,), np.int64)  # tokens remaining
        self._temperature = np.ones((max_slots,), np.float32)
        self._top_k = np.zeros((max_slots,), np.int32)
        self._top_p = np.ones((max_slots,), np.float32)
        self._greedy = np.zeros((max_slots,), bool)
        # Constrained decoding (serve/constrain.py, ISSUE 12): per-slot
        # grammar cursor (None = unconstrained). The planner caps the
        # decode block at 1 while any READY slot is constrained (the
        # mask encodes one automaton state per slot), the mask is built
        # on the host as part of the dispatch plan, and the masked twin
        # programs apply it in-dispatch — 1 dispatch/step holds with
        # grammar on, on both KV layouts. Engine-thread only.
        self.slot_constraint: list = [None] * max_slots
        # Per-slot adapter name (multi-LoRA, ISSUE 15; None = base).
        # Engine-thread only; joins the dispatch plan as the gathered
        # row-index array the *_lora twins consume.
        self.slot_adapter: list[str | None] = [None] * max_slots
        # lifetime grammar telemetry (engine-thread writes, scrape-side
        # monotone-float reads — the collective_* counter convention):
        # llm_grammar_mask_seconds_total / llm_spec_grammar_rejects_total
        self.grammar_mask_seconds_total = 0.0
        self.spec_grammar_rejects = 0

        # Admission control (VERDICT r4 #5 — the reference's ingress
        # backpressure, `05-KEDA-AutoScale/vllm-ingress-backpressure.yaml`,
        # moved into the engine so oversubscription degrades BOUNDED
        # instead of stretching TTFT without limit: at conc 32 over 8
        # slots the r4 ladders measured 5-30 s TTFT p99 with every
        # request eventually served late). ``max_queue``: reject at
        # submit once this many requests wait (finish_reason
        # "queue_full"; the API layer maps it to HTTP 429).
        # ``queue_timeout_s``: shed requests still unadmitted after this
        # long — a client that would see a worse-than-SLA TTFT gets a
        # fast failure it can retry against another replica (the
        # gateway's retry/fallback chains consume exactly this). Both
        # default off: capacity tests and closed-loop benches that WANT
        # deep queues keep today's behavior.
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if queue_timeout_s is not None and queue_timeout_s <= 0:
            raise ValueError(
                f"queue_timeout_s must be > 0, got {queue_timeout_s}")
        self.max_queue = max_queue
        self.queue_timeout_s = queue_timeout_s
        # serializes the max_queue check-then-put: without it two HTTP
        # threads can both see depth N-1 and overshoot the bound
        self._submit_lock = threading.Lock()
        self.pending: "queue.Queue[Request]" = queue.Queue()
        self.stats = EngineStats()
        self._uid = itertools.count()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()  # set on submit; idle loop waits on it
        self._thread: threading.Thread | None = None

        # Prefix caching (vLLM APC parity): True -> default-sized cache.
        from llm_in_practise_tpu.serve.prefix_cache import (
            PagedPrefixIndex,
            PrefixCache,
        )

        if self.paged is not None:
            # Paged engines share PHYSICAL PAGES instead of copying
            # rows: the L1 "cache" is the hash-per-page index over the
            # pool itself (partial-prefix hits at page granularity,
            # refcounted COW sharing — see prefix_cache.PagedPrefixIndex).
            # A row-based PrefixCache instance passed in is replaced;
            # its budget knobs carry over.
            want = bool(prefix_cache) or kv_pool is not None
            idx = None
            if want:
                kwargs = {}
                if isinstance(prefix_cache, PrefixCache):
                    kwargs = dict(max_tokens=prefix_cache.max_tokens,
                                  min_prefix=prefix_cache.min_prefix)
                idx = PagedPrefixIndex(self.paged.pool, **kwargs)
                # admission pressure reclaims cold shared prefixes
                # before it preempts anybody
                self.paged.pool.reclaim = idx.evict_pages
            self.prefix_cache = idx
        elif prefix_cache is True or (not prefix_cache
                                      and kv_pool is not None):
            prefix_cache = PrefixCache()
            self.prefix_cache = prefix_cache
        else:
            self.prefix_cache = prefix_cache or None
        # Tiered offload (LMCache parity): L1 evictions flow into the
        # host/remote pool instead of vanishing; lookups cascade back up.
        # (Paged engines populate the tiers by write-through only: an
        # evicted shared page has no token-tuple key of its own.)
        self.kv_pool = kv_pool
        if (kv_pool is not None and self.prefix_cache is not None
                and self.paged is None):
            prior = self.prefix_cache.on_evict
            def _evict(key, entry, _prior=prior):
                if _prior is not None:
                    _prior(key, entry)
                # with write-through on, the entry already went down the
                # tiers at prefill time — re-offloading on eviction would
                # double every device_get + TCP put
                if not kv_pool.offload_on_put:
                    kv_pool.offload(list(key), entry)
            self.prefix_cache.on_evict = _evict

        # Speculative decoding (vLLM ngram/prompt-lookup parity, lossless):
        # draft K tokens per slot by matching the trailing n-gram earlier
        # in that slot's context, verify all K+1 positions in ONE forward,
        # keep the longest prefix that matches what greedy would emit.
        # Decode is HBM-bound (weights dominate the traffic), so the wider
        # verify step costs ≈ one normal step; every accepted draft is a
        # full decode step saved (2-3x measured on one v5e chip at 38%
        # acceptance on self-similar text). Greedy-only: with sampling the
        # verify comparison is no longer exact, so mixed batches fall
        # back. Equality with one-token decode is bitwise on CPU; on TPU
        # the wide matmul's different reduction order can flip near-tie
        # argmaxes — the emitted tokens are still exact greedy outputs of
        # the verify forward itself (the same caveat applies to any
        # batched-verify speculator, vLLM's included).
        if speculative_k is not None and speculative_k < 1:
            raise ValueError(f"speculative_k must be >= 1, got {speculative_k}")
        self.speculative_k = speculative_k
        self.speculative_ngram = speculative_ngram
        self.slot_hist: list[list[int] | None] = [None] * max_slots
        self.spec_proposed = 0
        self.spec_accepted = 0
        # fused spec-round accounting (the BENCH_SPEC_LADDER evidence):
        # rounds = spec-verify dispatches issued; round_tokens = tokens
        # those dispatches actually committed (accepted + bonus +
        # extension) — tokens/dispatch on the spec path in two ints
        self.spec_rounds = 0
        self.spec_round_tokens = 0
        # Draft-MODEL speculation (vLLM draft-model / Eagle-style
        # proposer parity; the ngram speculator above is prompt-lookup):
        # a small model with its OWN slot KV cache proposes the k tokens
        # instead of the n-gram matcher. No activation hooks needed —
        # ``slot_hist`` already holds prompt+tokens, so a per-slot
        # ``_draft_sync`` watermark says how much of it the draft cache
        # has consumed; a lazy catch-up (chunked feed through the same
        # machinery as chunked prefill) covers initial prompt feed,
        # tokens emitted by non-spec steps, and rejected-token re-sync
        # uniformly (the draft cache index is pinned from the host every
        # dispatch, so stale rolled KV is simply overwritten in order).
        self.draft_model = draft_model
        self.draft_params = draft_params
        if draft_model is not None:
            if speculative_k is None:
                raise ValueError(
                    "draft_model needs speculative_k (the proposal len)")
            if draft_params is None:
                raise ValueError(
                    "draft_model needs draft_params (a None params tree "
                    "would fail opaquely inside the first jitted draft "
                    "dispatch on the serving thread)")
            self.draft_cache = draft_model.init_cache(
                max_slots, self.cache_len, dtype=cache_dtype)
            if mesh is not None:
                # TP serving (ISSUE 10 satellite): the draft is small —
                # REPLICATE its params and KV cache across the mesh
                # instead of sharding, so the draft roll/catch-up
                # programs run without collectives and their outputs
                # feed the sharded target's verify without resharding.
                # (An unplaced draft tree would sit committed on device
                # 0 and conflict with the mesh-placed target inside the
                # same jitted dispatch.)
                from jax.sharding import NamedSharding, PartitionSpec

                rep = NamedSharding(mesh, PartitionSpec())
                self.draft_params = draft_params = jax.device_put(
                    draft_params,
                    jax.tree_util.tree_map(lambda _: rep, draft_params))
                self.draft_cache = jax.device_put(
                    self.draft_cache,
                    jax.tree_util.tree_map(lambda _: rep,
                                           self.draft_cache))
            dax = int(getattr(draft_model, "cache_slot_axis", 0))
            if dax != self._sax:
                raise ValueError(
                    "draft_model cache layout differs from the target's "
                    f"(slot axis {dax} vs {self._sax})")
            for layer in self.draft_cache:
                layer["index"] = jnp.zeros((self.max_slots,), jnp.int32)
            self._draft_sync = np.zeros((max_slots,), np.int64)
            self._draft_uid = np.full((max_slots,), -1, np.int64)
            # catch-up window: biggest normal re-sync is a fully
            # accepted FUSED round — k+1 verify tokens plus the
            # decode_steps-1 extension (spec_verify_block) — or a
            # plain decode_steps block
            self._draft_window = max(
                16, 1 << (speculative_k + decode_steps
                          - 1).bit_length())
        # Multi-step decode (vLLM multi-step scheduling parity): run
        # ``decode_steps`` decode iterations inside ONE jitted call
        # (a lax.scan), paying host-dispatch overhead once per block.
        # This is the lever when dispatch latency rivals step time —
        # weak hosts, remote-tunnel setups; on a fast local host 1 is
        # fine. Block length is planned per step by
        # :func:`llm_in_practise_tpu.serve.mixed_step.plan_decode_block`
        # (soonest-completion cap under queueing, chunk-window caps while
        # prompts prefill); a speculative engine rides the SAME plan —
        # the fused spec round (serve/mixed_step.spec_verify_block)
        # verifies the k drafts and decodes the block's remaining n-1
        # steps in one dispatch. Slots that finish mid-block
        # waste their remaining rows; the freed slot's rows/index are
        # reset on reuse by the insert path (the same contract the
        # speculative burst relies on).
        if decode_steps < 1:
            raise ValueError(f"decode_steps must be >= 1, got {decode_steps}")
        self.decode_steps = decode_steps
        self.multi_blocks = 0
        self.multi_steps_total = 0  # decode iterations spent inside blocks
        # Fused mixed-batch step (r6): while prompts are mid-chunked-
        # prefill AND slots are decoding, ONE jitted program advances
        # every prefill row a chunk and runs the decode block — mixed-
        # load steps cost 1 dispatch instead of 2, and decoders keep
        # their n>1 amortization instead of degrading to single-token
        # dispatches (the r5 long-context TPOT collapse; see
        # serve/mixed_step.py and docs/perf.md Finding 17).
        self.mixed_step = bool(mixed_step)
        self.mixed_blocks = 0
        self._log = get_logger("serve.engine")
        # request tracing (obs/trace.py): spans parent to each request's
        # TraceContext; the process default keeps a single-process stack
        # (tests, chip sharing) on one correlated trace plane
        self.tracer = tracer if tracer is not None else get_tracer()
        # host-gap flight recorder (obs/steptrace.py, ISSUE 11): one
        # record per step(), partitioning the step's wall clock into
        # named host activities + device-busy time. Engine-thread
        # writer; /metrics reads its swapped snapshot.
        # LLM_TPU_STEPTRACE=off disables (tests pin golden-token
        # parity either way).
        self.steptrace = steptrace if steptrace is not None else StepTrace()
        # recent finished requests for GET /debug/requests — each
        # carries its critical-path breakdown (Request.cp). deque
        # append/iteration are GIL-atomic; HTTP readers snapshot with
        # list() (same contract as the slot_prefill .get reads).
        self.finished: deque = deque(maxlen=128)
        self._spec_suspended_logged = False
        self._mixed_fallbacks_logged: set[str] = set()
        # Guaranteed chunked-prefill budget: every engine step runs up to
        # this many prefill chunks BEFORE any decode work, so decode load
        # can never starve a prompt that is mid-prefill (the TTFT-fairness
        # guarantee chunked prefill exists for — vLLM enable_chunked_prefill,
        # Deployment/Ray/serve_run_examples/deepseek.py:32-35).
        if prefill_budget < 1:
            raise ValueError(
                f"prefill_budget must be >= 1, got {prefill_budget}"
            )
        self.prefill_budget = prefill_budget

        # Disaggregated serving (serve/disagg.py — the llm-d prefill/
        # decode split). ``role`` is a *soft* constraint the metrics make
        # assertable, not a hard gate: a decode replica whose handoff
        # entry was lost re-prefills locally (graceful degradation, the
        # llm-d fallback), and the ``local_prefills`` counter + a
        # logged-once warning surface that it happened. A prefill
        # replica needs a ``handoff`` store to publish into; requests
        # carrying a ``handoff_id`` finish at the end of prefill with
        # ``finish_reason="handoff"`` instead of occupying a decode slot.
        from llm_in_practise_tpu.serve.disagg import validate_roles

        self.role = validate_roles(role)
        self.handoff = handoff
        if role == "prefill" and handoff is None:
            raise ValueError(
                "role='prefill' needs a handoff store to publish KV into "
                "(serve.disagg.LocalHandoff or RemoteHandoff)")
        self.handoff_published = 0      # entries pinned into the store
        self.handoff_publish_failed = 0
        # publisher workers: the device→host copy + TCP put of each
        # handoff run OFF the engine thread (a dead pool server must
        # stall only the waiting handoff request, not the decode loop).
        # A small POOL, not one thread: publishes are independent I/O,
        # and serializing them would stack each one's transfer — or,
        # pool-down, its full connect timeout — onto every later
        # request's KV-ready time. Unbounded queue is safe: in-flight
        # handoffs are bounded by the router, which waits on each
        # publish before dispatching the decode half.
        self._publish_queue: "queue.Queue" = queue.Queue()
        self._publishers: list[threading.Thread] = []
        self._n_publishers = min(4, max_slots)
        self._publish_lock = threading.Lock()  # counter increments
        self.kv_admitted = 0            # requests seeded by external KV
        self.kv_rejected = 0            # external entries that failed checks
        self.local_prefills = 0         # prefills a decode replica ran
        self._decode_prefill_logged = False

        # Session-native serving (serve/sessions.py, ISSUE 17): requests
        # carrying a session_id pin their conversation KV across turns —
        # the store chains into the page pool's reclaim hook (after the
        # COW index, so sessions yield to active slots) and, with a
        # handoff store, publishes each finished turn for fleet-wide
        # migration. Attached AFTER the paged/prefix/handoff wiring
        # above — attach() reads all three.
        self.session_store = session_store
        if session_store is not None:
            session_store.attach(self)

        # SLO goodput thresholds (obs/meter.py GoodputMeter; exported
        # as llm_goodput_tokens_total{slo=…}); the tracer enables
        # per-phase blame of violated requests from the span ring
        self.stats.goodput.tracer = self.tracer
        if ttft_slo_s is not None or tpot_slo_s is not None:
            self.stats.goodput.configure(ttft_slo_s, tpot_slo_s)

        # Device-plane cost model (obs/cost.py): analytic FLOPs/bytes
        # per dispatch → live per-phase MFU / HBM-bandwidth-utilization
        # gauges. Fail-open None for model families the analytic
        # geometry doesn't cover (the gauges just don't render).
        # Under TP the peaks scale by the mesh's model extent so the
        # utilizations attribute per chip (ISSUE 10 satellite).
        self.cost_model = CostModel.from_model(model, params,
                                               cache_dtype=cache_dtype,
                                               tp=self.tp)
        # tensor-parallel collective attribution (docs/serving-tp.md):
        # per-chip ICI wire bytes of each dispatch's row-parallel
        # activation all-reduces (analytic — cost model), and the
        # lower-bound seconds they cost at datasheet ICI bandwidth.
        # Engine-thread writes, scrape-thread reads of monotone floats
        # (the single-writer convention of the spec_* counters). Both
        # stay 0.0 at tp=1, so the /metrics families render zeros there.
        self.collective_bytes_total = 0.0
        self.collective_seconds_total = 0.0
        # int8 quantized collectives (parallel/collectives.py): the
        # model facade carries the behavior; the engine only needs the
        # flag to halve the wire-byte attribution
        from llm_in_practise_tpu.parallel.collectives import (
            TPQuantizedCollectives,
        )

        self.tp_quantized_collectives = isinstance(
            getattr(model, "inner", model), TPQuantizedCollectives)

        # HBM ledger (obs/hbm.py, ISSUE 19): book this engine's durable
        # device allocations under their owner accounts; stop() returns
        # every byte. The page pool booked itself inside PagedKV; the
        # per-dispatch transient gather views pulse at the dispatch
        # sites. kv.draft is the draft cache's REAL byte footprint —
        # the same quantity /debug/kv's draft_kv_reserved_tokens
        # expresses in pool tokens through the kv_row_bytes exchange
        # rate, so --speculative setups see the draft tax on the
        # ownership scoreboard.
        self._hbm = get_ledger()
        self._hbm_booked = {}  # engine thread + stop(); freed once
        self._hbm_book("weights/model", tree_bytes(self.params))
        if self.cache is not None:
            self._hbm_book("kv.contiguous", tree_bytes(self.cache))
        if self.draft_model is not None:
            self._hbm_book("weights/draft_model",
                           tree_bytes(self.draft_params))
            self._hbm_book("kv.draft", tree_bytes(self.draft_cache))

        # Dispatch accounting: every jitted engine program is wrapped so
        # /metrics (llm_dispatches_*) and the mixed-step tests can assert
        # dispatches/step instead of inferring it from wall-clock. The
        # compile meter rides the same wrap: a jit-cache miss's call
        # time is booked as compile seconds (llm_compile_*), so a 40 s
        # recompile mid-serving is a counter bump, not a mystery stall.
        self.dispatch_meter = DispatchMeter()
        self.compile_meter = CompileMeter()
        _c = lambda fn: self.dispatch_meter.wrap(  # noqa: E731
            self.compile_meter.wrap(fn))
        self._decode = _c(jax.jit(self._decode_fn, donate_argnums=(1,)))
        self._decode_multi = _c(jax.jit(self._decode_multi_fn,
                                        donate_argnums=(1,),
                                        static_argnames=("n",)))
        self._decode_spec = _c(jax.jit(self._decode_spec_fn,
                                       donate_argnums=(1,),
                                       static_argnames=("m",)))
        self._prefill = _c(jax.jit(self._prefill_fn))
        self._prefill_suffix = _c(jax.jit(self._prefill_suffix_fn))
        self._insert = _c(jax.jit(self._insert_fn, donate_argnums=(0,),
                                  static_argnames=("slot",)))
        self._insert_batch = _c(jax.jit(self._insert_batch_fn,
                                        donate_argnums=(0,)))
        self._insert_rows = _c(jax.jit(self._insert_rows_fn,
                                       donate_argnums=(0,),
                                       static_argnames=("slot",)))
        self._chunk_slot = _c(jax.jit(self._chunk_slot_fn,
                                      donate_argnums=(1,)))
        self._chunk_batch = _c(jax.jit(self._chunk_batch_fn,
                                       donate_argnums=(1,)))
        self._slot_rows = _c(jax.jit(self._slot_rows_fn,
                                     static_argnames=("bucket",)))
        self._mixed_raw = make_mixed_step(model)
        self._mixed = _c(jax.jit(self._mixed_raw,
                                 donate_argnums=(1,),
                                 static_argnames=("n",)))
        # Grammar-masked twins (serve/constrain.py): SEPARATE compiled
        # programs with a trailing additive-mask argument, not a flag
        # on the unmasked ones — unconstrained steps keep the exact
        # pre-constraint executables (golden parity by construction)
        # and never pay the (B, vocab) mask transfer. jit is lazy, so
        # an engine that never sees a constrained request never
        # compiles these.
        self._decode_masked = _c(jax.jit(self._decode_masked_fn,
                                         donate_argnums=(1,)))
        self._decode_spec_masked = _c(jax.jit(
            self._decode_spec_masked_fn, donate_argnums=(1,),
            static_argnames=("m",)))
        self._mixed_masked_raw = make_masked_mixed_step(model)
        self._mixed_masked = _c(jax.jit(self._mixed_masked_raw,
                                        donate_argnums=(1,),
                                        static_argnames=("n",)))
        if self.paged is not None:
            # Paged twins of the engine programs: same RAW bodies (the
            # math that pins golden parity) between a page gather and a
            # window scatter, one dispatch each — see the "jitted
            # pieces, paged" section. The pool is donated so updates
            # are in place; the contiguous view is a transient XLA
            # frees between dispatches.
            self._pg_decode = _c(jax.jit(self._paged_decode_fn,
                                         donate_argnums=(1,)))
            self._pg_multi = _c(jax.jit(self._paged_multi_fn,
                                        donate_argnums=(1,),
                                        static_argnames=("n",)))
            self._pg_spec = _c(jax.jit(self._paged_spec_fn,
                                       donate_argnums=(1,),
                                       static_argnames=("m",)))
            self._pg_chunk = _c(jax.jit(self._paged_chunk_fn,
                                        donate_argnums=(1,)))
            self._pg_mixed = _c(jax.jit(self._paged_mixed_fn,
                                        donate_argnums=(1,),
                                        static_argnames=("n",)))
            self._pg_decode_masked = _c(jax.jit(
                self._paged_decode_masked_fn, donate_argnums=(1,)))
            self._pg_spec_masked = _c(jax.jit(
                self._paged_spec_masked_fn, donate_argnums=(1,),
                static_argnames=("m",)))
            self._pg_mixed_masked = _c(jax.jit(
                self._paged_mixed_masked_fn, donate_argnums=(1,),
                static_argnames=("n",)))
            self._pg_write_rows = _c(jax.jit(self._paged_write_rows_fn,
                                             donate_argnums=(0,)))
            self._pg_gather_rows = _c(jax.jit(self._paged_gather_rows_fn))
            self._pg_page_copy = _c(jax.jit(self._paged_page_copy_fn,
                                            donate_argnums=(0,)))
        if draft_model is not None:
            self._draft_chunk = _c(jax.jit(self._draft_chunk_fn,
                                           donate_argnums=(1,)))
            self._draft_roll = _c(jax.jit(self._draft_roll_fn,
                                          donate_argnums=(1,),
                                          static_argnames=("k",)))
        if adapter_registry is not None:
            # Adapter twins (serve/multi_lora.py, ISSUE 15 — the
            # grammar-masked-twin economics): SEPARATE compiled programs
            # taking a KW-ONLY ``lora`` pytree (per-row bank indices +
            # the stacked A/B factor banks) pushed as the thread-local
            # lora context INSIDE the traced body, so the facade's
            # interceptor adds the gathered low-rank delta on the LoRA
            # target matmuls. Keyword-only keeps every positional
            # donate_argnums index valid; jit laziness means a step
            # whose rows are all base runs the base executable and the
            # twin never compiles. Draft programs deliberately have NO
            # twins — drafts stay base-model (ISSUE 15) and rejected
            # drafts cost nothing; the verify dispatch IS the target
            # forward, so the spec twins below carry the delta.
            from llm_in_practise_tpu.serve.multi_lora import lora_wrap

            self._decode_lora = _c(jax.jit(
                lora_wrap(self._decode_fn), donate_argnums=(1,)))
            self._decode_multi_lora = _c(jax.jit(
                lora_wrap(self._decode_multi_fn), donate_argnums=(1,),
                static_argnames=("n",)))
            self._decode_spec_lora = _c(jax.jit(
                lora_wrap(self._decode_spec_fn), donate_argnums=(1,),
                static_argnames=("m",)))
            self._prefill_lora = _c(jax.jit(
                lora_wrap(self._prefill_fn)))
            self._prefill_suffix_lora = _c(jax.jit(
                lora_wrap(self._prefill_suffix_fn)))
            self._chunk_slot_lora = _c(jax.jit(
                lora_wrap(self._chunk_slot_fn), donate_argnums=(1,)))
            self._chunk_batch_lora = _c(jax.jit(
                lora_wrap(self._chunk_batch_fn), donate_argnums=(1,)))
            self._mixed_lora = _c(jax.jit(
                lora_wrap(self._mixed_raw), donate_argnums=(1,),
                static_argnames=("n",)))
            self._decode_masked_lora = _c(jax.jit(
                lora_wrap(self._decode_masked_fn), donate_argnums=(1,)))
            self._decode_spec_masked_lora = _c(jax.jit(
                lora_wrap(self._decode_spec_masked_fn),
                donate_argnums=(1,), static_argnames=("m",)))
            self._mixed_masked_lora = _c(jax.jit(
                lora_wrap(self._mixed_masked_raw), donate_argnums=(1,),
                static_argnames=("n",)))
            if self.paged is not None:
                self._pg_decode_lora = _c(jax.jit(
                    lora_wrap(self._paged_decode_fn),
                    donate_argnums=(1,)))
                self._pg_multi_lora = _c(jax.jit(
                    lora_wrap(self._paged_multi_fn), donate_argnums=(1,),
                    static_argnames=("n",)))
                self._pg_spec_lora = _c(jax.jit(
                    lora_wrap(self._paged_spec_fn), donate_argnums=(1,),
                    static_argnames=("m",)))
                self._pg_chunk_lora = _c(jax.jit(
                    lora_wrap(self._paged_chunk_fn), donate_argnums=(1,)))
                self._pg_mixed_lora = _c(jax.jit(
                    lora_wrap(self._paged_mixed_fn), donate_argnums=(1,),
                    static_argnames=("n",)))
                self._pg_decode_masked_lora = _c(jax.jit(
                    lora_wrap(self._paged_decode_masked_fn),
                    donate_argnums=(1,)))
                self._pg_spec_masked_lora = _c(jax.jit(
                    lora_wrap(self._paged_spec_masked_fn),
                    donate_argnums=(1,), static_argnames=("m",)))
                self._pg_mixed_masked_lora = _c(jax.jit(
                    lora_wrap(self._paged_mixed_masked_fn),
                    donate_argnums=(1,), static_argnames=("n",)))

    # --- jitted pieces -------------------------------------------------------

    def _vectorize_cache_index(self):
        """Scalar per-layer cache index -> (max_slots,) vector."""
        for layer in self.cache:
            layer["index"] = jnp.zeros((self.max_slots,), jnp.int32)

    def _cache_shardings(self):
        """KV heads ('k'/'v' buffers, second-to-last dim in either cache
        layout) shard over the ``model`` axis; everything else (latent
        MLA 'kv' buffers, indices) replicates."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from llm_in_practise_tpu.utils.tree import path_str

        tp = self.mesh.shape.get("model", 1)

        def leaf(path, x):
            key = path_str(path).rsplit("/", 1)[-1]
            if key in ("k", "v") and tp > 1 and x.shape[-2] % tp == 0:
                spec = [None] * x.ndim
                spec[-2] = "model"
                return NamedSharding(self.mesh, P(*spec))
            return NamedSharding(self.mesh, P())

        return jax.tree_util.tree_map_with_path(leaf, self.cache)

    def _decode_fn(self, params, cache, tokens, rng, temperature, top_k, top_p, greedy):
        logits, cache = self.model.apply(
            {"params": params}, tokens[:, None], deterministic=True, cache=cache
        )
        next_tok = sample_token_batched(
            rng, logits[:, -1, :].astype(jnp.float32),
            temperature=temperature, top_k=top_k, top_p=top_p, greedy=greedy,
        )
        return next_tok.astype(jnp.int32), cache

    def _decode_multi_fn(self, params, cache, tokens, rng, temperature,
                         top_k, top_p, greedy, *, n):
        """``n`` single-token decodes under one lax.scan — one compiled
        program, one dispatch. Returns ((B, n) tokens, cache). ``n`` is
        static (≤ ``decode_steps`` distinct compilations): blocks shrink
        when a slot is about to finish and requests are waiting. Body
        shared with the fused mixed step (serve/mixed_step.py)."""
        return decode_scan(self.model, params, cache, tokens, rng,
                           temperature, top_k, top_p, greedy, n=n)

    def _decode_spec_fn(self, params, cache, tokens, base, mask, *, m):
        """Fused speculative round (serve/mixed_step.spec_verify_block):
        verify the (B, K+1) proposed tokens, accept on DEVICE, fix the
        per-slot index (the work of the old separate ``_rewind``
        dispatch), and decode the planned block's remaining ``m`` steps
        — one dispatch per spec round, however long the block."""
        return spec_verify_block(self.model, params, cache, tokens,
                                 base, mask, m=m)

    def _decode_masked_fn(self, params, cache, tokens, rng, temperature,
                          top_k, top_p, greedy, gmask):
        """Grammar-masked single-token decode: the ``_decode_fn`` body
        plus the (B, vocab) additive logit mask staged by the host from
        each constrained slot's automaton state (serve/constrain.py).
        Zero rows leave unconstrained slots' sampling untouched."""
        logits, cache = self.model.apply(
            {"params": params}, tokens[:, None], deterministic=True,
            cache=cache
        )
        next_tok = sample_token_batched(
            rng, logits[:, -1, :].astype(jnp.float32) + gmask,
            temperature=temperature, top_k=top_k, top_p=top_p,
            greedy=greedy,
        )
        return next_tok.astype(jnp.int32), cache

    def _decode_spec_masked_fn(self, params, cache, tokens, base, mask,
                               gmasks, *, m):
        """Grammar-masked fused spec round: (B, K+1, vocab) staged
        masks — position ``j`` carries the automaton state after the
        first ``j`` drafts, so a grammar-forbidden draft truncates the
        on-device acceptance cumprod exactly like an argmax mismatch.
        Constrained rounds run at ``m == 0`` (the extension's tokens
        have no host-stageable grammar state)."""
        return spec_verify_block(self.model, params, cache, tokens,
                                 base, mask, m=m, gmasks=gmasks)

    def _prefill_fn(self, params, prompt_ids, length):
        """prompt_ids: (B, bucket), length: (B,). Returns per-request
        last-valid logits (B, vocab) and a B-row, BUCKET-length prefill
        cache (only bucket rows are ever written — allocating B x
        cache_len here would transiently rival the whole engine cache at
        a saturated admission burst). B > 1 = batched admission: several
        same-bucket prompts prefill in ONE dispatch (vLLM batches waiting
        prefills the same way; on TPU the batch dim also feeds the MXU
        properly for short prompts)."""
        B, bucket = prompt_ids.shape
        cache = self.model.init_cache(B, bucket, dtype=self.cache_dtype)
        logits, cache = self.model.apply(
            {"params": params}, prompt_ids, deterministic=True, cache=cache
        )
        last = jnp.take_along_axis(
            logits, (length - 1)[:, None, None], axis=1
        )[:, 0, :]
        return last, cache

    def _primed(self, cache, prefix_rows, prefix_len):
        """Fresh 1-slot cache with prefix KV rows inserted, index offset."""
        primed = []
        for layer, rows in zip(cache, prefix_rows):
            new = {"index": jnp.full_like(layer["index"], prefix_len)}
            for key, buf in layer.items():
                if key == "index":
                    continue
                new[key] = jax.lax.dynamic_update_slice_in_dim(
                    buf, rows[key].astype(buf.dtype), 0, axis=self._wax
                )
            primed.append(new)
        return primed

    def _prefill_suffix_fn(self, params, prefix_rows, prefix_len,
                           suffix_ids, suffix_len):
        """Prefill only the prompt suffix over pre-inserted prefix KV rows.

        ``prefix_rows``: per-layer {key: (1, bucket, ...)}; positions and
        causal masking follow from the cache index (= prefix_len), so this
        equals a cold prefill of the full prompt.
        """
        cache = self.model.init_cache(1, self.cache_len, dtype=self.cache_dtype)
        logits, cache = self.model.apply(
            {"params": params}, suffix_ids, deterministic=True,
            cache=self._primed(cache, prefix_rows, prefix_len)
        )
        last = jnp.take_along_axis(
            logits, (suffix_len - 1)[None, None, None], axis=1
        )[:, 0, :]
        return last, cache

    def _chunk_slot_fn(self, params, cache, chunk_ids, slot, done,
                       chunk_len):
        return self._chunk_slot_impl(self.model, params, cache, chunk_ids,
                                     slot, done, chunk_len)

    def _chunk_slot_impl(self, model, params, cache, chunk_ids, slot,
                         done, chunk_len):
        """One chunked-prefill step, DIRECTLY against the engine cache:
        slice ``slot``'s rows into a transient 1-slot view (index pinned
        to the host-tracked ``done`` — the device index may have drifted
        from other dispatches' writes into the reserved slot), run the
        fixed-size padded chunk, and scatter the chunk's KV back at
        ``(slot, done)``. The index is reset to ``done + chunk_len``
        (padding KV beyond it is overwritten by the next chunk / decode
        in order, and never attended). Only ONE slot-slice transient
        exists at a time, however many prefills are in flight.
        ``model`` is a parameter so the draft-model cache (speculative
        decoding) reuses the same machinery."""
        sax, wax = self._sax, self._wax
        mini = []
        for layer in cache:
            m = {}
            for key, buf in layer.items():
                if key == "index":
                    m["index"] = jnp.full((1,), done, jnp.int32)
                else:
                    m[key] = jax.lax.dynamic_slice_in_dim(
                        buf, slot, 1, axis=sax)
            mini.append(m)
        logits, mini = model.apply(
            {"params": params}, chunk_ids, deterministic=True, cache=mini
        )
        width = chunk_ids.shape[1]
        new = []
        for layer, m2 in zip(cache, mini):
            out = {}
            for key, buf in layer.items():
                if key == "index":
                    out["index"] = buf.at[slot].set(done + chunk_len)
                else:
                    rows = jax.lax.dynamic_slice_in_dim(
                        m2[key], done, width, axis=wax)
                    starts = [jnp.zeros((), jnp.int32)] * buf.ndim
                    starts[sax] = slot
                    starts[wax] = done
                    out[key] = jax.lax.dynamic_update_slice(
                        buf, rows.astype(buf.dtype), tuple(starts))
            new.append(out)
        last = jnp.take_along_axis(
            logits, (chunk_len - 1)[None, None, None], axis=1
        )[:, 0, :]
        return last, new

    # shared pin/advance idiom of the batched chunk, draft, and fused
    # mixed-step paths — single definition in serve/mixed_step.py
    _pin_index = staticmethod(pin_index)

    def _chunk_batch_fn(self, params, cache, chunk_ids, starts, lens):
        """Advance EVERY slot one prefill chunk in a single dispatch,
        operating on the engine cache DIRECTLY — the multi-slot twin of
        :meth:`_chunk_slot_fn`, and the r5 long-context TTFT fix: on a
        dispatch-taxed host (~120 ms tunnel RTT, docs/perf.md Finding 5)
        per-slot chunk dispatches serialize concurrent long prompts.
        (A gathered B-row mini cache was tried first and OOM'd: at 8K
        width the gather+scatter copies of full-width rows cost more
        HBM than the cache itself.)

        ``chunk_ids`` is (max_slots, chunk): real chunk tokens for
        mid-prefill rows, zeros elsewhere. ``starts`` pins each row's
        cache index for the forward (host-tracked ``done`` for prefill
        rows; the row's current length for others — their rows compute
        garbage KV beyond their index, which the overwrite-before-
        attend invariant already covers, same as the single-slot path's
        drift writes). ``lens`` is the real chunk length per row (0 for
        non-prefill rows), so the returned index ``starts + lens``
        advances exactly the prefilling rows. The caller guarantees
        every row's ``starts[i] + chunk <= cache_len`` (no clamped
        scatter can touch attended rows). Body shared with the fused
        mixed step (serve/mixed_step.py).
        """
        return batched_chunk(self.model, params, cache, chunk_ids,
                             starts, lens)

    def _draft_chunk_fn(self, params, cache, chunk_ids, slot, done,
                        chunk_len):
        """Chunked feed into the DRAFT cache (catch-up beyond the
        batched window: initial prompt sync, mostly)."""
        return self._chunk_slot_impl(self.draft_model, params, cache,
                                     chunk_ids, slot, done, chunk_len)

    def _draft_roll_fn(self, params, cache, catchup, starts, lens, *,
                       k: int):
        """One dispatch: feed each slot's un-synced tokens (``catchup``
        padded rows, index pinned to ``starts``) through the draft
        model, then roll ``k`` greedy draft tokens with a ``lax.scan``
        of single-token decodes. Returns ``(drafts (S, k), cache)``.
        The returned cache's index is ``starts + lens`` — the rolled
        tokens' KV beyond it is garbage-for-later, overwritten by the
        next round's catch-up (overwrite-before-attend, as everywhere
        else in this engine)."""
        model = self.draft_model
        logits, cache2 = model.apply(
            {"params": params}, catchup, deterministic=True,
            cache=self._pin_index(cache, starts)
        )
        # the catch-up apply advanced every row's index by the PADDED
        # width W; re-pin to the true filled length before rolling, or
        # draft tokens 2..k decode at wrong RoPE positions and write
        # their KV above the watermark (review r5: draft quality
        # collapsed to ~1 usable token whenever the gap < W)
        cache2 = self._pin_index(cache2, starts + lens)
        last = jnp.take_along_axis(
            logits, jnp.maximum(lens - 1, 0)[:, None, None], axis=1
        )[:, 0, :]
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)

        def body(carry, _):
            cache_c, tok = carry
            lg, cache_c = model.apply(
                {"params": params}, tok[:, None], deterministic=True,
                cache=cache_c)
            nxt = jnp.argmax(lg[:, 0, :], axis=-1).astype(jnp.int32)
            return (cache_c, nxt), nxt

        (cache3, _), rest = jax.lax.scan(
            body, (cache2, first), None, length=k - 1)
        drafts = jnp.concatenate(
            [first[:, None], jnp.swapaxes(rest, 0, 1)], axis=1)  # (S, k)
        return drafts, self._pin_index(cache3, starts + lens)

    def _draft_model_propose(self, active: list[int], k: int) -> dict:
        """Host side of draft-model proposal: re-sync each slot's draft
        cache to its ``slot_hist`` (chunked for big gaps), then one
        batched catch-up+roll dispatch. Returns {slot: [k tokens]}."""
        W = self._draft_window
        rows = []
        for s in active:
            hist = self.slot_hist[s]
            req = self.slot_req[s]
            if hist is None or req is None:
                continue
            if self._draft_uid[s] != req.uid:     # recycled slot
                self._draft_uid[s] = req.uid
                self._draft_sync[s] = 0
            # the roll writes up to len(hist)+k positions, and the
            # W-wide catch-up window must also fit — a clamped scatter
            # near the cache end would shift backward over already-
            # synced real KV (the idle-row clamp exists for dead rows
            # only; active rows must be exact, so skip them instead)
            # tightest post-catch-up watermark is len(hist)-1 (the last
            # token is always unsynced), so that is the window bound
            if (len(hist) + k > self.cache_len
                    or len(hist) - 1 + W > self.cache_len):
                # This slot now falls into the idle-row clamped dead
                # write below, which may overwrite its already-synced
                # draft KV near the cache tail. That is safe only while
                # the skip is permanent — so enforce the invariant:
                # drop the watermark, and any future re-admission of
                # this slot forces a full KV re-sync instead of
                # attending the clamped dead-write's corrupted rows
                # (ADVICE.md round 5).
                self._draft_uid[s] = -1
                continue
            # big gap (initial prompt): chunked feed down to <= W
            while len(hist) - int(self._draft_sync[s]) > W:
                done = int(self._draft_sync[s])
                chunk = hist[done: done + W]
                padded = np.zeros((1, W), np.int32)
                padded[0, :len(chunk)] = chunk
                _, self.draft_cache = self._draft_chunk(
                    self.draft_params, self.draft_cache,
                    jnp.asarray(padded), jnp.asarray(s, jnp.int32),
                    jnp.asarray(done, jnp.int32),
                    jnp.asarray(len(chunk), jnp.int32))
                self._draft_sync[s] = done + len(chunk)
            rows.append(s)
        if not rows:
            return {}
        catchup = np.zeros((self.max_slots, W), np.int32)
        starts = np.zeros((self.max_slots,), np.int32)
        lens = np.zeros((self.max_slots,), np.int32)
        for s in rows:
            hist = self.slot_hist[s]
            done = int(self._draft_sync[s])
            gap = hist[done:]
            catchup[s, :len(gap)] = gap
            starts[s] = done
            lens[s] = len(gap)
        for s in range(self.max_slots):
            if s not in rows:                      # idle rows: dead write
                starts[s] = min(int(self._draft_sync[s]),
                                self.cache_len - W)
        drafts, self.draft_cache = self._draft_roll(
            self.draft_params, self.draft_cache, jnp.asarray(catchup),
            jnp.asarray(starts), jnp.asarray(lens), k=k)
        drafts = np.asarray(drafts)
        out = {}
        for s in rows:
            self._draft_sync[s] = len(self.slot_hist[s])
            out[s] = [int(t) for t in drafts[s]]
        return out

    def _slot_rows_fn(self, cache, slot, bucket: int):
        """Copy ``slot``'s first ``bucket`` KV rows out as a 1-slot rows
        list (prefix-cache storage for the chunked path)."""
        rows = []
        for layer in cache:
            r = {}
            for key, buf in layer.items():
                if key == "index":
                    continue
                s = jax.lax.dynamic_slice_in_dim(
                    buf, slot, 1, axis=self._sax)
                r[key] = jax.lax.slice_in_dim(
                    s, 0, bucket, axis=self._wax)
            rows.append(r)
        return rows

    def _slot_write(self, eng, rows, slot, width):
        """Write ``rows`` (slot-axis size 1 or B) into ``eng`` at
        ``slot`` (scalar or (B,) vector), first ``width`` positions of
        the sequence axis — in either cache layout."""
        rows = rows.astype(eng.dtype)
        single = isinstance(slot, int)  # one slot: drop rows' slot axis
        if self._sax == 0:
            return eng.at[slot, :width].set(rows[0] if single else rows)
        return eng.at[:, slot, :width].set(rows[:, 0] if single else rows)

    def _insert_fn(self, engine_cache, prefill_cache, slot: int, length):
        """Copy a prefilled request's cache rows into ``slot``. The
        prefill cache may be bucket-length (one-shot path) or full-length
        (suffix/chunked paths); only its width is written."""
        new = []
        for eng, pre in zip(engine_cache, prefill_cache):
            layer = {}
            for key in eng:
                if key == "index":
                    layer["index"] = eng["index"].at[slot].set(length)
                else:
                    width = pre[key].shape[self._wax]
                    layer[key] = self._slot_write(
                        eng[key], pre[key], slot, width)
            new.append(layer)
        return new

    def _insert_batch_fn(self, engine_cache, pre_cache, slot_ids, lengths):
        """Scatter a B-row bucket-length prefill cache into B slots at
        once. ``slot_ids`` is a traced (B,) vector, so one compilation
        serves every slot combination of a given batch size."""
        new = []
        for eng, pre in zip(engine_cache, pre_cache):
            layer = {}
            for key in eng:
                if key == "index":
                    layer["index"] = eng["index"].at[slot_ids].set(lengths)
                else:
                    width = pre[key].shape[self._wax]
                    layer[key] = self._slot_write(
                        eng[key], pre[key], slot_ids, width)
            new.append(layer)
        return new

    def _insert_rows_fn(self, engine_cache, rows, slot: int, length):
        """Copy stored prefix rows (bucket-length) directly into ``slot``."""
        new = []
        for eng, layer_rows in zip(engine_cache, rows):
            layer = {}
            for key in eng:
                if key == "index":
                    layer["index"] = eng["index"].at[slot].set(length)
                else:
                    bucket = layer_rows[key].shape[self._wax]
                    layer[key] = self._slot_write(
                        eng[key], layer_rows[key], slot, bucket)
            new.append(layer)
        return new

    # --- jitted pieces, paged (serve/paged_kv.py) ----------------------------
    #
    # Each program is gather -> UNCHANGED raw engine body -> window
    # scatter, in ONE jitted dispatch. The host passes precomputed flat
    # pool-row index arrays (PagedKV.gather_idx / scatter_idx), so the
    # jitted code is pure take/at — no traced block-table arithmetic,
    # no retrace (shapes are the only static component: one compile per
    # pow2 view-width bucket per program, same bound as prefill
    # buckets). Discarded writes (idle rows, padding past a row's valid
    # window) are routed by the host indices into the reserved trash
    # page, which replaces the contiguous path's clamp-and-overwrite
    # dead-write reasoning wholesale.

    def _paged_view(self, pool, gidx, index_vec):
        """Gather each slot's pages into a contiguous cache view
        (slots, W, ...) with the per-slot index pinned from the host."""
        S, W = gidx.shape
        flat = gidx.reshape(-1)
        view = []
        for layer in pool:
            d = {"index": index_vec.astype(jnp.int32)}
            for key, buf in layer.items():
                d[key] = jnp.take(buf, flat, axis=0).reshape(
                    (S, W) + buf.shape[1:])
            view.append(d)
        return view

    def _paged_writeback(self, pool, view, sidx, wstart):
        """Scatter each row's freshly written window
        ``[wstart[s], wstart[s] + Wwin)`` from the view back into the
        pool at the host-resolved page rows ``sidx``."""
        S, Wwin = sidx.shape
        flat = sidx.reshape(-1)
        j = jnp.arange(Wwin)
        new = []
        for pl, vl in zip(pool, view):
            d = {}
            for key, buf in pl.items():
                vb = vl[key]
                W = vb.shape[1]
                pos = jnp.clip(wstart[:, None] + j[None, :], 0, W - 1)
                idx = pos.reshape((S, Wwin) + (1,) * (vb.ndim - 2))
                rows = jnp.take_along_axis(vb, idx, axis=1)
                d[key] = buf.at[flat].set(
                    rows.reshape((S * Wwin,) + vb.shape[2:]).astype(
                        buf.dtype))
            new.append(d)
        return new

    def _paged_decode_fn(self, params, pool, gidx, index_vec, sidx,
                         tokens, rng, temperature, top_k, top_p, greedy):
        view = self._paged_view(pool, gidx, index_vec)
        tok, view = self._decode_fn(params, view, tokens, rng,
                                    temperature, top_k, top_p, greedy)
        return tok, self._paged_writeback(pool, view, sidx, index_vec)

    def _paged_multi_fn(self, params, pool, gidx, index_vec, sidx,
                        tokens, rng, temperature, top_k, top_p, greedy,
                        *, n):
        view = self._paged_view(pool, gidx, index_vec)
        toks, view = decode_scan(self.model, params, view, tokens, rng,
                                 temperature, top_k, top_p, greedy, n=n)
        return toks, self._paged_writeback(pool, view, sidx, index_vec)

    def _paged_spec_fn(self, params, pool, gidx, index_vec, sidx, tokens,
                       mask, *, m):
        view = self._paged_view(pool, gidx, index_vec)
        # base = the pinned per-dispatch index; the block body's index
        # fixup matters only within the view (the pool derives each
        # dispatch's index from host slot_len), but the ACCEPTANCE and
        # the m-step extension run on device exactly like the
        # contiguous twin — rejected rows' page contents are either
        # overwritten by the extension in order or by the next real
        # write
        out, n_acc, extra, view = spec_verify_block(
            self.model, params, view, tokens, index_vec, mask, m=m)
        return out, n_acc, extra, self._paged_writeback(
            pool, view, sidx, index_vec)

    def _paged_chunk_fn(self, params, pool, gidx, chunk_ids, starts,
                        lens, sidx):
        view = self._paged_view(pool, gidx, starts)
        last, view = batched_chunk(self.model, params, view, chunk_ids,
                                   starts, lens)
        return last, self._paged_writeback(pool, view, sidx, starts)

    def _paged_mixed_fn(self, params, pool, gidx, chunk_ids, starts,
                        lens, advance, tokens, rng, temperature, top_k,
                        top_p, greedy, sidx, *, n):
        view = self._paged_view(pool, gidx, starts)
        chunk_last, toks, view = self._mixed_raw(
            params, view, chunk_ids, starts, lens, advance, tokens,
            rng, temperature, top_k, top_p, greedy, n=n)
        return chunk_last, toks, self._paged_writeback(
            pool, view, sidx, starts)

    def _paged_decode_masked_fn(self, params, pool, gidx, index_vec,
                                sidx, tokens, rng, temperature, top_k,
                                top_p, greedy, gmask):
        """Paged twin of ``_decode_masked_fn``: gather → masked decode
        body → window scatter, one dispatch (grammar on, paged layout —
        the 1-dispatch-per-step invariant is layout-independent)."""
        view = self._paged_view(pool, gidx, index_vec)
        tok, view = self._decode_masked_fn(
            params, view, tokens, rng, temperature, top_k, top_p,
            greedy, gmask)
        return tok, self._paged_writeback(pool, view, sidx, index_vec)

    def _paged_spec_masked_fn(self, params, pool, gidx, index_vec, sidx,
                              tokens, mask, gmasks, *, m):
        view = self._paged_view(pool, gidx, index_vec)
        out, n_acc, extra, view = spec_verify_block(
            self.model, params, view, tokens, index_vec, mask, m=m,
            gmasks=gmasks)
        return out, n_acc, extra, self._paged_writeback(
            pool, view, sidx, index_vec)

    def _paged_mixed_masked_fn(self, params, pool, gidx, chunk_ids,
                               starts, lens, advance, tokens, rng,
                               temperature, top_k, top_p, greedy,
                               gmask, sidx, *, n):
        view = self._paged_view(pool, gidx, starts)
        chunk_last, toks, view = self._mixed_masked_raw(
            params, view, chunk_ids, starts, lens, advance, tokens,
            rng, temperature, top_k, top_p, greedy, gmask, n=n)
        return chunk_last, toks, self._paged_writeback(
            pool, view, sidx, starts)

    def _paged_write_rows_fn(self, pool, rows, sidx):
        """Scatter B bucket-width row sets (one-shot prefill output, a
        prefix/handoff entry's rows) into pages; ``rows`` may carry an
        ``index`` key (pool iteration ignores it)."""
        S, Wb = sidx.shape
        flat = sidx.reshape(-1)
        new = []
        for pl, rl in zip(pool, rows):
            d = {}
            for key, buf in pl.items():
                rb = rl[key]
                d[key] = buf.at[flat].set(
                    rb.reshape((S * Wb,) + rb.shape[2:]).astype(
                        buf.dtype))
            new.append(d)
        return new

    def _paged_gather_rows_fn(self, pool, gidx):
        """Index-free rows list (1, W, ...) per layer — the page-wise
        twin of ``_slot_rows_fn`` for prefix/handoff entries."""
        S, W = gidx.shape
        flat = gidx.reshape(-1)
        return [
            {key: jnp.take(buf, flat, axis=0).reshape(
                (S, W) + buf.shape[1:])
             for key, buf in layer.items()}
            for layer in pool
        ]

    def _paged_page_copy_fn(self, pool, src, dst):
        """Copy one physical page's rows (COW fork: a write would land
        in a page some other reader still maps)."""
        P = self.paged.page_size
        new = []
        for layer in pool:
            d = {}
            for key, buf in layer.items():
                rows = jax.lax.dynamic_slice_in_dim(buf, src * P, P,
                                                    axis=0)
                d[key] = jax.lax.dynamic_update_slice_in_dim(
                    buf, rows, dst * P, axis=0)
            new.append(d)
        return new

    # --- paged host-side plumbing -------------------------------------------

    def _paged_width(self, need: int) -> int:
        """Pow2-bucketed view width covering ``need`` rows (bounded by
        ``cache_len`` — feasibility gates guarantee ``need`` fits)."""
        w = self.paged.page_size
        while w < need:
            w *= 2
        w = min(w, self.cache_len)
        if w < need:
            raise AssertionError(
                f"paged view width {w} < needed {need} "
                f"(cache_len {self.cache_len})")
        return w

    def _paged_index_vec(self, W: int, wwin: int) -> np.ndarray:
        """Per-row pinned cache index for a decode-family dispatch:
        active rows at their true length (the caller sized ``W`` so
        their writes fit un-clamped), mid-prefill rows at ``done``,
        free rows at 0 — clamped so even dead in-view writes stay
        inside the view (their scatter targets are trash anyway).
        Reads only host slot state, nothing paged: the CONTIGUOUS
        fused spec round reuses it with ``W = cache_len`` so the
        slot-state → index convention has one definition."""
        idx = np.zeros((self.max_slots,), np.int32)
        for s in range(self.max_slots):
            if s in self.slot_prefill:
                idx[s] = self.slot_prefill[s]["done"]
            elif self.slot_req[s] is not None:
                idx[s] = int(self.slot_len[s])
        return np.minimum(idx, max(W - wwin, 0)).astype(np.int32)

    def _paged_cow_fork(self, slot: int, start: int, width: int) -> None:
        """Fork any shared page the write window
        ``[start, start + width)`` would touch. With full-page-only
        sharing no live path writes inside a shared page (the index
        caps hits below the last prompt position, suffixes start at the
        share boundary, and spec rewind never dips below the prompt) —
        this is the defensive half of the COW contract, kept exact so a
        future scheduler change degrades to a page copy instead of
        corrupting a neighbour's prefix."""
        if width <= 0:
            return
        P = self.paged.page_size
        pool = self.paged.pool
        bt = self.paged.block_tables
        for lp in range(start // P,
                        min((start + width - 1) // P + 1,
                            self.paged.pages_per_slot)):
            page = int(bt[slot, lp])
            if page == 0 or pool.refcount(page) <= 1:
                continue
            fresh = pool.try_alloc(1)
            while fresh is None:
                # pool dry mid-fork: apply preemption pressure until a
                # page frees, exactly like the reserve loops — a single
                # victim whose pages are all still shared frees nothing
                victim = self._paged_pick_victim(exclude=slot)
                if victim is None:
                    raise RuntimeError(
                        "page pool exhausted during COW fork")
                self._paged_preempt(victim)
                fresh = pool.try_alloc(1)
            self.paged.kv = self._pg_page_copy(
                self.paged.kv, jnp.asarray(page, jnp.int32),
                jnp.asarray(fresh[0], jnp.int32))
            bt[slot, lp] = fresh[0]
            pool.release([page])

    def _paged_pick_victim(self, exclude: int | None = None) -> int | None:
        """Preemption policy: the YOUNGEST occupied slot (highest uid)
        other than ``exclude`` — least work lost, and its re-prefill is
        mostly a page-index hit since its pages are registered on the
        way out (vLLM preempts LIFO for the same reason)."""
        best, best_uid = None, -1
        for s in range(self.max_slots):
            if s == exclude or self.slot_req[s] is None:
                continue
            uid = self.slot_req[s].uid
            if uid > best_uid:
                best, best_uid = s, uid
        return best

    def _paged_preempt(self, slot: int) -> None:
        """Preempt ``slot`` by recompute: register its pages in the
        prefix index (so re-admission is mostly a page hit), release
        them, and put the request back at the HEAD of the queue —
        already-emitted tokens ride along via the resume fields, so the
        client stream continues where it left off."""
        req = self.slot_req[slot]
        st = self.slot_prefill.pop(slot, None)
        if st is None and self.slot_ready[slot]:
            hist = self.slot_hist[slot]
            req.resume_last = hist[-1]
            req.resume_budget = int(self.slot_budget[slot])
            req.prompt_ids = list(hist[:-1])
            self._paged_register_pages(hist[:-1], slot, req.adapter)
        elif st is not None and st["done"] > 0:
            # mid-prefill: nothing emitted — requeue as a fresh prompt,
            # but keep the already-computed full pages reusable
            self._paged_register_pages(req.prompt_ids[:st["done"]], slot,
                                       req.adapter)
        self.paged.release_slot(slot)
        self.slot_req[slot] = None
        self.slot_ready[slot] = False
        self.slot_budget[slot] = 0
        self.slot_hist[slot] = None
        # the adapter pin rides the requeue (req.adapter_ref stays
        # held); only the SLOT's stamp clears
        self.slot_adapter[slot] = None
        # the grammar cursor itself stays on req.constraint_state —
        # re-admission resumes from the exact grammar position
        self.slot_constraint[slot] = None
        if self.draft_model is not None:
            # force a full draft-cache re-sync if this slot is reused
            # for this request (its target KV is being recomputed)
            self._draft_uid[slot] = -1
        self.preemptions += 1
        self._hbm.note_reclaim("kv_pool.pages", "preempt")
        # the re-admission's wait + recompute are charged to the
        # preempt_recompute critical-path segment from this stamp on;
        # the queue-wait origin moves here too (the slotted time just
        # spent is already booked to its dispatch segments)
        req.requeue_time = time.monotonic()
        req.cp_queue_origin = req.requeue_time
        with self.pending.mutex:
            self.pending.queue.appendleft(req)
        self._log.info(
            "preempted slot %d (uid %d) under page-pool pressure; "
            "request requeued for recompute (resume at %d tokens)",
            slot, req.uid, len(req.prompt_ids))

    def _paged_reserve_active(self, active: list[int],
                              width: int) -> list[int]:
        """Reserve ``width`` more positions for every ready slot before
        a decode-family dispatch; preempted victims drop out of
        ``active``, and a slot that cannot grow even as the last
        occupant finishes with the contiguous layout's ``cache``
        reason. Returns the surviving active list."""
        out = list(active)
        for s in list(out):
            if s not in out or self.slot_req[s] is None:
                continue
            while not self.paged.extend(s, int(self.slot_len[s]) + width):
                victim = self._paged_pick_victim(exclude=s)
                if victim is None:
                    self._finish_slot(s, "cache")
                    if s in out:
                        out.remove(s)
                    break
                self._paged_preempt(victim)
                if victim in out:
                    out.remove(victim)
        return [s for s in out if self.slot_req[s] is not None
                and self.slot_ready[s]]

    def _pulse_view(self, W: int, n_slots: int | None = None) -> None:
        """Ledger-pulse this dispatch's transient gather view (account
        ``transient_view``): W tokens × the viewed rows at the pool's
        byte rate. XLA frees the view inside the dispatch, so only the
        account's high-water mark moves — the pool+view coexistence
        peak ROADMAP item 1's in-place paged attention reclaims."""
        self._hbm.pulse("transient_view", self.paged.view_bytes(W, n_slots))

    def _paged_decode_dispatch(self, active: list[int], n: int, sub,
                               gmask=None, lora=None):
        """Issue one paged decode dispatch (single-token via the
        ``_decode_fn`` body at n==1 so the rng use matches the
        contiguous program exactly; an n-step scan block otherwise).
        Pages for the writes were reserved by the caller. ``gmask``
        (constrained decoding) routes to the masked twin — the planner
        guarantees n == 1 then. ``lora`` (multi-LoRA) routes to the
        adapter twin of whichever program would run; both compose.
        Returns the sampled tokens, shape (max_slots, n)."""
        W = self._paged_width(
            max(int(self.slot_len[s]) for s in active) + n)
        self._pulse_view(W)
        idxv = self._paged_index_vec(W, n)
        valid = np.zeros((self.max_slots,), np.int32)
        for s in active:
            valid[s] = n
            self._paged_cow_fork(s, int(self.slot_len[s]), n)
        gidx = jnp.asarray(self.paged.gather_idx(W))
        sidx = jnp.asarray(self.paged.scatter_idx(idxv, valid, n))
        idxv = jnp.asarray(idxv)
        tokens = jnp.asarray(self.slot_last_token)
        args = (jnp.asarray(self._temperature),
                jnp.asarray(self._top_k),
                jnp.asarray(self._top_p),
                jnp.asarray(self._greedy))
        kw = {} if lora is None else {"lora": lora}
        if gmask is not None:
            if n != 1:
                raise AssertionError(
                    f"grammar-masked paged decode must be n=1, got {n}")
            fn = (self._pg_decode_masked if lora is None
                  else self._pg_decode_masked_lora)
            tok, self.paged.kv = fn(
                self.params, self.paged.kv, gidx, idxv, sidx, tokens,
                sub, *args, jnp.asarray(gmask), **kw)
            return tok[:, None]
        if n == 1:
            fn = self._pg_decode if lora is None else self._pg_decode_lora
            tok, self.paged.kv = fn(
                self.params, self.paged.kv, gidx, idxv, sidx, tokens,
                sub, *args, **kw)
            return tok[:, None]
        fn = self._pg_multi if lora is None else self._pg_multi_lora
        toks, self.paged.kv = fn(
            self.params, self.paged.kv, gidx, idxv, sidx, tokens, sub,
            *args, n=n, **kw)
        return toks

    def _paged_register_pages(self, token_ids, slot: int,
                              adapter: str | None = None) -> None:
        """Index every FULL page of ``token_ids`` (whose KV fills
        ``slot``'s first pages) for refcounted sharing. ``adapter``
        namespaces the chain keys (multi-LoRA prefix isolation)."""
        if self.prefix_cache is None:
            return
        nfull = len(token_ids) // self.paged.page_size
        if nfull <= 0:
            return
        pages = self.paged.slot_pages(slot)[:nfull]
        if len(pages) == nfull:
            self.prefix_cache.register(
                self._ns_ids(adapter,
                             token_ids[:nfull * self.paged.page_size]),
                pages)

    def _paged_gather_entry(self, slot: int, plen: int, last_logits):
        """Page-aligned prefix entry for ``slot``'s first ``plen``
        positions — rows span ceil(plen/P)*P, not a pow2 bucket nor
        ``cache_len``, so handoff/offload ship only live pages."""
        from llm_in_practise_tpu.serve import prefix_cache as pc
        from llm_in_practise_tpu.serve.paged_kv import pages_for

        width = pages_for(plen, self.paged.page_size) * self.paged.page_size
        gidx = self.paged.row_gather_idx(slot, width)
        rows = self._pg_gather_rows(self.paged.kv, jnp.asarray(gidx))
        return pc.PrefixEntry(length=plen, bucket=width, rows=rows,
                              last_logits=last_logits, slot_axis=0,
                              page_size=self.paged.page_size)

    def _paged_insert_entry(self, slot: int, entry, length: int) -> None:
        """Scatter a row-based entry's first ``length`` positions into
        ``slot``'s (already reserved) pages. Rows are padded on host to
        a pow2 bucket so the jitted scatter keeps a bounded compile
        set whatever widths the tiers shipped."""
        self._paged_cow_fork(slot, 0, length)
        Wb = self._bucket_for(length)
        padded = []
        for layer in entry.rows:
            d = {}
            for key, arr in layer.items():
                if key == "index":
                    continue
                # tier/handoff entries reach a paged engine as HOST
                # numpy (TieredKV.lookup(device=False), HostEntry), so
                # this materializes nothing from the device
                arr = np.asarray(arr)  # graftlint: disable=host-sync
                out = np.zeros((1, Wb) + arr.shape[2:], arr.dtype)
                out[:, :min(length, arr.shape[1])] = (
                    arr[:, :min(length, arr.shape[1])])
                d[key] = out
            padded.append(d)
        sidx = self.paged.rows_scatter_idx([slot], [length], Wb)
        self.paged.kv = self._pg_write_rows(
            self.paged.kv, padded, jnp.asarray(sidx))

    # --- public API ----------------------------------------------------------

    def _shed(self, req: Request) -> Request:
        """Fail a request fast with ``finish_reason="queue_full"``: the
        stream closes immediately with zero tokens, the caller (API
        layer / gateway) turns that into 429 + retry-elsewhere."""
        req.finish_time = time.monotonic()
        req.finish_reason = "queue_full"
        self._record_finished(req)
        req.tokens.put(_FINISH)
        with self.stats.lock:
            self.stats.requests_shed += 1
        return req

    def submit(self, prompt_ids, params: SamplingParams | None = None, *,
               kv_entry=None, handoff_id: str | None = None,
               trace=None, adapter: str | None = None,
               session_id: str | None = None) -> Request:
        """``kv_entry`` (optional): a :class:`~.kv_pool.HostEntry` claimed
        from a handoff store — validated and uploaded HERE, on the
        caller's (HTTP) thread, so the engine loop admits it as a pure
        direct insert. ``handoff_id`` (optional): prefill-only request —
        publish the prompt KV under this id instead of decoding.
        ``trace`` (optional): a :class:`~..obs.trace.TraceContext` the
        engine parents this request's phase spans to.
        ``adapter`` (optional): registered LoRA adapter name to decode
        under (serve/multi_lora.py); unknown names raise ValueError on
        this thread, before anything is queued.
        ``session_id`` (optional): conversation handle — on finish the
        turn's KV pages stay pinned under it (serve/sessions.py) and
        admission consults the session store's pending fleet pulls."""
        params = params or SamplingParams()
        prompt_ids = list(map(int, prompt_ids))
        max_prompt = self.cache_len - 2
        if len(prompt_ids) > max_prompt:  # sliding-window crop (reference
            prompt_ids = prompt_ids[-max_prompt:]  # minigpt/generate.py:18-20)
        req = Request(next(self._uid), prompt_ids, params, engine=self,
                      handoff_id=handoff_id, trace=trace, adapter=adapter,
                      session_id=session_id)
        if session_id is not None and self.session_store is not None:
            self.session_store.touch(session_id)
        if (self.paged is not None
                and not self.paged.fits_ever(len(prompt_ids) + 1)):
            # the prompt can NEVER fit the page pool (prompt pages + the
            # first decode page exceed capacity even on an empty pool) —
            # fail synchronously with a reason the API layer maps to a
            # 422, instead of letting the request age out of the queue
            # as a generic queue_full after queue_timeout_s
            self.rejected_too_large += 1
            with self.stats.lock:
                self.stats.requests_total += 1
            req.finish_time = time.monotonic()
            req.finish_reason = "too_large"
            self._record_finished(req)
            req.tokens.put(_FINISH)
            return req
        if adapter is not None:
            # pin the adapter for this request's whole lifetime — a
            # refcounted row can't be evicted (or hot-swapped) while a
            # request decodes under it; _record_finished releases
            if self.adapter_registry is None:
                raise ValueError(
                    f"adapter {adapter!r} requested but the engine has "
                    "no adapter_registry")
            try:
                self.adapter_registry.acquire(adapter)
            except KeyError:
                raise ValueError(
                    f"unknown adapter {adapter!r}") from None
            req.adapter_ref = True
        # the upload must land on the request BEFORE it is queued — the
        # engine thread may admit it the instant the put releases
        if kv_entry is not None:
            t0 = time.monotonic()
            req.kv_entry = self._accept_external_kv(kv_entry, prompt_ids)
            # validate + device upload of the claimed entry — the
            # decode-side half of the handoff wire cost (the kv-pool
            # server cross-checks with kvpool_handoff_wire_seconds)
            req.cp_add("handoff_wire", time.monotonic() - t0)
        with self.stats.lock:
            self.stats.requests_total += 1
        with self._submit_lock:
            if (self.max_queue is not None
                    and self.pending.qsize() >= self.max_queue):
                shed = True
            else:
                shed = False
                self.pending.put(req)
        if shed:
            # the caller (api layer) re-pins a claimed handoff entry on
            # this path so the retry elsewhere can still use it
            return self._shed(req)
        with self.stats.lock:
            self.stats.queue_depth = self.pending.qsize()
        self._wake.set()
        return req

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.cache_len

    # --- multi-LoRA plumbing (serve/multi_lora.py, ISSUE 15) -----------------

    def _ns_ids(self, adapter: str | None, token_ids) -> list[int]:
        """Prefix-cache key namespace: tokens shifted by the adapter's
        registry generation (``t + (ns << 32)``) — length-preserving and
        injective (Python ints don't narrow), so BOTH cache layouts'
        token-tuple keys (PrefixLRU windows, kv-pool tiers, per-page
        paged chains) isolate tenants without any cache-side change.
        LoRA targets include v_proj by default, so adapter KV differs
        from base KV row-for-row — cross-tenant hits would be silent
        corruption, and a hot-swapped adapter name must miss its own
        stale entries (fresh ns per register covers that). Base requests
        (ns 0) keep the identity mapping: existing keys, entries and
        cross-restart pool contents stay valid."""
        ns = (self.adapter_registry.ns_of(adapter)
              if self.adapter_registry is not None and adapter is not None
              else 0)
        if ns == 0:
            return token_ids if isinstance(token_ids, list) \
                else list(token_ids)
        shift = ns << 32
        return [int(t) + shift for t in token_ids]

    def _lora_args(self):
        """Gathered-BGMV jit args for a SLOT-WIDE dispatch (decode /
        mixed / spec / chunk_batch rows are the max_slots slot plane),
        or None when every slot is base — the caller then runs the base
        executable and the twin never traces. Computed OUTSIDE the
        dispatch_wait scope (the gmask idiom): the bank snapshot is
        host work, booked as ``adapter_gather``."""
        reg = self.adapter_registry
        if reg is None or all(a is None for a in self.slot_adapter):
            return None
        with self.steptrace.scope("adapter_gather"):
            return reg.dispatch_args(list(self.slot_adapter))

    def _lora_args_for(self, adapters: list[str | None]):
        """Gathered-BGMV jit args for a dispatch whose batch rows are
        REQUESTS (grouped prefill) or a single slot, not the slot
        plane."""
        reg = self.adapter_registry
        if reg is None or all(a is None for a in adapters):
            return None
        with self.steptrace.scope("adapter_gather"):
            return reg.dispatch_args(list(adapters))

    def _trace_phase(self, req: Request, name: str, duration_s: float,
                     **attrs) -> None:
        """Record one engine phase span for a traced request. Untraced
        requests (direct engine use, benches) cost one ``is None``."""
        if req.trace is None:
            return
        self.tracer.record(name, req.trace, duration_s=duration_s,
                           uid=req.uid, **attrs)

    @staticmethod
    def _note_cache_outcome(req: Request, hit, plen: int) -> None:
        """Label the request's warm-vs-cold TTFT outcome from the
        prefix-/handoff-hit the admission path resolved. First admission
        wins: a preempt-resume re-admission page-hits its OWN registered
        pages and must not relabel a cold request as warm."""
        if req.cache_outcome is not None or req.resume_last is not None:
            return
        if hit is None:
            req.cache_outcome = "cold"
        elif getattr(hit, "length", 0) >= plen:
            req.cache_outcome = "hit"
        else:
            req.cache_outcome = "partial"

    @staticmethod
    def _cp_pf_spent(req: Request) -> float:
        """Prefill-attributed critical-path seconds booked so far —
        the admission segment is the admit wall MINUS what the inner
        prefill dispatches already claimed."""
        return (req.cp.get("prefill_dispatch", 0.0)
                + req.cp.get("preempt_recompute", 0.0))

    def _cp_admission(self, req: Request, dt: float, pre: float) -> None:
        req.cp_add("admission",
                   max(0.0, dt - (self._cp_pf_spent(req) - pre)))

    def _record_finished(self, req: Request) -> None:
        """Finalize the request's critical-path breakdown and remember
        it for ``GET /debug/requests``. ``host_gap`` is the residual
        wall time no attributed segment claims — exactly the
        between-dispatch host time the steptrace recorder measures per
        step, here per request. Runs on whichever thread finishes the
        request (engine, publisher, HTTP shed path)."""
        wall = (req.finish_time or time.monotonic()) - req.submit_time
        if req.finish_reason == "queue_full" and not req.cp:
            # a shed spent its whole life waiting; say so
            req.cp["queue_wait"] = wall
        attributed = sum(v for k, v in req.cp.items()
                         if k != "stream_flush")
        req.cp["host_gap"] = max(0.0, wall - attributed)
        with self.stats.lock:
            cp = self.stats.critical_path
            for seg, dt in req.cp.items():
                # stream_flush aggregates through note_stream_flush on
                # the handler thread — the ONLY aggregate writer for
                # that segment; summing it here too would double-book a
                # stream that closed before the engine finished (client
                # disconnect mid-decode)
                if seg in cp and seg != "stream_flush":
                    cp[seg] += dt
        # the ring must not pin KV: a shed request can still hold the
        # device/host entry uploaded at submit() (admission, which
        # nulls it, never ran) — 128 retained multi-MB buffers under
        # sustained overload is an OOM, not a debug view
        req.kv_entry = None
        # multi-LoRA: drop the submit-time adapter pin and book the
        # tenant's generated tokens (llm_tenant_tokens_total{adapter=…}).
        # This is the SINGLE finish funnel — sheds, handoff publishes
        # and normal finishes all pass here exactly once; preempt
        # requeues do NOT, so the ref rides the requeue.
        if req.adapter_ref:
            req.adapter_ref = False
            reg = self.adapter_registry
            if reg is not None:
                reg.release(req.adapter)
                reg.note_tokens(req.adapter, req.n_generated)
        self.finished.append(req)

    def _note_device_phase(self, phase: str, *, tokens: int,
                           attended_keys: float, weight_passes: float,
                           kv_read_tokens: float, dt: float) -> None:
        """Book one dispatch's device-plane sample (obs/cost.py → the
        llm_dispatch_mfu / llm_dispatch_hbm_bw_util gauges). ``dt`` is
        dispatch-issue + result-fetch wall time on this thread; with no
        cost model only tokens-per-dispatch is recorded. Draft-model
        dispatches are not booked (the cost model covers the target
        model; the draft's work would inflate both utilizations)."""
        # host-gap recorder: the forced dispatch window is device-busy
        # time; it is deducted from the surrounding host activity so the
        # step partition never double-counts this wall clock
        self.steptrace.note_device(dt, phase)
        cm = self.cost_model
        mfu = bw = None
        if cm is not None and dt > 0:
            mfu = cm.mfu(cm.step_flops(tokens, attended_keys), dt)
            bw = cm.hbm_util(
                cm.step_bytes(weight_passes, kv_read_tokens, tokens), dt)
        if cm is not None and self.tp > 1:
            # TP collective attribution: every forward position pays
            # the row-parallel activation all-reduces — analytic
            # per-chip wire bytes + lower-bound ICI seconds
            # (llm_collective_{bytes,seconds}_total)
            cb = cm.collective_bytes(
                tokens, quantized=self.tp_quantized_collectives)
            self.collective_bytes_total += cb
            self.collective_seconds_total += cm.collective_seconds(cb)
        self.dispatch_meter.note_phase(phase, tokens=tokens, duration_s=dt,
                                       mfu=mfu, hbm_bw_util=bw)

    def _admit(self) -> bool:
        """Move pending requests into free slots. Plain one-shot prefills
        (no prefix hit, no chunking) are collected and run as BATCHED
        dispatches; prefix hits and chunked prompts take their own paths."""
        admitted = False
        self._paged_admit_blocked = False
        # snapshot the knob: it is the blessed runtime attribute (the
        # serve bench flips it post-warmup from another thread) and a
        # mid-step disable to None must not turn a passed `is not None`
        # check into a float<=None TypeError further down
        timeout_s = self.queue_timeout_s
        if timeout_s is not None:
            # shed stale requests every engine step, not only when a
            # slot frees — a client whose deadline passed should fail AT
            # the deadline, not after burning a full queue wait. FIFO
            # order means staleness is monotone from the head.
            now = time.monotonic()
            with self.steptrace.scope("queue_drain"):
                while True:
                    with self.pending.mutex:
                        head = (self.pending.queue[0]
                                if self.pending.queue else None)
                        if (head is None
                                or head.resume_last is not None
                                or now - head.submit_time <= timeout_s):
                            # preempted-resume requests are exempt: their
                            # stream already started, so a deadline shed
                            # would truncate a live response
                            break
                        self.pending.queue.popleft()
                    self._shed(head)
        batch: list[tuple[int, Request, int]] = []
        deferred: list[tuple[int, Request, int]] = []
        seen: set[tuple[int, ...]] = set()
        for slot in range(self.max_slots):
            if self.slot_req[slot] is not None:
                continue
            if self._paged_admit_blocked:
                # the page pool could not cover the previous admission
                # this step — later queue entries would fail the same
                # reservation (and double-count admission telemetry)
                break
            req = None
            with self.steptrace.scope("queue_drain"):
                while req is None:
                    try:
                        req = self.pending.get_nowait()
                    except queue.Empty:
                        break
                    if (timeout_s is not None
                            and req.resume_last is None
                            and time.monotonic() - req.submit_time
                            > timeout_s):
                        # waited past the deadline: the client is better
                        # served by a fast 429 it can retry elsewhere
                        # than by a TTFT already worse than any SLA
                        self._shed(req)
                        req = None
            if req is None:
                break
            # queue wait = submit → a slot freed for it; under sustained
            # load this span is where a request's time actually goes
            self._trace_phase(req, "engine.queue_wait",
                              time.monotonic() - req.submit_time,
                              slot=slot)
            cp_base = req.cp_queue_origin
            if cp_base is None:
                # first pop: a claimed-KV upload at submit() runs
                # BEFORE queueing and is already booked to
                # handoff_wire — shift the origin so queue_wait
                # doesn't re-claim that window (the segments must
                # partition, not overlap)
                cp_base = req.submit_time + req.cp.get("handoff_wire", 0.0)
            req.cp_add("queue_wait",
                       max(0.0, time.monotonic() - cp_base))
            # re-arm: if admission blocks (dry page pool) and requeues
            # this request, the next pop books only [here, next pop]
            req.cp_queue_origin = time.monotonic()
            plen = len(req.prompt_ids)
            hit = self._lookup_prefix(req, plen)
            if (self.role == "decode"
                    and (hit is None or hit.length < plen)):
                # graceful degradation, but visible: actual prefill
                # work on a decode replica is exactly the interference
                # disaggregation removes. Counted HERE — where the
                # prefill is really about to run — so neither sheds nor
                # full prefix/handoff hits inflate the signal.
                self.local_prefills += 1
                if not self._decode_prefill_logged:
                    self._decode_prefill_logged = True
                    self._log.warning(
                        "decode-role engine is prefilling locally "
                        "(handoff entry lost or request arrived without "
                        "one); serving continues but this replica is no "
                        "longer interference-free — see "
                        "llm_local_prefills_total")
            if hit is None and not self._should_chunk(0, plen):
                self.slot_req[slot] = req   # reserve; activated post-batch
                self.slot_adapter[slot] = req.adapter
                self.slot_ready[slot] = False
                cacheable = (self.prefix_cache is not None
                             and plen >= self.prefix_cache.min_prefix)
                if cacheable and (req.adapter,
                                  tuple(req.prompt_ids)) in seen:
                    # duplicate of a prompt prefilling THIS burst: after
                    # the batch stores its prefix entry this becomes a
                    # full-prefix hit — keep the sequential path's
                    # intra-burst reuse instead of prefilling it again
                    # (its cache label comes from that later lookup)
                    deferred.append((slot, req, plen))
                else:
                    if cacheable:
                        seen.add((req.adapter, tuple(req.prompt_ids)))
                    self._note_cache_outcome(req, None, plen)
                    batch.append((slot, req, plen))
            else:
                t0 = time.monotonic()
                path = ("kv_direct_insert"
                        if hit is not None and hit.length == plen
                        else "prefill")
                pre = self._cp_pf_spent(req)
                self._begin_prefill(req, slot, plen, hit=hit)
                dt = time.monotonic() - t0
                self._trace_phase(req, "engine.admit", dt, slot=slot,
                                  path=path, prompt_tokens=plen)
                self._cp_admission(req, dt, pre)
            admitted = True
        if batch:
            t0 = time.monotonic()
            pre = {req.uid: self._cp_pf_spent(req) for _, req, _ in batch}
            self._prefill_batch(batch)
            dt = time.monotonic() - t0
            for slot, req, plen in batch:
                self._trace_phase(req, "engine.admit", dt, slot=slot,
                                  path="oneshot_batch", prompt_tokens=plen,
                                  batched=len(batch))
                self._cp_admission(req, dt, pre[req.uid])
        for slot, req, plen in deferred:
            t0 = time.monotonic()
            pre = self._cp_pf_spent(req)
            self._begin_prefill(req, slot, plen)  # fresh lookup: now a hit
            dt = time.monotonic() - t0
            self._trace_phase(req, "engine.admit", dt,
                              slot=slot, path="deferred_prefix_hit",
                              prompt_tokens=plen)
            self._cp_admission(req, dt, pre)
        with self.stats.lock:
            self.stats.queue_depth = self.pending.qsize()
            self.stats.active_slots = sum(r is not None for r in self.slot_req)
        return admitted

    def _prefill_batch(self, batch: list[tuple[int, "Request", int]]) -> None:
        """One-shot prefill for several admitted requests in as few
        dispatches as possible: group by bucket, split each group into
        power-of-two sub-batches (compiled variants bounded at
        log2(max_slots) per bucket), sample every first token in ONE
        batched call."""
        if self.paged is not None:
            # page-granular admission: reserve ACTUAL prompt pages (+1
            # decode token) per member; a dry pool requeues the member
            # and blocks further admission this step
            kept, blocked = [], []
            for slot, req, plen in batch:
                if (not self._paged_admit_blocked
                        and self.paged.extend(slot, plen + 1)):
                    kept.append((slot, req, plen))
                else:
                    self.slot_req[slot] = None
                    self.slot_adapter[slot] = None
                    self.slot_ready[slot] = False
                    self._paged_admit_blocked = True
                    blocked.append(req)
            # requeue in REVERSE so the oldest blocked member lands at
            # the queue head (appendleft in forward order would invert
            # FIFO — and the timeout-shed loop assumes head-monotone
            # staleness)
            with self.pending.mutex:
                for req in reversed(blocked):
                    self.pending.queue.appendleft(req)
            batch = kept
            if not batch:
                return
        by_bucket: dict[int, list[tuple[int, Request, int]]] = {}
        for slot, req, plen in batch:
            by_bucket.setdefault(self._bucket_for(plen), []).append(
                (slot, req, plen))
        for bucket, group in by_bucket.items():
            i = 0
            while i < len(group):
                size = 1 << ((len(group) - i).bit_length() - 1)
                part = group[i:i + size]
                i += size
                with self.steptrace.scope("index_build"):
                    ids = np.zeros((size, bucket), np.int32)
                    lens = np.zeros((size,), np.int32)
                    for j, (_, req, plen) in enumerate(part):
                        ids[j, :plen] = req.prompt_ids
                        lens[j] = plen
                # per-REQUEST adapter rows (the one dispatch whose batch
                # dim is requests, not the slot plane)
                lora = self._lora_args_for(
                    [r.adapter for _, r, _ in part])
                kw = {} if lora is None else {"lora": lora}
                pf = self._prefill if lora is None else self._prefill_lora
                with self.steptrace.scope("dispatch_wait"):
                    t0 = time.monotonic()
                    last, pre = pf(
                        self.params, jnp.asarray(ids), jnp.asarray(lens),
                        **kw)
                    if self.paged is not None:
                        sidx = self.paged.rows_scatter_idx(
                            [p[0] for p in part], [p[2] for p in part],
                            bucket)
                        self.paged.kv = self._pg_write_rows(
                            self.paged.kv, pre, jnp.asarray(sidx))
                    else:
                        slot_ids = np.array([p[0] for p in part],
                                            np.int32)
                        self.cache = self._insert_batch(
                            self.cache, pre, jnp.asarray(slot_ids),
                            jnp.asarray(lens))
                    self.rng, sub = jax.random.split(self.rng)
                    logits = last.astype(jnp.float32)
                    if any(r.params.constraint is not None
                           for _, r, _ in part):
                        # constrained members' first tokens obey their
                        # grammar start states; zero rows leave the
                        # rest of the batch untouched
                        logits = logits + self._grammar_mask_rows(
                            [self._ensure_constraint(r)
                             for _, r, _ in part])
                    first = np.asarray(sample_token_batched(
                        sub, logits,
                        temperature=jnp.asarray(
                            [r.params.temperature for _, r, _ in part],
                            jnp.float32),
                        top_k=jnp.asarray(
                            [r.params.top_k for _, r, _ in part],
                            jnp.int32),
                        top_p=jnp.asarray(
                            [r.params.top_p for _, r, _ in part],
                            jnp.float32),
                        greedy=jnp.asarray(
                            [r.params.greedy for _, r, _ in part], bool),
                    ))
                    # device plane: useful (un-padded) tokens only, so
                    # bucket padding shows up as lost MFU — which it is.
                    # (dt is honest: np.asarray above forced the chain.)
                    keys = sum(CostModel.chunk_keys(p, 0)
                               for _, _, p in part)
                    dt = time.monotonic() - t0
                    self._note_device_phase(
                        "prefill",
                        tokens=sum(p for _, _, p in part),
                        attended_keys=keys,
                        weight_passes=1, kv_read_tokens=keys,
                        dt=dt)
                for _, req, _ in part:
                    # every member waited the whole batched dispatch
                    req.cp_add("prefill_dispatch", dt)
                with self.steptrace.scope("sample_commit"):
                    for j, (slot, req, plen) in enumerate(part):
                        if self.paged is not None:
                            # rows are in pages now — register them
                            # instead of slicing copies (handoff
                            # gathers page-wise)
                            row_slices = None
                            self._paged_store_prefix(req, plen, slot,
                                                     last[j:j + 1])
                        else:
                            sl = ((slice(None),) * self._sax
                                  + (slice(j, j + 1),))
                            row_slices = [
                                {k: v[sl] for k, v in layer.items()
                                 if k != "index"} for layer in pre]
                            self._store_prefix(req, plen, row_slices,
                                               last[j:j + 1])
                        if req.handoff_id is not None:
                            # the group's bucket IS _bucket_for(plen),
                            # so these rows are already handoff-width —
                            # skip the redundant _slot_rows gather
                            self._complete_handoff(slot, req, plen,
                                                   last[j:j + 1],
                                                   rows=row_slices)
                        else:
                            self._activate_with_token(slot, req, plen,
                                                      int(first[j]))

    def _complete_handoff(self, slot: int, req: Request, plen: int,
                          last_logits, rows=None) -> None:
        """Prefill-role completion: the prompt's KV rows are in ``slot``
        — queue them (plus the last-position logits the decode replica
        samples the first token from) for publication under the
        request's handoff id, finish the request WITHOUT decoding, and
        free the slot. The engine thread pays only the row gather (one
        dispatch, skipped when the batch/chunked paths already hold the
        rows); the device→host copy and the TCP put run on a dedicated
        publisher thread — a slow or dead pool server must stall the
        WAITING handoff request (whose consumer blocks on ``_FINISH``
        until the publish lands), never the engine loop that other
        requests' decode blocks run on. ``rows``: bucket-width
        index-free row dicts already sliced from the prefill cache."""
        from llm_in_practise_tpu.serve import prefix_cache as pc

        with self.steptrace.scope("publish"):
            if self.paged is not None:
                # page-wise handoff: the entry spans ceil(plen/P)*P rows
                # — only live pages ship over the wire, not a pow2
                # bucket (a 200-token prompt is 13 16-row pages = 208
                # rows, where the bucket path shipped 256). The gather
                # COPIES the page rows into fresh buffers, so the
                # slot's pages free right here.
                entry = self._paged_gather_entry(slot, plen, last_logits)
                self.paged.release_slot(slot)
            else:
                bucket = self._bucket_for(plen)
                if rows is None:
                    rows = self._slot_rows(self.cache,
                                           jnp.asarray(slot, jnp.int32),
                                           bucket=bucket)
                # _slot_rows / the batch slices COPY the rows into fresh
                # buffers, so the entry is independent of the slot,
                # which frees right here
                entry = pc.PrefixEntry(length=plen, bucket=bucket,
                                       rows=rows,
                                       last_logits=last_logits,
                                       slot_axis=self._sax)
            self.slot_req[slot] = None
            self.slot_ready[slot] = False
            self.slot_budget[slot] = 0
            self.slot_hist[slot] = None
            self.slot_adapter[slot] = None
            if not self._publishers:
                self._publishers = [
                    threading.Thread(target=self._run_publisher,
                                     daemon=True)
                    for _ in range(self._n_publishers)]
                for t in self._publishers:
                    t.start()
            self._publish_queue.put((req, plen, entry))

    def _run_publisher(self) -> None:
        """Handoff publisher loop: device→host copy + store put, off the
        engine thread. Finishes each request only once its entry is
        pinned (or the publish definitively failed), so the router's
        wait on the prefill response still means 'the KV is claimable'.
        Several of these run concurrently — see ``_n_publishers``."""
        from llm_in_practise_tpu.serve.kv_pool import entry_to_host

        while True:
            req, plen, entry = self._publish_queue.get()
            t0 = time.monotonic()
            staged = 0
            try:
                if self.handoff is None:
                    raise RuntimeError("engine has no handoff store")
                host = entry_to_host(entry)
                # ledger account handoff_staging (host plane): the
                # entry's RAM between the device→host copy and the
                # pool put — freed below whether the put lands or not
                staged = host_entry_bytes(host)
                self._hbm.book("handoff_staging", staged)
                self.handoff.publish(req.handoff_id, host)
            except Exception as e:  # noqa: BLE001 — transport/pool
                # refusal: the request must still finish (the caller
                # re-prefills at a serving replica)
                with self._publish_lock:
                    self.handoff_publish_failed += 1
                self._log.warning("handoff publish %s failed: %s: %s",
                                  req.handoff_id, type(e).__name__, e)
                req.finish_reason = "handoff_failed"
            else:
                with self._publish_lock:
                    self.handoff_published += 1
                req.finish_reason = "handoff"
            if staged:
                self._hbm.book("handoff_staging", -staged)
            # device→host copy + store put — the KV-transfer cost the
            # disaggregation trade pays; its span is how a dashboard
            # shows handoff overhead per request
            self._trace_phase(req, "handoff.publish",
                              time.monotonic() - t0,
                              handoff_id=req.handoff_id,
                              prompt_tokens=plen,
                              ok=req.finish_reason == "handoff")
            req.cp_add("handoff_wire", time.monotonic() - t0)
            req.finish_time = time.monotonic()
            # KV-claimable time is this request's TTFT analog: per-role
            # llm_ttft_seconds on a prefill replica = prefill service
            req.first_token_time = req.finish_time
            self._record_finished(req)
            req.tokens.put(_FINISH)
            self.stats.observe_finished(req)

    def _activate(self, slot: int, req: Request, plen: int, last_logits,
                  rows=None):
        """Slot bookkeeping once the prompt's KV is in place; samples the
        first token from the prefill logits. ``rows`` forwards
        already-gathered KV rows to the handoff path (chunked prefill
        gathers them for the prefix store anyway)."""
        if req.handoff_id is not None:
            return self._complete_handoff(slot, req, plen, last_logits,
                                          rows=rows)
        if req.resume_last is not None:
            # preemption resume: the "next" token was already emitted
            # before the preempt — no sampling, no rng split (the
            # stream must not fork from what the client saw)
            return self._activate_with_token(slot, req, plen, 0)
        self.rng, sub = jax.random.split(self.rng)
        logits = last_logits.astype(jnp.float32)
        cs = self._ensure_constraint(req)
        if cs is not None:
            # the FIRST generated token is sampled from the prefill
            # logits — it must obey the grammar's start state too
            logits = logits + self._grammar_mask_rows([cs])
        first = sample_token_batched(
            sub, logits,
            temperature=jnp.asarray([req.params.temperature], jnp.float32),
            top_k=jnp.asarray([req.params.top_k], jnp.int32),
            top_p=jnp.asarray([req.params.top_p], jnp.float32),
            greedy=jnp.asarray([req.params.greedy], bool),
        )
        self._activate_with_token(slot, req, plen, int(first[0]))

    def _activate_with_token(self, slot: int, req: Request, plen: int,
                             first_id: int):
        resumed = req.resume_last is not None
        if resumed:
            # preemption resume (paged layout): the prompt now IS the
            # full emitted history minus the resume token, whose KV is
            # the next decode's to write. Nothing is emitted here and
            # the TTFT stamp is the original one.
            first_id = req.resume_last
            req.resume_last = None
        else:
            req.first_token_time = time.monotonic()
        self.slot_req[slot] = req
        self.slot_ready[slot] = True
        self.slot_last_token[slot] = first_id
        self.slot_len[slot] = plen
        self.slot_budget[slot] = (req.resume_budget if resumed
                                  else req.params.max_tokens - 1)
        self._temperature[slot] = req.params.temperature
        self._top_k[slot] = req.params.top_k
        self._top_p[slot] = req.params.top_p
        self._greedy[slot] = req.params.greedy
        self.slot_hist[slot] = list(req.prompt_ids) + [first_id]
        # constrained decoding: install the request's grammar cursor
        # (resume keeps the preempt-time position — already advanced
        # over everything the client saw, including the resume token)
        cs = self.slot_constraint[slot] = self._ensure_constraint(req)
        if not resumed:
            self._emit(slot, first_id)
            self._constraint_commit(slot, cs, first_id)

    def _chunk_span(self, rem: int) -> int:
        """Padded length the chunked path would write for ``rem`` tokens."""
        c = self.chunked_prefill
        return -(-rem // c) * c

    def _oneshot_fits(self, done: int, rem: int) -> bool:
        return done + self._bucket_for(rem) <= self.cache_len

    def _chunked_fits(self, done: int, rem: int) -> bool:
        return (self.chunked_prefill is not None
                and done + self._chunk_span(rem) <= self.cache_len)

    def _should_chunk(self, done: int, rem: int) -> bool:
        """Chunk when the remainder is long (the point of interleaving) OR
        when only the chunk span fits the cache. Single source of truth
        for both admission paths (_admit and _begin_prefill)."""
        return self._chunked_fits(done, rem) and (
            rem > self.chunked_prefill or not self._oneshot_fits(done, rem)
        )

    def _accept_external_kv(self, host, prompt_ids):
        """Validate a claimed handoff :class:`~.kv_pool.HostEntry` and
        upload it as a device PrefixEntry (on the caller's thread), or
        ``None`` (counted) when it cannot seed a slot here — wrong cache
        layout/length means replica config drift, and a rejected entry
        degrades to local prefill rather than corrupting the slot."""
        from llm_in_practise_tpu.serve.disagg import usable_for_engine
        from llm_in_practise_tpu.serve.kv_pool import entry_to_device

        why = usable_for_engine(host, prompt_ids, self)
        if why is not None:
            self.kv_rejected += 1
            self._log.warning("rejecting handed-off KV entry: %s", why)
            return None
        if self.paged is not None:
            # keep the entry HOST-side: paged admission scatters it
            # page-by-page into the slot's reserved pages (no whole-
            # entry device buffer ever exists)
            return host
        return entry_to_device(host)

    def _lookup_prefix(self, req: Request, plen: int):
        if self.paged is not None:
            return self._paged_lookup(req, plen)
        ext = req.kv_entry
        if ext is not None:
            # handed-off KV (disaggregated serving): already validated
            # full-length at submit — admission is a pure direct insert,
            # no prefill dispatch, no mid-prefill rows on this replica
            req.kv_entry = None
            self.kv_admitted += 1
            return ext

        def usable(entry) -> bool:
            # rows from another engine (shared pool / restart) may be in
            # the other cache layout — their shapes are transposed
            # relative to this engine's writes and would scatter garbage
            if getattr(entry, "slot_axis", 0) != self._sax:
                return False
            # rows from another engine (shared pool) may be padded to a
            # bucket this engine's cache can't hold — the insert/suffix
            # scatters would clamp and corrupt the slot. Page-aligned
            # widths are judged POST-pow2-padding (entry_to_device pads
            # them so the jitted insert keeps a bounded compile set).
            from llm_in_practise_tpu.serve.kv_pool import effective_bucket

            if effective_bucket(entry) > self.cache_len:
                return False
            # every padded write the remaining prefill would do must land
            # inside cache_len, or the scatter clamps and corrupts the
            # prefix KV — either the one-shot bucket or the chunk span fits
            if entry.length == plen:
                return True
            rem = plen - entry.length
            return (self._oneshot_fits(entry.length, rem)
                    or self._chunked_fits(entry.length, rem))

        if self.prefix_cache is None:
            return None
        # multi-LoRA: adapter-namespaced key tokens — tenants (whose
        # adapters rewrite v_proj, hence the KV rows themselves) can
        # never hit each other's entries, including the base model's
        key_ids = self._ns_ids(req.adapter, req.prompt_ids)
        hit = self.prefix_cache.lookup(key_ids, usable)
        if hit is not None or self.kv_pool is None:
            return hit
        # L1 miss: cascade into the host/remote pool; a hit is promoted
        # back into L1 so the hot set migrates toward HBM. ``usable``
        # reads only entry metadata (length/bucket/slot_axis), so it
        # filters host entries before the device upload (and remote
        # entries before promotion).
        hit = self.kv_pool.lookup(key_ids, usable=usable)
        if hit is None:
            return None
        self.prefix_cache.put(key_ids[: hit.length], hit)
        return hit

    def _paged_lookup(self, req: Request, plen: int):
        """Paged admission's prefix resolution, best hit first:

        1. a claimed handoff entry (full-length host rows, validated at
           submit) — the disagg direct-insert path;
        2. the page index — partial-prefix hits at PAGE granularity,
           zero copies: the matched physical pages are refcounted into
           this slot's block table (the all-or-nothing direct-insert
           limitation this layout removes);
        3. the kv-pool tiers (host/remote row entries), fetched
           host-side and page-scattered at admission; their pages are
           then registered so the NEXT request hits tier 2.
        """
        from llm_in_practise_tpu.serve.paged_kv import PagedHit

        ext = req.kv_entry
        if ext is not None:
            req.kv_entry = None
            self.kv_admitted += 1
            return PagedHit(length=ext.length, entry=ext,
                            last_logits=ext.last_logits, external=True)
        pages = []
        if self.prefix_cache is not None:
            key_ids = self._ns_ids(req.adapter, req.prompt_ids)
            pages = self.prefix_cache.lookup(key_ids)
        # a fleet-pulled session entry (serve/sessions.py) outranks a
        # SHORTER local page hit; when it wins, the pool references the
        # index lookup took for us are handed straight back
        if self.session_store is not None and req.session_id is not None:
            hit = self._session_pull_hit(
                req, plen, len(pages) * self.paged.page_size)
            if hit is not None:
                if pages:
                    self.paged.pool.release(pages)
                return hit
        if pages:
            return PagedHit(length=len(pages) * self.paged.page_size,
                            pages=pages)
        if self.kv_pool is None or self.prefix_cache is None:
            return None

        def usable(entry) -> bool:
            # layout must match (slot axis 0), and every padded write
            # the remaining suffix prefill would do must land inside
            # cache_len — the paged one-shot suffix runs a
            # bucket_for(rem)-wide chunk at `done`, so the fit law is
            # the SAME as the contiguous filter (only the entry-bucket
            # cap is dropped: the page scatter writes positions, not
            # padded buckets)
            if getattr(entry, "slot_axis", 0) != 0:
                return False
            if entry.length >= plen:
                return entry.length == plen
            rem = plen - entry.length
            return (self._oneshot_fits(entry.length, rem)
                    or self._chunked_fits(entry.length, rem))

        from llm_in_practise_tpu.serve.kv_pool import TieredKV

        if isinstance(self.kv_pool, TieredKV):
            # host-side entries: the rows are page-scattered at
            # admission, so a whole-entry device upload would be waste
            host = self.kv_pool.lookup(key_ids, usable=usable,
                                       device=False)
        else:
            # bare pools (HostKVPool etc.) have no device kwarg and
            # already return host entries
            host = self.kv_pool.lookup(key_ids, usable=usable)
        if host is None:
            return None
        return PagedHit(
            length=host.length, entry=host,
            last_logits=host.last_logits if host.length == plen else None)

    def _session_pull_hit(self, req: Request, plen: int, page_len: int):
        """A usable :class:`~.paged_kv.PagedHit` from the session
        store's pending fleet pull for this request, or ``None``. The
        entry rides the tier-entry admission path (host rows scattered
        into reserved pages), so the SAME fit law applies; consume-once
        — an entry that loses to a longer page hit or fails the fit
        law is dropped (the local re-prefill degradation)."""
        from llm_in_practise_tpu.serve.paged_kv import PagedHit

        pulled = self.session_store.take_pending(req.session_id,
                                                 req.prompt_ids)
        if pulled is None:
            return None
        host, n = pulled
        if host.last_logits is None and n >= plen:
            # no stored logits for the final position: keep one token
            # to recompute (the page-index hit applies the same cap)
            n = plen - 1
        if getattr(host, "slot_axis", 0) != 0 or n <= page_len or n <= 0:
            return None
        if n < plen and not (self._oneshot_fits(n, plen - n)
                             or self._chunked_fits(n, plen - n)):
            return None
        return PagedHit(
            length=n, entry=host,
            last_logits=host.last_logits if n == plen else None)

    def _paged_begin_prefill(self, req: Request, slot: int, plen: int,
                             hit) -> None:
        """Paged admission for one request: reserve ACTUAL pages
        (prompt + first decode token — not a cache_len worst case), map
        or scatter whatever prefix the lookup found, then chunk or
        one-shot the suffix. A dry pool requeues the request and blocks
        further admission this step (decode-side growth may preempt;
        admission never does)."""
        P = self.paged.page_size
        self._note_cache_outcome(req, hit, plen)
        if hit is not None and hit.pages is not None:
            # a page hit whose suffix neither chunks nor fits a one-shot
            # bucket inside cache_len shrinks page by page first (the
            # paged analog of the contiguous usable() fit filter)
            done, rem = hit.length, plen - hit.length
            while (done > 0 and not self._should_chunk(done, rem)
                   and done + self._bucket_for(rem) > self.cache_len):
                done -= P
                rem += P
            if done < hit.length:
                self.paged.pool.release(hit.pages[done // P:])
                hit = (dataclasses.replace(hit, length=done,
                                           pages=hit.pages[:done // P])
                       if done > 0 else None)
            if hit is not None:
                self.paged.map_shared(slot, hit.pages)
        if not self.paged.extend(slot, plen + 1):
            # not admissible right now: hand the shared refs back, put
            # the request at the queue head, stop admitting this step
            # (decode-side growth may preempt; admission never does)
            self.paged.release_slot(slot)
            self.slot_req[slot] = None
            self.slot_adapter[slot] = None
            if hit is not None and hit.entry is not None and hit.external:
                # a handoff claim is consume-once: stash it back on the
                # request (and un-count the consumption) or the retry
                # pays a full local prefill for an entry we still hold
                req.kv_entry = hit.entry
                self.kv_admitted -= 1
            self._paged_admit_blocked = True
            with self.pending.mutex:
                self.pending.queue.appendleft(req)
            return
        done = hit.length if hit is not None else 0
        if hit is not None and hit.entry is not None:
            self._paged_insert_entry(slot, hit.entry, hit.length)
            # promote the tier hit into the page index: the next
            # request with this prefix shares pages instead of
            # re-fetching rows
            self._paged_register_pages(req.prompt_ids[:hit.length], slot,
                                       req.adapter)
            if hit.length == plen:
                self._activate(slot, req, plen, hit.last_logits)
                return
        rem = plen - done
        if self._should_chunk(done, rem):
            self.slot_req[slot] = req
            self.slot_ready[slot] = False
            self.slot_prefill[slot] = {"req": req, "plen": plen,
                                       "done": done, "last_logits": None}
            return
        last_logits = self._paged_suffix(slot, req.prompt_ids[done:],
                                         done, req=req)
        # store the finished prompt like every other completion path:
        # register its pages for sharing + tier write-through (the
        # contiguous twin does this in _finish_prefill)
        self._paged_store_prefix(req, plen, slot, last_logits)
        self._activate(slot, req, plen, last_logits)

    def _paged_suffix(self, slot: int, suffix, done: int, req=None):
        """One-shot prefill of ``suffix`` into ``slot`` at ``done``
        through the paged chunk program (the dedicated contiguous
        ``_prefill_suffix`` program has no paged twin — the chunk body
        is the same pinned-index math). Returns the last-position
        logits row. ``req``: books the dispatch into the request's
        critical-path breakdown when given."""
        C = self._bucket_for(len(suffix))
        # slot-plane adapters: the LoRA chunk program indexes the full
        # slot plane, so adapters keep the all-slots dispatch; the plain
        # path runs a SINGLE-ROW chunk — gathering only the owning
        # slot's pages instead of a W-wide view of every slot, which is
        # what makes a warm follow-up turn cheaper than its cold
        # re-prefill (the view gather, not the attention, dominates a
        # short suffix over a long prefix)
        lora = self._lora_args()
        one = lora is None
        with self.steptrace.scope("index_build"):
            W = self._paged_width(done + C)
            # the single-row path gathers ONE slot's pages, not a
            # W-wide view of every slot — pulse what it actually costs
            self._pulse_view(W, 1 if one else None)
            if one:
                tok = np.zeros((1, C), np.int32)
                tok[0, :len(suffix)] = suffix
                starts = np.array([done], np.int32)
                lens = np.array([len(suffix)], np.int32)
                self._paged_cow_fork(slot, done, len(suffix))
                fs = np.zeros((self.max_slots,), np.int32)
                fs[slot] = done
                fv = np.zeros((self.max_slots,), np.int32)
                fv[slot] = len(suffix)
                sidx = self.paged.scatter_idx(fs, fv, C)[slot:slot + 1]
                gidx = self.paged.row_gather_idx(slot, W)
            else:
                tok = np.zeros((self.max_slots, C), np.int32)
                tok[slot, :len(suffix)] = suffix
                starts = self._paged_index_vec(W, C)
                starts[slot] = done
                lens = np.zeros((self.max_slots,), np.int32)
                lens[slot] = len(suffix)
                valid = np.zeros((self.max_slots,), np.int32)
                valid[slot] = len(suffix)
                self._paged_cow_fork(slot, done, len(suffix))
                sidx = self.paged.scatter_idx(starts, valid, C)
                gidx = self.paged.gather_idx(W)
        kw = {} if lora is None else {"lora": lora}
        with self.steptrace.scope("dispatch_wait"):
            t0 = time.monotonic()
            fn = self._pg_chunk if lora is None else self._pg_chunk_lora
            last, self.paged.kv = fn(
                self.params, self.paged.kv, jnp.asarray(gidx),
                jnp.asarray(tok), jnp.asarray(starts), jnp.asarray(lens),
                jnp.asarray(sidx), **kw)
            out = last[0:1] if one else last[slot:slot + 1]
            # force + stamp dt exactly like _prefill_into_slot (the
            # logits feed the first-token sample on this same call path
            # anyway)
            jax.block_until_ready(out)
            dt = time.monotonic() - t0
            keys = CostModel.chunk_keys(len(suffix), done)
            self._note_device_phase(
                "prefill", tokens=len(suffix), attended_keys=keys,
                weight_passes=1, kv_read_tokens=keys, dt=dt)
        if req is not None:
            req.cp_add("prefill_dispatch", dt)
        return out

    _UNSET = object()

    def _begin_prefill(self, req: Request, slot: int, plen: int,
                       hit=_UNSET) -> None:
        """Route one admitted request: full prefix hit → direct insert;
        long remainder (chunked prefill on) → incremental, one chunk per
        engine step so running slots keep decoding; otherwise one-shot.
        ``hit`` may be passed by ``_admit`` (which already looked it up)."""
        # stamp the slot's adapter BEFORE any prefill dispatch — the
        # suffix/chunk programs below read the slot plane for their
        # gathered-BGMV indices
        self.slot_adapter[slot] = req.adapter
        if self.paged is not None:
            if hit is self._UNSET:
                hit = self._lookup_prefix(req, plen)
            return self._paged_begin_prefill(req, slot, plen, hit)
        if hit is self._UNSET:
            hit = self._lookup_prefix(req, plen)
        self._note_cache_outcome(req, hit, plen)
        if hit is not None and hit.length == plen:
            self.cache = self._insert_rows(
                self.cache, hit.rows, slot, jnp.asarray(plen, jnp.int32))
            self._activate(slot, req, plen, hit.last_logits)
            return
        done = hit.length if hit is not None else 0
        rem = plen - done
        # a hit that fits neither way was already filtered by
        # _lookup_prefix's usable()
        if self._should_chunk(done, rem):
            # Chunks write DIRECTLY into the slot's cache rows — no
            # per-prefill full-length mini cache (at 8B/8K that was
            # 1.2 GiB per in-flight prefill, the long-context OOM); the
            # only transient is one slot-slice inside the jitted chunk.
            # Garbage rows other dispatches write into the reserved slot
            # (single-step decode / speculative drift at its device
            # index) are always overwritten by the chunk that owns that
            # range — or, beyond the prompt, by real decode in order —
            # before any query can attend them (causal masking keys off
            # absolute position).
            if hit is not None:
                self.cache = self._insert_rows(
                    self.cache, hit.rows, slot,
                    jnp.asarray(done, jnp.int32))
            self.slot_req[slot] = req   # slot reserved, not yet decodable
            self.slot_ready[slot] = False
            self.slot_prefill[slot] = {"req": req, "plen": plen, "done": done,
                                       "last_logits": None}
            return
        last_logits = self._prefill_into_slot(req, slot, plen, hit)
        self._activate(slot, req, plen, last_logits)

    def _advance_prefills(self, budget: int = 1) -> bool:
        """Advance every in-flight chunked prefill by one chunk per
        budget unit, then finalize finished prompts. Multiple mid-
        prefill slots advance TOGETHER in one batched dispatch
        (:meth:`_chunk_batch_fn`) — concurrent long prompts no longer
        serialize per slot — while a single prefill keeps the 1-slot
        program (and, with budget > 1, gets several chunks per step, so
        ``prefill_budget`` still bounds a lone prompt's TTFT at
        ~chunks/budget steps)."""
        progressed = False
        while budget > 0 and self.slot_prefill:
            # paged layout: no per-chunk page reservation is needed —
            # admission reserved the WHOLE prompt's pages (+1 decode
            # token) before the slot entered slot_prefill, so every
            # chunk write is already covered; only decode GROWTH
            # allocates on demand (_paged_reserve_active)
            with self.steptrace.scope("index_build"):
                entries = []
                for slot in sorted(self.slot_prefill):
                    st = self.slot_prefill[slot]
                    chunk = st["req"].prompt_ids[
                        st["done"]: st["done"] + self.chunked_prefill]
                    entries.append((slot, st, chunk))
            C = self.chunked_prefill
            # whole-cache batching needs every row's C-wide write window
            # inside cache_len — a clamped scatter on a near-full ACTIVE
            # row would overwrite attended KV. Rare tail case: fall back
            # to sequential single-slot chunks. (The paged layout is
            # always batchable: discarded writes are routed to the
            # trash page by the host-built scatter indices, so there is
            # no clamp hazard to dodge.)
            batchable = self.paged is not None or (
                len(entries) > 1 and all(
                    int(self.slot_len[s]) + C <= self.cache_len
                    for s in range(self.max_slots)
                    if s not in self.slot_prefill
                    and self.slot_req[s] is not None  # free rows are dead
                ))
            # device-plane accounting reads each chunk's pre-advance
            # context; compute before the branches mutate st["done"]
            pf_tokens = sum(len(c) for _, _, c in entries)
            pf_keys = sum(CostModel.chunk_keys(len(c), st["done"])
                          for _, st, c in entries)
            lora = self._lora_args()   # slot-plane (batched chunk rows)
            kw = {} if lora is None else {"lora": lora}
            with self.steptrace.scope("dispatch_wait"):
                t0 = time.monotonic()
                if self.paged is not None:
                    self._paged_chunk_dispatch(entries, lora=lora)
                elif batchable:
                    tok, starts, lens = self._chunk_batch_rows(entries)
                    fn = (self._chunk_batch if lora is None
                          else self._chunk_batch_lora)
                    last, self.cache = fn(
                        self.params, self.cache, jnp.asarray(tok),
                        jnp.asarray(starts), jnp.asarray(lens), **kw)
                    for slot, st, chunk in entries:
                        st["last_logits"] = last[slot:slot + 1]
                        st["done"] += len(chunk)
                else:
                    for slot, st, chunk in entries:
                        # the 1-row program wants a 1-row index array
                        sl = self._lora_args_for([st["req"].adapter])
                        skw = {} if sl is None else {"lora": sl}
                        fn = (self._chunk_slot if sl is None
                              else self._chunk_slot_lora)
                        padded = np.zeros((1, C), np.int32)
                        padded[0, :len(chunk)] = chunk
                        st["last_logits"], self.cache = fn(
                            self.params, self.cache, jnp.asarray(padded),
                            jnp.asarray(slot, jnp.int32),
                            jnp.asarray(st["done"], jnp.int32),
                            jnp.asarray(len(chunk), jnp.int32),
                            **skw,
                        )
                        st["done"] += len(chunk)
                # force the chunks' last-logits before stamping dt: on
                # an async backend issue time alone would inflate the
                # prefill MFU/BW gauges ~device-time/dispatch-time-fold
                # (the decode and fused paths force every dispatch the
                # same way). The logits are consumed at activation
                # regardless; KV writes land in the same program, so
                # this waits only for work the next chunk depends on
                # anyway.
                jax.block_until_ready([st["last_logits"]
                                       for _, st, _ in entries])
                dt = time.monotonic() - t0
                self._trace_chunks(entries, dt, batched=batchable)
                self._note_device_phase(
                    "prefill", tokens=pf_tokens, attended_keys=pf_keys,
                    weight_passes=1 if batchable else len(entries),
                    kv_read_tokens=pf_keys, dt=dt)
            budget -= 1
            progressed = True
            with self.steptrace.scope("sample_commit"):
                self._finalize_prefills()
        return progressed

    def _trace_chunks(self, entries, dt: float, *, batched: bool,
                      fused: bool = False) -> None:
        """One ``engine.prefill_chunk`` span per traced mid-prefill row
        (the duration is dispatch-issue time — on an async backend the
        device compute may still be in flight, see docs/observability.md)."""
        for slot, st, chunk in entries:
            self._trace_phase(st["req"], "engine.prefill_chunk", dt,
                              slot=slot, done=st["done"],
                              chunk_tokens=len(chunk), batched=batched,
                              fused=fused)
            # every mid-prefill request waited the whole chunk dispatch
            st["req"].cp_add("prefill_dispatch", dt)

    def _chunk_batch_rows(self, entries):
        """Host arrays (tok, starts, lens) for a whole-cache batched
        chunk dispatch — shared by the sequential batched path and the
        fused mixed step. Non-prefill rows get zero tokens at their own
        index: garbage KV beyond it, overwritten in order before any
        query attends it; min() keeps the dead write window of FREE
        rows inside the cache (occupied rows already fit by the
        caller's precheck — ``batchable`` / ``_mixed_feasible`` — so
        their min() is a no-op)."""
        C = self.chunked_prefill
        tok = np.zeros((self.max_slots, C), np.int32)
        starts = np.zeros((self.max_slots,), np.int32)
        lens = np.zeros((self.max_slots,), np.int32)
        for s in range(self.max_slots):
            if s not in self.slot_prefill:
                starts[s] = min(int(self.slot_len[s]),
                                self.cache_len - C)
        for slot, st, chunk in entries:
            tok[slot, :len(chunk)] = chunk
            starts[slot] = st["done"]
            lens[slot] = len(chunk)
        return tok, starts, lens

    def _paged_chunk_dispatch(self, entries, lora=None) -> None:
        """Advance every mid-prefill row one chunk against the PAGE
        POOL in a single dispatch: gather a bucketed contiguous view,
        run the shared ``batched_chunk`` body, scatter each prefill
        row's real chunk window back to its pages (everything else —
        idle rows' dead windows, padding — lands in the trash page)."""
        C = self.chunked_prefill
        tok, starts, lens = self._chunk_batch_rows(entries)
        W = self._paged_width(
            max(st["done"] for _, st, _ in entries) + C)
        self._pulse_view(W)
        # non-prefill rows' dead C-wide in-view writes must stay inside
        # the view; their view copy is discarded (windows are trash),
        # so the clamp is harmless — prefill rows stay exact
        starts = np.minimum(starts, W - C)
        valid = np.zeros((self.max_slots,), np.int32)
        for slot, st, chunk in entries:
            starts[slot] = st["done"]
            valid[slot] = len(chunk)
            self._paged_cow_fork(slot, st["done"], len(chunk))
        sidx = self.paged.scatter_idx(starts, valid, C)
        gidx = self.paged.gather_idx(W)
        kw = {} if lora is None else {"lora": lora}
        fn = self._pg_chunk if lora is None else self._pg_chunk_lora
        last, self.paged.kv = fn(
            self.params, self.paged.kv, jnp.asarray(gidx),
            jnp.asarray(tok), jnp.asarray(starts), jnp.asarray(lens),
            jnp.asarray(sidx), **kw)
        for slot, st, chunk in entries:
            st["last_logits"] = last[slot:slot + 1]
            st["done"] += len(chunk)

    def _finalize_prefills(self) -> None:
        """Activate every chunked prefill whose prompt is fully fed —
        shared tail of the sequential and fused mixed-step paths."""
        for slot in list(self.slot_prefill):
            st = self.slot_prefill[slot]
            if st["done"] < st["plen"]:
                continue
            req, plen = st["req"], st["plen"]
            del self.slot_prefill[slot]
            # rows are already in the slot; store the prefix entry
            # from them (the index is plen — set by the final chunk)
            rows = None
            if self.paged is not None:
                self._paged_store_prefix(req, plen, slot,
                                         st["last_logits"])
            elif self.prefix_cache is not None:
                rows = self._slot_rows(
                    self.cache, jnp.asarray(slot, jnp.int32),
                    bucket=self._bucket_for(plen))
                self._store_prefix(req, plen, rows,
                                   st["last_logits"],
                                   rows_ready=True)
            # the gathered rows ride through to the handoff path so a
            # chunked handoff doesn't pay the gather dispatch twice
            self._activate(slot, req, plen, st["last_logits"], rows=rows)

    def _paged_store_prefix(self, req: Request, plen: int, slot: int,
                            last_logits) -> None:
        """Paged twin of ``_store_prefix``: the prompt's KV is already
        in ``slot``'s pages, so "storing" the prefix is registering the
        full pages in the sharing index (zero copies) plus the optional
        kv-pool write-through of a page-aligned row entry. Write-through
        is duck-typed: a lookup-only pool (bare HostKVPool) simply gets
        no copies."""
        if self.prefix_cache is not None:
            self._paged_register_pages(req.prompt_ids[:plen], slot,
                                       req.adapter)
        if (self.kv_pool is not None
                and getattr(self.kv_pool, "offload_on_put", False)):
            self.kv_pool.offload(
                self._ns_ids(req.adapter, req.prompt_ids[:plen]),
                self._paged_gather_entry(slot, plen, last_logits))

    def _store_prefix(self, req: Request, plen: int, pre_cache,
                      last_logits, *, rows_ready: bool = False) -> None:
        """Store a finished prompt's prefix entry (L1 + optional pool
        write-through). ``pre_cache`` must be a 1-row cache/rows list;
        ``rows_ready=True`` means it is ALREADY bucket-width index-free
        rows (the chunked path's ``_slot_rows`` output) — re-slicing
        would dispatch identity copies per layer."""
        from llm_in_practise_tpu.serve import prefix_cache as pc

        if self.prefix_cache is None:
            return
        bucket = self._bucket_for(plen)
        entry = pc.PrefixEntry(
            length=plen, bucket=bucket,
            rows=(pre_cache if rows_ready
                  else pc.slice_cache_rows(pre_cache, bucket,
                                           axis=self._wax)),
            last_logits=last_logits,
            slot_axis=self._sax,
        )
        key_ids = self._ns_ids(req.adapter, req.prompt_ids)
        self.prefix_cache.put(key_ids, entry)
        if self.kv_pool is not None and self.kv_pool.offload_on_put:
            # LMCache streaming write-through: the pool copy means a
            # sibling / restarted engine starts with this prefix warm.
            self.kv_pool.offload(key_ids[:plen], entry)

    def _finish_prefill(self, req: Request, slot: int, plen: int,
                        pre_cache, last_logits) -> None:
        """Store the finished prompt's prefix entry and move its KV rows
        into the slot — shared tail of the suffix/chunked prefill paths."""
        self._store_prefix(req, plen, pre_cache, last_logits)
        self.cache = self._insert(
            self.cache, pre_cache, slot, jnp.asarray(plen, jnp.int32)
        )

    def _prefill_into_slot(self, req: Request, slot: int, plen: int, hit):
        """One-shot prefill (reusing any cached prefix rows) into ``slot``;
        returns the last-position logits."""
        with self.steptrace.scope("dispatch_wait"):
            return self._prefill_into_slot_timed(req, slot, plen, hit)

    def _prefill_into_slot_timed(self, req, slot, plen, hit):
        lora = self._lora_args_for([req.adapter])
        kw = {} if lora is None else {"lora": lora}
        t0 = time.monotonic()
        if hit is not None:
            suffix = req.prompt_ids[hit.length:]
            sbucket = self._bucket_for(len(suffix))
            padded = np.zeros((1, sbucket), np.int32)
            padded[0, :len(suffix)] = suffix
            fn = (self._prefill_suffix if lora is None
                  else self._prefill_suffix_lora)
            last_logits, pre_cache = fn(
                self.params, hit.rows, jnp.asarray(hit.length, jnp.int32),
                jnp.asarray(padded), jnp.asarray(len(suffix), jnp.int32),
                **kw)
            new, start = len(suffix), hit.length
        else:
            bucket = self._bucket_for(plen)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = req.prompt_ids
            fn = self._prefill if lora is None else self._prefill_lora
            last_logits, pre_cache = fn(
                self.params, jnp.asarray(padded),
                jnp.asarray([plen], jnp.int32), **kw
            )
            new, start = plen, 0
        # force + stamp dt BEFORE the insert/prefix-store work so this
        # sample covers exactly the prefill forward, same boundary as
        # the chunked/fused paths (async-backend honesty — see
        # _advance_prefills); the logits feed the first-token sample on
        # this same call path anyway
        jax.block_until_ready(last_logits)
        dt = time.monotonic() - t0
        keys = CostModel.chunk_keys(new, start)
        self._note_device_phase(
            "prefill", tokens=new, attended_keys=keys,
            weight_passes=1, kv_read_tokens=keys, dt=dt)
        req.cp_add("prefill_dispatch", dt)
        self._finish_prefill(req, slot, plen, pre_cache, last_logits)
        return last_logits

    def _finish_slot(self, slot: int, reason: str) -> None:
        """Finish ``slot``'s request with ``reason`` and free the slot —
        the single exit for decode completions (eos/length/cache) and
        the paged pool's last-occupant exhaustion. In the paged layout
        the slot's full pages are registered for sharing on the way out
        (a follow-up turn reuses the whole conversation's KV) and the
        block table releases its references — the churn test pins that
        this leaks nothing."""
        req = self.slot_req[slot]
        req.finish_time = time.monotonic()
        req.finish_reason = reason
        if req.first_token_time is not None:
            # the decode phase: first token → finish (TPOT × tokens).
            # Recorded BEFORE _FINISH is released: a consumer that
            # saw the stream end must find the span in the ring.
            self._trace_phase(
                req, "engine.decode",
                req.finish_time - req.first_token_time,
                slot=slot, tokens=req.n_generated,
                finish_reason=req.finish_reason)
        if self.paged is not None:
            hist = self.slot_hist[slot]
            if hist:
                self._paged_register_pages(hist[:-1], slot, req.adapter)
                if (self.session_store is not None
                        and req.session_id is not None):
                    # sessions pin + publish BEFORE release_slot: the
                    # block table still maps the pages, so the pin's
                    # share() can never race a refcount-zero free
                    self._session_note_finish(slot, req, hist[:-1])
            self.paged.release_slot(slot)
        elif (self.session_store is not None
                and req.session_id is not None):
            # contiguous layout: no pages to pin — the store tracks the
            # conversation's token history and turn accounting only
            # (warm turns ride the row-based PrefixCache's LRU)
            hist = self.slot_hist[slot]
            self.session_store.note_finish(
                req.session_id, hist[:-1] if hist else req.prompt_ids,
                [], adapter=req.adapter,
                cache_outcome=req.cache_outcome)
        # breakdown finalized BEFORE _FINISH is released: a consumer
        # that saw the stream end must find the request in the
        # /debug/requests ring (same ordering rule as the decode span)
        self._record_finished(req)
        req.tokens.put(_FINISH)
        self.stats.observe_finished(req)
        self.slot_req[slot] = None
        self.slot_ready[slot] = False
        self.slot_budget[slot] = 0
        self.slot_constraint[slot] = None
        self.slot_adapter[slot] = None

    def _session_note_finish(self, slot: int, req: Request,
                             token_ids) -> None:
        """Session pin + fleet publish for a finishing paged slot
        (serve/sessions.py, ISSUE 17). ``token_ids`` is the KV-valid
        conversation history (``hist[:-1]`` — the final emitted token's
        KV was never written). Pins the full-page chain prefix under
        the session id, then — in fleet mode — gathers a page-aligned
        copy on THIS thread (the pages are still slot-mapped) and hands
        it to the store's publisher thread for the device→host copy +
        pool put, mirroring the disagg publisher split."""
        P = self.paged.page_size
        nfull = len(token_ids) // P
        pages = self.paged.slot_pages(slot)[:nfull] if nfull > 0 else []
        self.session_store.note_finish(
            req.session_id, token_ids, pages, adapter=req.adapter,
            cache_outcome=req.cache_outcome)
        if self.handoff is not None and nfull > 0:
            with self.steptrace.scope("publish"):
                # no last_logits: the entry is a page-aligned PARTIAL
                # prefix by design — the claiming replica recomputes at
                # least the suffix, which yields fresh logits
                entry = self._paged_gather_entry(slot, nfull * P, None)
            self.session_store.publish(
                req.session_id, token_ids[:nfull * P], entry)

    def _emit(self, slot: int, token_id: int):
        req = self.slot_req[slot]
        budget_left = self.slot_budget[slot] > 0
        hit_eos = self.eos_id is not None and token_id == self.eos_id
        # cache_len guard: the emitted token's write (next decode) must fit.
        room = self.slot_len[slot] + 1 < self.cache_len
        if not hit_eos:
            req.tokens.put(token_id)
            req.n_generated += 1
        if hit_eos or not budget_left or not room:
            self._finish_slot(slot, "stop" if hit_eos else
                              ("length" if not budget_left else "cache"))

    def _draft(self, hist: list[int], k: int) -> list[int] | None:
        """Prompt-lookup draft: find the most recent earlier occurrence of
        the trailing n-gram and propose the k tokens that followed it.
        Vectorized — this runs on the host between every decode step."""
        window = np.asarray(hist[-2048:], np.int32)   # bound the scan
        for n in range(self.speculative_ngram, 0, -1):
            if window.size <= n:
                continue
            pat = window[-n:]
            # candidate start positions, excluding the trailing n-gram
            # itself; match = all n positions equal at once
            limit = window.size - n
            hitmask = window[:limit] == pat[0]
            for j in range(1, n):
                hitmask &= window[j:limit + j] == pat[j]
            hits = np.nonzero(hitmask)[0]
            if hits.size:
                i = int(hits[-1])             # most recent occurrence
                cont = window[i + n: i + n + k].tolist()
                if cont:
                    return cont              # un-padded; caller zero-fills
        return None

    def _spec_applicable(self, active: list[int]) -> bool:
        """Whether the speculative verify step CAN run this step —
        shared by :meth:`_try_speculative` and the mixed-step
        composition decision (the two must never diverge: composition
        skips the fused dispatch on the promise that a verify runs
        instead)."""
        k = self.speculative_k
        if k is None:
            return False
        if not all(self._greedy[s] for s in active):
            return False                      # lossless only under greedy
        # every write of the wide step must land inside the cache — the
        # per-slot scatter clamps at the end and would corrupt tail
        # rows. That bound applies to mid-prefill rows too: the verify
        # writes k+1 dead rows at each one's device index (= done), and
        # a clamp there would shift backward over already-attended
        # prompt KV (in-bounds dead writes are fine — the owning chunk
        # overwrites them before any query attends).
        return (all(self.slot_len[s] + k + 1 <= self.cache_len
                    for s in active)
                and all(st["done"] + k + 1 <= self.cache_len
                        for st in self.slot_prefill.values()))

    def _spec_headroom(self, active: list[int]) -> int:
        """Cache rows available for the spec extension ABOVE the k+1
        verify rows — min over decoding and mid-prefill rows (their
        dead write windows widen with the extension too)."""
        k = self.speculative_k
        lens = [int(self.slot_len[s]) for s in active]
        lens += [st["done"] for st in self.slot_prefill.values()]
        return self.cache_len - (k + 1) - (max(lens) if lens else 0)

    def _try_speculative(self, active: list[int]) -> bool:
        """One FUSED speculative round (the ROADMAP item 4 tentpole):
        draft k tokens per slot (ngram or draft model), then verify +
        accept + decode the planned block's remaining steps inside ONE
        jitted dispatch (serve/mixed_step.spec_verify_block) — the old
        path paid a second ``_rewind`` dispatch on the contiguous
        layout and capped every round at ``decode_steps=1`` economics.
        Returns False when the spec path doesn't apply this step
        (caller falls back to plain decode)."""
        k = self.speculative_k
        with self.steptrace.scope("plan"):
            applicable = self._spec_applicable(active)
            if applicable:
                # the extension m rides the SAME token-budget plan as a
                # plain block (soonest-finish cap under queueing, chunk
                # caps while prefilling): one fused dispatch spans
                # verify + m greedy steps, so acceptance-count is part
                # of the dispatch plan and the compile set stays
                # pow2-bounded
                m = plan_spec_extension(
                    block=self._plan_block(active), k=k,
                    headroom=self._spec_headroom(active))
        if not applicable:
            return False
        # draft BEFORE touching the page pool: drafting needs no pool
        # pages (ngram is host-side; the draft model's cache is its own
        # contiguous buffer), so a draft-miss step returns to the plain
        # path without having preempted or cache-finished anybody for a
        # k+1+m reservation that would never be used
        with self.steptrace.scope("draft_propose"):
            if self.draft_model is not None:
                drafts = self._draft_model_propose(active, k)
            else:
                drafts = {}
                for s in active:
                    d = self._draft(self.slot_hist[s], k)
                    if d is not None:
                        drafts[s] = d         # un-padded, 1..k tokens
        if not drafts:
            return False                      # nothing to verify; plain step
        if self.paged is not None:
            # the fused round writes k+1+m rows per slot: reserve the
            # pages up front (preempting youngest slots if dry) — the
            # speculative watermark of any preempted slot is reset in
            # _paged_preempt, so a recycled draft cache re-syncs
            with self.steptrace.scope("admit"):
                active = self._paged_reserve_active(active, k + 1 + m)
            if not active:
                return True
            drafts = {s: d for s, d in drafts.items() if s in active}
        with self.steptrace.scope("index_build"):
            tokens = np.zeros((self.max_slots, k + 1), np.int32)
            tokens[:, 0] = self.slot_last_token
            for s, d in drafts.items():
                tokens[s, 1: 1 + len(d)] = d
            mask = np.zeros((self.max_slots,), np.int32)
            mask[active] = 1
        # grammar composition (ISSUE 12): stage k+1 per-position masks
        # by tentatively advancing each constrained slot's automaton
        # over its drafts — the on-device acceptance cumprod then
        # rejects grammar-forbidden drafts like argmax mismatches.
        # (_plan_block capped the block at 1 for constrained actives,
        # so m == 0 here whenever gmasks is not None.)
        gmasks = self._grammar_spec_masks(active, tokens, k, drafts)
        # multi-LoRA: the verify IS the target forward, so the adapter
        # delta rides the spec twins; the drafts above stayed base-model
        lora = self._lora_args()
        kw = {} if lora is None else {"lora": lora}
        with self.steptrace.scope("dispatch_wait"):
            t0 = time.monotonic()
            if self.paged is not None:
                W = self._paged_width(
                    max(int(self.slot_len[s]) for s in active)
                    + k + 1 + m)
                self._pulse_view(W)
                idxv = self._paged_index_vec(W, k + 1 + m)
                valid = np.zeros((self.max_slots,), np.int32)
                for s in active:
                    valid[s] = k + 1 + m
                    self._paged_cow_fork(s, int(self.slot_len[s]),
                                         k + 1 + m)
                if gmasks is not None:
                    fn = (self._pg_spec_masked if lora is None
                          else self._pg_spec_masked_lora)
                    out, n_acc, extra, self.paged.kv = fn(
                        self.params, self.paged.kv,
                        jnp.asarray(self.paged.gather_idx(W)),
                        jnp.asarray(idxv),
                        jnp.asarray(self.paged.scatter_idx(
                            idxv, valid, k + 1 + m)),
                        jnp.asarray(tokens), jnp.asarray(mask),
                        jnp.asarray(gmasks), m=m, **kw)
                else:
                    fn = (self._pg_spec if lora is None
                          else self._pg_spec_lora)
                    out, n_acc, extra, self.paged.kv = fn(
                        self.params, self.paged.kv,
                        jnp.asarray(self.paged.gather_idx(W)),
                        jnp.asarray(idxv),
                        jnp.asarray(self.paged.scatter_idx(idxv, valid,
                                                           k + 1 + m)),
                        jnp.asarray(tokens), jnp.asarray(mask), m=m,
                        **kw)
            elif gmasks is not None:
                fn = (self._decode_spec_masked if lora is None
                      else self._decode_spec_masked_lora)
                base = self._paged_index_vec(self.cache_len, k + 1 + m)
                out, n_acc, extra, self.cache = fn(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(base), jnp.asarray(mask),
                    jnp.asarray(gmasks), m=m, **kw)
            else:
                # per-row pinned index: the slot-state → index
                # convention lives in ONE place (_paged_index_vec reads
                # only host slot state — nothing paged about it); here
                # the "view" is the whole contiguous cache, so
                # W = cache_len. Free rows' dead k+1+m write window is
                # clamped inside the cache; live rows already fit
                # (_spec_applicable + the headroom cap on m), so their
                # clamp is a no-op.
                base = self._paged_index_vec(self.cache_len, k + 1 + m)
                fn = (self._decode_spec if lora is None
                      else self._decode_spec_lora)
                out, n_acc, extra, self.cache = fn(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(base), jnp.asarray(mask), m=m, **kw)
            out_host = np.asarray(out)
            acc_host = np.asarray(n_acc)
            extra_host = np.asarray(extra)
            # the verify is ONE wide forward over k+1 positions per slot
            # plus m single-token extension passes (that width
            # amortizing the weight read is the whole spec bet — the
            # decode MFU gauge shows it paying off or not). Useful
            # positions only: an undrafted/short-draft slot's zero
            # padding is wasted work and must read as lost MFU, same
            # convention as the spec_proposed/spec_accepted counters
            # below.
            useful = {s: len(drafts.get(s, ())) + 1 + m for s in active}
            keys = sum(CostModel.block_keys(useful[s],
                                            int(self.slot_len[s]))
                       for s in active)
            dt = time.monotonic() - t0
            self._note_device_phase(
                "decode", tokens=sum(useful.values()),
                attended_keys=keys, weight_passes=1 + m,
                kv_read_tokens=keys, dt=dt)
        self.spec_rounds += 1
        with self.steptrace.scope("sample_commit"):
            for s in active:
                self.slot_req[s].cp_add("decode_dispatch", dt)
            for s in active:
                n_acc_s = int(acc_host[s])
                # metrics over real drafted positions only — zero
                # padding (and undrafted slots' zero fill) must not
                # inflate either counter
                n_drafted = len(drafts.get(s, ()))
                self.spec_proposed += n_drafted
                self.spec_accepted += min(n_acc_s, n_drafted)
                burst = [int(out_host[s, j]) for j in range(n_acc_s + 1)]
                burst += [int(extra_host[s, j]) for j in range(m)]
                for tok in burst:
                    if self.slot_req[s] is None:
                        break                 # finished mid-burst (eos/len)
                    self._commit_token(s, tok)
                    self.spec_round_tokens += 1
        return True

    def _commit_token(self, slot: int, tok: int) -> None:
        """Book one generated token into a slot: budget/length/last-token
        tracking, spec history, grammar advance, and emission (which may
        finish the slot). The single, speculative, and multi-step paths
        all commit here."""
        self.slot_budget[slot] -= 1
        self.slot_len[slot] += 1
        self.slot_last_token[slot] = tok
        if self.slot_hist[slot] is not None:
            self.slot_hist[slot].append(tok)
        # capture before _emit: an eos/budget finish clears the slot's
        # constraint reference, but the cursor must still advance (it
        # lives on the request and the stream's last token is part of
        # the grammar position a preempt-resume would continue from)
        cs = self.slot_constraint[slot]
        self._emit(slot, tok)
        self._constraint_commit(slot, cs, tok)

    def _update_active_stats(self) -> None:
        with self.stats.lock:
            self.stats.active_slots = sum(
                r is not None for r in self.slot_req)

    def _ready_slots(self) -> list[int]:
        return [s for s, r in enumerate(self.slot_req)
                if r is not None and self.slot_ready[s]]

    # --- grammar (constrained decoding, serve/constrain.py) ------------------

    def _ensure_constraint(self, req: Request):
        """This request's live grammar cursor, minted from the compiled
        automaton on first touch (activation). The lazy automaton-state
        compile the mint may trigger books under ``grammar_compile``
        (the PR 11 coverage gate must see it, not an ``other`` blob)."""
        if req.constraint_state is None and req.params.constraint is not None:
            with self.steptrace.scope("grammar_compile"):
                req.constraint_state = req.params.constraint.cursor()
        return req.constraint_state

    def _constrained_active(self, active: list[int]) -> bool:
        return any(self.slot_constraint[s] is not None for s in active)

    def _grammar_mask_rows(self, cursors) -> np.ndarray:
        """(len(cursors), vocab) float32 additive mask rows — None
        entries get zero rows. The ONE staging-accounting site: wall
        time books into llm_grammar_mask_seconds_total under the
        ``grammar_mask`` activity, lazy vocab-wide state compiles (the
        dominant grammar cost) under ``grammar_compile``. At least one
        cursor must be non-None."""
        t0 = time.monotonic()
        with self.steptrace.scope("grammar_mask"):
            out = np.zeros(
                (len(cursors),
                 next(c.vocab_size for c in cursors if c is not None)),
                np.float32)
            for j, cs in enumerate(cursors):
                if cs is None:
                    continue
                if cs.needs_compile():
                    with self.steptrace.scope("grammar_compile"):
                        cs.auto.ensure(cs.cur)
                out[j] = cs.mask_row()
        self.grammar_mask_seconds_total += time.monotonic() - t0
        return out

    def _grammar_masks(self, active: list[int]):
        """(max_slots, vocab) float32 additive mask for this step's
        decode — each constrained slot's automaton-state row, zeros for
        unconstrained slots — or None when no active slot is
        constrained (the unmasked programs then run untouched). The
        slot_constraint vector IS the constrained-active set: cursors
        install at activation and clear at finish/preempt."""
        if not self._constrained_active(active):
            return None
        return self._grammar_mask_rows(self.slot_constraint)

    def _grammar_spec_masks(self, active: list[int], tokens, k: int,
                            drafts: dict):
        """(max_slots, k+1, vocab) staged masks for a fused spec round:
        the host advances each constrained slot's grammar TENTATIVELY
        over its drafted tokens — position ``j`` gets the state after
        the first ``j`` drafts, so the masked verify's acceptance
        cumprod truncates at a grammar-forbidden draft exactly like an
        argmax mismatch (serve/mixed_step.spec_verify_block). Rejected
        drafted tokens count into llm_spec_grammar_rejects_total.
        Returns None when no active slot is constrained."""
        rows = [(s, self.slot_constraint[s]) for s in active
                if self.slot_constraint[s] is not None]
        if not rows:
            return None
        t0 = time.monotonic()
        with self.steptrace.scope("grammar_mask"):
            gmasks = np.zeros(
                (self.max_slots, k + 1, rows[0][1].vocab_size),
                np.float32)
            for s, cs in rows:
                auto, cur = cs.auto, cs.cur
                n_drafted = len(drafts.get(s, ()))
                for j in range(k + 1):
                    if not auto.compiled(cur):
                        with self.steptrace.scope("grammar_compile"):
                            auto.ensure(cur)
                    gmasks[s, j] = auto.mask(cur)
                    if j >= k:
                        break
                    # stage through position j+1's input token — a real
                    # draft or the zero padding (padding acts as an
                    # implicit draft on the unmasked path too); a
                    # forbidden token ends the staging: positions past
                    # it can never be accepted (cumprod is already 0),
                    # so their zero rows are inert
                    nxt = auto.step(cur, int(tokens[s, j + 1]))
                    if nxt is None:
                        if j < n_drafted:
                            self.spec_grammar_rejects += 1
                        break
                    cur = nxt
        self.grammar_mask_seconds_total += time.monotonic() - t0
        return gmasks

    def _constraint_commit(self, slot: int, cs, tok: int) -> None:
        """Advance ``slot``'s grammar cursor over an emitted token; a
        completed value finishes the stream (``finish_reason="stop"``)
        — deterministic, and independent of whether the vocab has an
        EOS id at all. An explicit EOS emission is the grammar's own
        allowed stop (accepting states admit it) and is not consumed."""
        if cs is None:
            return
        if self.eos_id is not None and tok == self.eos_id:
            return
        if cs.advance(tok) and self.slot_req[slot] is not None:
            self._finish_slot(slot, "stop")

    def _plan_block(self, active: list[int]) -> int:
        """Token-budget plan for this step's decode block length: the
        soonest-completion cap under queueing plus (while prompts are
        mid-prefill) the chunk-window caps — policy in
        :func:`llm_in_practise_tpu.serve.mixed_step.plan_decode_block`.

        Constrained decoding caps the block at 1 whenever a READY slot
        carries a grammar: the per-slot mask encodes exactly one
        automaton state, and tokens 2..n of a block would sample
        unmasked (the fused spec round is the multi-token path for
        constrained slots — drafts are host-known, so k+1 states can be
        staged). This also drives ``plan_spec_extension`` to m=0."""
        if self._constrained_active(active):
            return 1
        soonest = None
        if active and self.pending.qsize() > 0:
            # Requests are waiting on a slot: cap the block at the
            # soonest *deterministic* completion among active slots
            # (token budget or cache room, whichever bites first), so
            # the freed slot refills at the very next step instead of
            # idling out the tail of a fixed-length block. This is the
            # TTFT half of multi-step scheduling: full blocks when
            # nobody waits, shortest-useful blocks under queueing.
            soonest = int(min(
                min(self.slot_budget[s],
                    self.cache_len - 1 - self.slot_len[s])
                for s in active
            ))
        chunk = headroom = None
        if self.slot_prefill:
            chunk = self.chunked_prefill
            headroom = min(
                self.cache_len - chunk - st["done"]
                for st in self.slot_prefill.values())
        return plan_decode_block(
            decode_steps=self.decode_steps,
            queue_depth=self.pending.qsize(),
            soonest_finish=soonest,
            chunk=chunk,
            prefill_headroom=headroom,
        )

    def _mixed_feasible(self, active: list[int], n: int) -> tuple[bool, str]:
        """Can this step run as ONE fused dispatch? The bounds are the
        scatter-clamp invariants documented in serve/mixed_step.py; a
        miss falls back to the sequential two-dispatch path (rare tail:
        rows butting against the cache end)."""
        C = self.chunked_prefill
        if n > C:
            # the scan's garbage rows above each prefill watermark must
            # be covered by the next chunk's padded write; the planner
            # already caps n <= chunk, this keeps the invariant local
            return False, (
                f"block length exceeds the chunk window: n {n} > "
                f"chunk {C}")
        for slot, st in self.slot_prefill.items():
            if st["done"] + C + n > self.cache_len:
                return False, (
                    "prefill row near the cache end: "
                    f"slot {slot} done {st['done']} + chunk {C} + "
                    f"block {n} > cache_len {self.cache_len}")
        for s in range(self.max_slots):
            # every occupied non-prefill row receives the dead chunk
            # write at its own index (free rows clamp; occupied rows
            # must fit exactly) — same bound as the batched chunk path
            if s in self.slot_prefill or self.slot_req[s] is None:
                continue
            if int(self.slot_len[s]) + C > self.cache_len:
                return False, (
                    "decode row lacks the chunk dead-write window: "
                    f"slot {s} len {int(self.slot_len[s])} + chunk {C} "
                    f"> cache_len {self.cache_len}")
        return True, ""

    def _mixed_dispatch(self, active: list[int], n: int) -> bool:
        """Issue the fused mixed-batch program: every mid-prefill row
        advances one chunk AND every ready row decodes an ``n``-block,
        in ONE device dispatch (serve/mixed_step.py). Host bookkeeping
        mirrors the sequential paths exactly: chunk results feed
        ``slot_prefill``/finalization, block tokens commit per slot.
        Returns False (nothing dispatched) only when paged page
        reservation drained either half — the caller falls through to
        the sequential paths for this step."""
        C = self.chunked_prefill
        if self.paged is not None:
            # reserve the decode half's writes: n rows per ready slot
            # (may preempt youngest). The prefill half needs nothing —
            # admission reserved every prompt page up front, and the
            # scan's garbage rows above each prefill watermark scatter
            # to the trash page.
            with self.steptrace.scope("admit"):
                active = self._paged_reserve_active(active, n)
            if not active or not self.slot_prefill:
                return False
        with self.steptrace.scope("index_build"):
            entries = []
            for slot in sorted(self.slot_prefill):
                st = self.slot_prefill[slot]
                chunk = st["req"].prompt_ids[st["done"]: st["done"] + C]
                entries.append((slot, st, chunk))
            tok, starts, lens = self._chunk_batch_rows(entries)
            advance = np.zeros((self.max_slots,), np.int32)
            advance[active] = n
        # constrained decoding: the decode half of the fused step masks
        # each grammar slot's logits (n == 1 then, by _plan_block);
        # mid-prefill rows need nothing — their first token samples at
        # finalization, where _activate applies the start-state mask
        gmask = self._grammar_masks(active)
        # multi-LoRA: slot-plane adapter rows cover BOTH halves of the
        # fused program (prefill rows and decode rows are the same
        # max_slots plane)
        lora = self._lora_args()
        kw = {} if lora is None else {"lora": lora}
        # per-phase device accounting for the ONE fused dispatch: the
        # wall time is split between prefill and decode in proportion
        # to each half's FLOPs (token-count fallback without a cost
        # model) — arxiv 2311.03687's phase dissection must survive the
        # fusion that merged the phases into one program
        pf_tokens = sum(len(c) for _, _, c in entries)
        pf_keys = sum(CostModel.chunk_keys(len(c), st["done"])
                      for _, st, c in entries)
        dc_tokens = n * len(active)
        dc_keys = sum(CostModel.block_keys(n, int(self.slot_len[s]))
                      for s in active)
        # one scope spans through the two note_device_phase calls below
        # (their dt shares must land inside it so the device deduction
        # balances) — and the dispatch calls themselves, so a raising
        # dispatch can't leak an open scope frame
        with self.steptrace.scope("dispatch_wait"):
            t0 = time.monotonic()
            self.rng, sub = jax.random.split(self.rng)
            if self.paged is not None:
                # view must hold: each prefill row's chunk + the scan's
                # n garbage rows above it (done+C+n), and each occupied
                # decode row's dead chunk window (len+C; the scan's
                # real n rows overwrite its head) — the same extents
                # _mixed_feasible bounds against cache_len
                need = max(
                    [st["done"] + C + n for _, st, _ in entries]
                    + [int(self.slot_len[s]) + C
                       for s in range(self.max_slots)
                       if s not in self.slot_prefill
                       and self.slot_req[s] is not None] + [C + n])
                W = self._paged_width(need)
                self._pulse_view(W)
                starts = np.minimum(starts, W - C)
                valid = np.zeros((self.max_slots,), np.int32)
                for slot, st, chunk in entries:
                    starts[slot] = st["done"]
                    valid[slot] = len(chunk)
                    self._paged_cow_fork(slot, st["done"], len(chunk))
                for s in active:
                    valid[s] = n
                    self._paged_cow_fork(s, int(self.slot_len[s]), n)
                if gmask is not None:
                    fn = (self._pg_mixed_masked if lora is None
                          else self._pg_mixed_masked_lora)
                    chunk_last, toks, self.paged.kv = fn(
                        self.params, self.paged.kv,
                        jnp.asarray(self.paged.gather_idx(W)),
                        jnp.asarray(tok), jnp.asarray(starts),
                        jnp.asarray(lens), jnp.asarray(advance),
                        jnp.asarray(self.slot_last_token), sub,
                        jnp.asarray(self._temperature),
                        jnp.asarray(self._top_k),
                        jnp.asarray(self._top_p),
                        jnp.asarray(self._greedy),
                        jnp.asarray(gmask),
                        jnp.asarray(self.paged.scatter_idx(
                            starts, valid, C)),
                        n=n, **kw,
                    )
                else:
                    fn = (self._pg_mixed if lora is None
                          else self._pg_mixed_lora)
                    chunk_last, toks, self.paged.kv = fn(
                        self.params, self.paged.kv,
                        jnp.asarray(self.paged.gather_idx(W)),
                        jnp.asarray(tok), jnp.asarray(starts),
                        jnp.asarray(lens), jnp.asarray(advance),
                        jnp.asarray(self.slot_last_token), sub,
                        jnp.asarray(self._temperature),
                        jnp.asarray(self._top_k),
                        jnp.asarray(self._top_p),
                        jnp.asarray(self._greedy),
                        jnp.asarray(self.paged.scatter_idx(
                            starts, valid, C)),
                        n=n, **kw,
                    )
            elif gmask is not None:
                fn = (self._mixed_masked if lora is None
                      else self._mixed_masked_lora)
                chunk_last, toks, self.cache = fn(
                    self.params, self.cache, jnp.asarray(tok),
                    jnp.asarray(starts), jnp.asarray(lens),
                    jnp.asarray(advance),
                    jnp.asarray(self.slot_last_token), sub,
                    jnp.asarray(self._temperature),
                    jnp.asarray(self._top_k),
                    jnp.asarray(self._top_p),
                    jnp.asarray(self._greedy),
                    jnp.asarray(gmask),
                    n=n, **kw,
                )
            else:
                fn = self._mixed if lora is None else self._mixed_lora
                chunk_last, toks, self.cache = fn(
                    self.params, self.cache, jnp.asarray(tok),
                    jnp.asarray(starts), jnp.asarray(lens),
                    jnp.asarray(advance),
                    jnp.asarray(self.slot_last_token), sub,
                    jnp.asarray(self._temperature),
                    jnp.asarray(self._top_k),
                    jnp.asarray(self._top_p),
                    jnp.asarray(self._greedy),
                    n=n, **kw,
                )
            toks_host = np.asarray(toks)  # forces the dispatch's results
            dt = time.monotonic() - t0
            self.mixed_blocks += 1
            for slot, st, chunk in entries:
                st["last_logits"] = chunk_last[slot:slot + 1]
                st["done"] += len(chunk)
            self._trace_chunks(entries, dt, batched=True, fused=True)
            cm = self.cost_model
            if cm is not None:
                pf, df = (cm.step_flops(pf_tokens, pf_keys),
                          cm.step_flops(dc_tokens, dc_keys))
                share = pf / (pf + df) if pf + df > 0 else 0.5
            else:
                share = pf_tokens / max(pf_tokens + dc_tokens, 1)
            self._note_device_phase(
                "prefill", tokens=pf_tokens, attended_keys=pf_keys,
                weight_passes=1, kv_read_tokens=pf_keys, dt=dt * share)
            self._note_device_phase(
                "decode", tokens=dc_tokens, attended_keys=dc_keys,
                weight_passes=n, kv_read_tokens=dc_keys,
                dt=dt * (1 - share))
        with self.steptrace.scope("sample_commit"):
            # decode members waited the whole fused dispatch, like the
            # prefill members booked in _trace_chunks
            for s in active:
                self.slot_req[s].cp_add("decode_dispatch", dt)
            self._finalize_prefills()
            self._commit_block(active, toks_host, n)
        return True

    def _commit_block(self, active: list[int], toks_host, n: int) -> None:
        """Book an ``n``-step decode block's tokens ((B, n) host array)
        into every active slot — shared by the fused mixed step and the
        sequential multi-step path, so the two dispatch modes commit
        (and stop at mid-block finishes) identically."""
        if n > 1:
            self.multi_blocks += 1
            self.multi_steps_total += n
        for slot in active:
            for j in range(n):
                if self.slot_req[slot] is None:
                    break                 # finished mid-block (eos/len)
                self._commit_token(slot, int(toks_host[slot, j]))

    def step(self) -> bool:
        """One engine iteration. Returns False when fully idle."""
        with self._lock:
            before = self.dispatch_meter.total
            # the flight recorder brackets the WHOLE step; the timeline
            # (per-segment intervals for the Perfetto dual-lane view)
            # is only captured while a Chrome-JSONL sink is attached
            self.steptrace.step_begin(
                timeline=getattr(self.tracer, "has_file_sink", False))
            busy = False
            try:
                busy = self._step_locked()
                return busy
            finally:
                spent = self.dispatch_meter.total - before
                # idle background-loop polls (~10 Hz while waiting on
                # _wake) must not record 0-dispatch steps, or the
                # per-step rolling mean decays to 0 on any bursty
                # server and the metric stops meaning anything (the
                # steptrace ring follows the same rule)
                if busy or spent:
                    self.dispatch_meter.note_step(spent)
                    self.steptrace.step_end(self.tracer)
                else:
                    self.steptrace.step_abort()

    def _step_locked(self) -> bool:
        with self.steptrace.scope("admit"):
            self._admit()
        budget = self.prefill_budget
        active = self._ready_slots()
        # A speculative engine at decode_steps=1 keeps speculating
        # while prompts prefill (the r5 composition): its verify step
        # yields 1+accepted tokens per dispatch, strictly more than the
        # fused step's single token at n=1 — suspending it there would
        # REGRESS mixed-load TPOT on accepting workloads. On a
        # ``--role decode`` replica speculation NEVER suspends (ISSUE 9
        # / ROADMAP item 4): prefill on such a replica is the rare
        # degraded local-re-prefill path, and the fused spec round
        # (verify + the block's remaining steps in one dispatch) beats
        # the plain fused block at every decode_steps. Mixed
        # (``--role both``) replicas with decode_steps>1 keep the
        # documented suspend-during-prefill behavior: there the fused
        # mixed step's chunk+block amortization wins. Composition only
        # applies when speculation actually CAN run this step —
        # non-greedy traffic on a spec engine must not lose the fused
        # step too.
        with self.steptrace.scope("plan"):
            spec_composes = (
                (self.decode_steps == 1 or self.role == "decode")
                and self._spec_applicable(active)
                # the verify runs AFTER this step's chunks advance each
                # prefill row (by up to budget chunks) — account for
                # that movement here, or near the cache tail the
                # composition promise breaks: the feasible fused
                # dispatch is skipped and _try_speculative then
                # declines post-advance, leaving 2 dispatches for 1
                # token
                and all(st["done"] + budget * self.chunked_prefill
                        + self.speculative_k + 1 <= self.cache_len
                        for st in self.slot_prefill.values())
            )
        pre_progress = False
        if (self.mixed_step and self.slot_prefill and active
                and not spec_composes):
            # Fused mixed-batch step: prefill chunks + the decode block
            # in ONE dispatch, so decoders keep their n>1 amortization
            # while prompts prefill (r5: forcing n=1 here collapsed
            # conc-4 long-context TPOT p99 from ~67 ms to 315 ms).
            if budget > 1:
                # the fused program carries ONE chunk per dispatch;
                # spend the rest of the guaranteed prefill budget
                # sequentially first so the TTFT bound
                # (ceil(chunks/budget) steps) still holds — and
                # re-snapshot the ready set, since a prompt finishing
                # its last chunk here activates and must join this
                # step's decode block (sequential-path parity)
                pre_progress = self._advance_prefills(budget - 1)
                budget = 1
                active = self._ready_slots()
            if self.slot_prefill and active:
                with self.steptrace.scope("plan"):
                    n = self._plan_block(active)
                    ok, why = self._mixed_feasible(active, n)
                if ok:
                    # the decode-replica suspension gate is GONE
                    # (ISSUE 9 satellite): on role="decode" the branch
                    # above composes speculation whenever it can run at
                    # all, so reaching here means spec was inapplicable
                    # (non-greedy traffic / cache tail) — logging
                    # "suspended" would be noise. Only mixed replicas
                    # still suspend by policy, and only they log it.
                    if (self.speculative_k is not None
                            and self.role != "decode"
                            and not self._spec_suspended_logged):
                        self._spec_suspended_logged = True
                        self._log.info(
                            "speculative decoding suspended while a "
                            "prompt is mid-prefill: the fused mixed "
                            "step runs plain decode blocks (greedy "
                            "outputs are unchanged — spec is lossless); "
                            "speculation resumes when no prefill is in "
                            "flight")
                    if self._mixed_dispatch(active, n):
                        self._update_active_stats()
                        return True
                    # paged page reservation drained one half of the
                    # mixed sets: run this step's remainder on the
                    # sequential paths
                    active = self._ready_slots()
                else:
                    # log each fallback KIND once (the detail after ':'
                    # varies per occurrence; keying the dedup on it
                    # would grow without bound on a long-running server)
                    kind = why.split(":", 1)[0]
                    if kind not in self._mixed_fallbacks_logged:
                        self._mixed_fallbacks_logged.add(kind)
                        self._log.info(
                            "fused mixed step fell back to sequential "
                            "dispatches: %s", why)
        progressed = self._advance_prefills(budget) or pre_progress
        active = self._ready_slots()
        if not active:
            return progressed or bool(self.slot_prefill)
        if self._try_speculative(active):
            self._update_active_stats()
            return True
        self.rng, sub = jax.random.split(self.rng)
        with self.steptrace.scope("plan"):
            n = self._plan_block(active)
            use_multi = (
                n > 1
                # (a spec engine reaching here DIDN'T speculate this
                # step — draft miss / non-greedy — and must not also
                # forfeit the block amortization; the fused spec round
                # otherwise spans the same plan itself)
                # every row the block writes must land inside the cache
                and all(self.slot_len[s] + n <= self.cache_len
                        for s in active)
            )
        if use_multi:
            if self.paged is not None:
                with self.steptrace.scope("admit"):
                    active = self._paged_reserve_active(active, n)
                if not active:
                    return True  # reservation finished/preempted them all
            lora = self._lora_args()
            kw = {} if lora is None else {"lora": lora}
            with self.steptrace.scope("dispatch_wait"):
                t0 = time.monotonic()
                if self.paged is not None:
                    toks = self._paged_decode_dispatch(active, n, sub,
                                                       lora=lora)
                else:
                    fn = (self._decode_multi if lora is None
                          else self._decode_multi_lora)
                    toks, self.cache = fn(
                        self.params, self.cache,
                        jnp.asarray(self.slot_last_token),
                        sub,
                        jnp.asarray(self._temperature),
                        jnp.asarray(self._top_k),
                        jnp.asarray(self._top_p),
                        jnp.asarray(self._greedy),
                        n=n, **kw,
                    )
                toks_host = np.asarray(toks)
                keys = sum(CostModel.block_keys(n, int(self.slot_len[s]))
                           for s in active)
                dt = time.monotonic() - t0
                self._note_device_phase(
                    "decode", tokens=n * len(active), attended_keys=keys,
                    weight_passes=n, kv_read_tokens=keys, dt=dt)
            with self.steptrace.scope("sample_commit"):
                for s in active:
                    self.slot_req[s].cp_add("decode_dispatch", dt)
                self._commit_block(active, toks_host, n)
            self._update_active_stats()
            return True
        if self.paged is not None:
            with self.steptrace.scope("admit"):
                active = self._paged_reserve_active(active, 1)
            if not active:
                return True
        # constrained decoding: per-slot grammar mask rows, applied by
        # the masked twin program in the SAME single dispatch
        gmask = self._grammar_masks(active)
        lora = self._lora_args()
        kw = {} if lora is None else {"lora": lora}
        with self.steptrace.scope("dispatch_wait"):
            t0 = time.monotonic()
            if self.paged is not None:
                next_tok = self._paged_decode_dispatch(active, 1, sub,
                                                       gmask=gmask,
                                                       lora=lora)
                next_tok = next_tok[:, 0]
            elif gmask is not None:
                fn = (self._decode_masked if lora is None
                      else self._decode_masked_lora)
                next_tok, self.cache = fn(
                    self.params, self.cache,
                    jnp.asarray(self.slot_last_token),
                    sub,
                    jnp.asarray(self._temperature),
                    jnp.asarray(self._top_k),
                    jnp.asarray(self._top_p),
                    jnp.asarray(self._greedy),
                    jnp.asarray(gmask), **kw,
                )
            else:
                fn = self._decode if lora is None else self._decode_lora
                next_tok, self.cache = fn(
                    self.params, self.cache,
                    jnp.asarray(self.slot_last_token),
                    sub,
                    jnp.asarray(self._temperature),
                    jnp.asarray(self._top_k),
                    jnp.asarray(self._top_p),
                    jnp.asarray(self._greedy),
                    **kw,
                )
            next_host = np.asarray(next_tok)
            keys = sum(CostModel.block_keys(1, int(self.slot_len[s]))
                       for s in active)
            dt = time.monotonic() - t0
            self._note_device_phase(
                "decode", tokens=len(active), attended_keys=keys,
                weight_passes=1, kv_read_tokens=keys, dt=dt)
        with self.steptrace.scope("sample_commit"):
            for s in active:
                self.slot_req[s].cp_add("decode_dispatch", dt)
            for slot in active:
                self._commit_token(slot, int(next_host[slot]))
        self._update_active_stats()
        return True

    # --- background loop -----------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            busy = self.step()
            if not busy:  # idle: block until a submit wakes us (don't spin)
                self._wake.wait(timeout=0.1)
                self._wake.clear()

    def _hbm_book(self, owner: str, n_bytes: int) -> None:
        """Book one durable allocation under ``owner`` and remember it
        so ``stop()`` frees exactly what ``__init__`` booked."""
        self._hbm.book(owner, n_bytes)
        self._hbm_booked[owner] = n_bytes

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self.session_store is not None:
            # drop every session pin (and stop the publisher) so pool
            # leak checks see only live-slot references after shutdown
            self.session_store.close()
        # return every ledger byte this engine booked (idempotent — a
        # second stop() finds the books already empty)
        for owner, n in self._hbm_booked.items():
            self._hbm.book(owner, -n)
        self._hbm_booked = {}
        if self.paged is not None:
            self.paged.close()

    def is_alive(self) -> bool:
        """True while the engine can still make progress on submitted
        requests: not stopped, and — when a background loop was started
        — its thread is actually running. The API layer polls this so a
        dead engine surfaces as a 5xx instead of a client blocking
        forever on a token queue no one will ever fill."""
        if self._stop.is_set():
            return False
        return self._thread is None or self._thread.is_alive()

    # --- introspection -------------------------------------------------------

    def debug_kv(self) -> dict:
        """The ``GET /debug/kv`` payload: page-pool occupancy, sharing,
        fragmentation, refcount histogram, and per-slot block-table
        sizes (docs/paged-kv.md). Contiguous engines report their fixed
        reservation so the endpoint exists under either layout."""
        if self.paged is None:
            return {
                "layout": "contiguous",
                "max_slots": self.max_slots,
                "cache_len": self.cache_len,
                "kv_tokens_reserved": self.max_slots * self.cache_len,
                "ledger_account": "kv.contiguous",
                "kv_bytes": self._hbm_booked.get("kv.contiguous", 0),
            }
        snap = self.paged.debug_snapshot()
        live = 0
        for s in range(self.max_slots):
            # lock-free read from HTTP/scrape threads: the engine thread
            # pops slot_prefill concurrently, so membership-then-
            # subscript would be a TOCTOU KeyError — snapshot with .get
            st = self.slot_prefill.get(s)
            if st is not None:
                live += int(st["done"])
            elif self.slot_req[s] is not None:
                live += int(self.slot_len[s])
        mapped_tokens = snap["pages_slot_mapped"] * self.paged.page_size
        snap["live_tokens"] = live
        # internal fragmentation: allocated-but-unfilled slack of the
        # slot-mapped pages (tail of each slot's last page + reserved
        # decode headroom) — the waste the CONTIGUOUS layout suffers at
        # (cache_len - context) per slot, shrunk to < page_size here
        snap["fragmentation"] = (
            round(1.0 - live / mapped_tokens, 4) if mapped_tokens else 0.0)
        snap["preemptions"] = self.preemptions
        snap["rejected_too_large"] = self.rejected_too_large
        # satellite of ISSUE 9: with a draft model and an explicit pool
        # budget, the draft cache's contiguous bytes were deducted from
        # the page pool (token-equivalent) so admission can't over-admit
        snap["draft_kv_reserved_tokens"] = self.draft_kv_reserved_tokens
        # the same reservation in bytes, as the ledger books it (account
        # kv.draft) — /debug/kv and /debug/hbm agree on the draft tax
        snap["draft_kv_account_bytes"] = self._hbm_booked.get("kv.draft", 0)
        if self.prefix_cache is not None:
            snap["prefix_index_entries"] = self.prefix_cache.n_entries
        return snap

    def debug_requests(self, limit: int = 64) -> dict:
        """The ``GET /debug/requests`` payload: the recent-finished ring
        with each request's critical-path breakdown (CP_SEGMENTS). The
        engine segments partition the request's submit→finish wall
        clock (``host_gap`` is the residual); ``stream_flush`` is the
        API-side SSE tail, measured concurrently with decode and
        reported alongside, and may still be absent for a stream whose
        handler hasn't closed yet. Reads are lock-free snapshots of the
        GIL-atomic deque (HTTP threads vs. the finishing threads)."""
        now = time.monotonic()
        out = []
        for r in list(self.finished)[-limit:]:
            wall = (r.finish_time - r.submit_time
                    if r.finish_time is not None else None)
            out.append({
                "uid": r.uid,
                "finish_reason": r.finish_reason,
                "prompt_tokens": len(r.prompt_ids),
                "completion_tokens": r.n_generated,
                "cache": r.cache_outcome,
                "ttft_s": (round(r.ttft_s, 6)
                           if r.ttft_s is not None else None),
                "wall_s": round(wall, 6) if wall is not None else None,
                "age_s": (round(now - r.finish_time, 3)
                          if r.finish_time is not None else None),
                "segments": {k: round(v, 6) for k, v in r.cp.items()},
            })
        return {
            "capacity": self.finished.maxlen,
            "segments": list(CP_SEGMENTS),
            "critical_path_seconds_total":
                {k: round(v, 6) for k, v in
                 self.stats.critical_path_snapshot().items()},
            "finished": out,
        }

    def debug_sessions(self) -> dict:
        """The ``GET /debug/sessions`` payload (serve/sessions.py) —
        pinned conversations, turn/eviction/pull accounting. Exists
        under every configuration so the endpoint never 404s on a
        replica that happens to run without the store."""
        if self.session_store is None:
            return {"enabled": False}
        return self.session_store.debug_snapshot()

    def page_capacity_detail(self, prompt_tokens: int) -> dict:
        """Why a prompt 422s: the page math for the API error body."""
        from llm_in_practise_tpu.serve.paged_kv import pages_for

        P = self.paged.page_size
        return {
            "prompt_tokens": prompt_tokens,
            "page_size": P,
            "pages_needed": pages_for(prompt_tokens + 1, P),
            "pages_capacity": self.paged.pool.capacity,
        }

    # --- convenience ---------------------------------------------------------

    def generate(self, prompt_ids, params: SamplingParams | None = None,
                 *, adapter: str | None = None) -> list[int]:
        """Blocking single-request helper (drives steps if no thread runs)."""
        req = self.submit(prompt_ids, params, adapter=adapter)
        if self._thread is None:
            while self.step():
                pass
        return req.result()


def shard_params_for_serving(params, strategy, mesh):
    """Place model params for sharded serving (TP/FSDP over ``mesh``) —
    the loading step vLLM does per tensor-parallel rank, here one
    device_put against the strategy's NamedShardings.

    Packed quantized trees (Int8/Int4/NF4/AWQ leaves from
    ``quant/io.load_packed``) are detected and placed through
    :func:`~llm_in_practise_tpu.quant.sharding.quant_tree_shardings`
    with the SAME strategy rule table — each component array of a
    packed leaf gets the sharding the bf16 weight would have, respecting
    the format's internal blocking (ISSUE 10: int8 14B loads
    shard-parallel instead of failing fast at the CLI)."""
    from llm_in_practise_tpu.quant.sharding import (
        QUANT_LEAVES,
        shard_quant_tree,
    )

    is_quant = lambda x: isinstance(x, QUANT_LEAVES)  # noqa: E731
    if any(is_quant(leaf) for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=is_quant)):
        return shard_quant_tree(params, mesh, strategy.effective_rules())
    return jax.device_put(params, strategy.param_shardings(params, mesh))

"""Replica autoscaling — the reference's Ray Serve / KEDA scaling story,
in-process.

The reference scales serving two ways:

- Ray Serve app-level autoscaling (``Deployment/Ray/serve_deploy_examples/
  qwen3_app_autoscaling.yaml:12-19``): ``min_replicas``/``max_replicas``,
  ``target_ongoing_requests: 5``, ``upscale_delay_s``/``downscale_delay_s``,
  ``max_ongoing_requests: 64`` per replica.
- KEDA on Kubernetes (``LLM_on_Kubernetes/Inference_Platfrom/05-KEDA-AutoScale/
  keda-scaledobject.yaml:37-55``): Prometheus triggers on queue depth / p99
  TTFT, with HPA stabilization windows (the cluster-level analog lives in
  ``deploy/k8s/03-autoscale/``).

This module is the Ray-Serve-shaped half: a controller that watches
ongoing requests across a :class:`~.gateway.Router` group and grows or
shrinks the upstream set through user-supplied ``spawn``/``stop``
callables (a thread-local engine replica, a subprocess, or a K8s scale
call — the controller doesn't care). Decisions follow Ray's semantics:

- desired = ceil(mean ongoing over ``look_back_period_s`` / target)
- an upscale fires only after the need persists ``upscale_delay_s``;
  a downscale only after ``downscale_delay_s`` (slow-down, fast-up)
- always within [min_replicas, max_replicas]; downscale picks idle
  replicas and **drains** them: a victim leaves the router (no new
  picks) and is stopped no earlier than the next tick, once its
  in-flight count reads zero — a request that selected the upstream in
  the instant before the swap gets a full metrics interval to register
  and finish.

``tick(now)`` is the whole control law — deterministic and clock-injected
so tests drive it without sleeping; ``start()`` wraps it in a daemon
thread for production use.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque

from llm_in_practise_tpu.serve.gateway import Router, Upstream


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Ray Serve ``autoscaling_config`` field-for-field (yaml:12-19)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 5.0
    upscale_delay_s: float = 30.0
    downscale_delay_s: float = 600.0
    look_back_period_s: float = 30.0
    metrics_interval_s: float = 10.0

    def __post_init__(self):
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")
        if self.target_ongoing_requests <= 0:
            raise ValueError("target_ongoing_requests must be > 0")


class ReplicaAutoscaler:
    """Scale one router group's upstream set to its request load.

    ``spawn() -> Upstream`` brings up a replica and returns its endpoint;
    ``stop(upstream)`` tears one down. Both run on the controller thread
    (or the caller of :meth:`tick`); the router sees membership changes
    atomically under its list replacement.
    """

    def __init__(self, router: Router, group: str, *,
                 spawn, stop, config: AutoscaleConfig | None = None,
                 clock=time.time, role: str | None = None):
        self.router = router
        self.group = group
        # Disaggregated serving: a role-scoped scaler controls ONE pool
        # of a group (role="prefill" or "decode"); its load signal is
        # the in-flight count on that pool's upstreams, which measures
        # exactly what that pool is short of — pending prefill handoffs
        # ARE the prefill queue depth (the gateway holds pending for the
        # whole /internal/handoff/prefill call), and pending decode
        # streams ARE slot occupancy (the stream handle holds pending
        # until the stream closes — see gateway._StreamHandle). None =
        # scale the whole group (pre-disagg behavior). role="both"
        # replicas belong to neither role pool and are left alone.
        self.role = role
        self.spawn = spawn
        self.stop = stop
        self.config = config or AutoscaleConfig()
        self.clock = clock
        # membership lock shared by every scaler over the same router:
        # two groups' controllers must not interleave their list swaps
        # (read-modify-write of router.upstreams would lose updates)
        self._router_lock = router.__dict__.setdefault(
            "_membership_lock", threading.Lock())
        # (ts, ongoing) samples inside the look-back window
        self._samples: "deque[tuple[float, float]]" = deque()  # guarded-by: _lock
        self._want_up_since: float | None = None    # guarded-by: _lock
        self._want_down_since: float | None = None  # guarded-by: _lock
        self._draining: list[Upstream] = []         # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self._thread: threading.Thread | None = None
        # decision counters: written by the controller thread, read by
        # scrapes/tests from other threads — and tick() is callable
        # directly (tests, manual control), so increments hold the lock
        self.upscales = 0     # guarded-by: _lock
        self.downscales = 0   # guarded-by: _lock
        self.errors = 0       # guarded-by: _lock

    # -- observability --------------------------------------------------------

    def replicas(self) -> list[Upstream]:
        return [u for u in self.router.upstreams
                if u.group == self.group
                and (self.role is None
                     or getattr(u, "role", "both") == self.role)]

    def ongoing(self) -> int:
        """Current in-flight count (public: tests/metrics callers).
        Takes the state lock — ``tick`` holds it already and uses
        :meth:`_ongoing_locked` (reading ``_draining`` lock-free here
        would race tick's drain-list mutation)."""
        with self._lock:
            return self._ongoing_locked()

    def _ongoing_locked(self) -> int:
        # draining victims left the router but their in-flight requests are
        # still load — excluding them would bias the mean downward during
        # every drain and trigger cascading downscales
        return (sum(u.pending for u in self.replicas())
                + sum(u.pending for u in self._draining))

    # -- the control law ------------------------------------------------------

    def _mean_ongoing_locked(self, now: float) -> float:
        cfg = self.config
        while self._samples and now - self._samples[0][0] > cfg.look_back_period_s:
            self._samples.popleft()
        if not self._samples:
            return 0.0
        return sum(v for _, v in self._samples) / len(self._samples)

    def tick(self, now: float | None = None) -> int:
        """One control step; returns the replica delta applied (+/-/0).

        Decisions are taken under the state lock; the user-supplied
        ``spawn``/``stop`` callbacks run **outside** it — a slow spawn must
        not block metric sampling, and a callback that re-enters scaler
        methods (``ongoing()``, even ``tick()``) must not deadlock. One
        controller per group: concurrent ``tick`` calls would race the
        spawn/stop decisions themselves.
        """
        cfg = self.config
        now = self.clock() if now is None else now
        to_stop: list[Upstream] = []
        n_spawn = 0
        with self._lock:
            # reap: draining replicas whose last in-flight request finished
            for u in list(self._draining):
                if u.pending == 0:
                    self._draining.remove(u)
                    to_stop.append(u)
            self._samples.append((now, float(self._ongoing_locked())))
            current = len(self.replicas())
            desired = math.ceil(
                self._mean_ongoing_locked(now) / cfg.target_ongoing_requests)
            desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))

            if desired > current:
                self._want_down_since = None
                if self._want_up_since is None:
                    self._want_up_since = now
                if now - self._want_up_since >= cfg.upscale_delay_s:
                    self._want_up_since = None
                    n_spawn = desired - current
            elif desired < current:
                self._want_up_since = None
                if self._want_down_since is None:
                    self._want_down_since = now
                if now - self._want_down_since >= cfg.downscale_delay_s:
                    self._want_down_since = None
                    # drain the idlest replicas: out of the router now (no
                    # new picks), stopped only once in-flight hits zero — a
                    # request that raced the selection finishes before
                    # teardown; reaped no earlier than the NEXT tick, so a
                    # request thread that picked the victim just before the
                    # swap gets one metrics interval to bump pending
                    victims = sorted(
                        (u for u in self.replicas() if u.pending == 0),
                        key=lambda u: u.served,
                    )[: current - desired]
                    if victims:
                        gone = set(map(id, victims))
                        with self._router_lock:  # atomic list swap
                            self.router.upstreams = [
                                u for u in self.router.upstreams
                                if id(u) not in gone]
                        self._draining.extend(victims)
            else:
                self._want_up_since = None
                self._want_down_since = None

        # -- callbacks, outside the lock --
        for u in to_stop:
            self.stop(u)
        if to_stop:
            with self._lock:
                self.downscales += len(to_stop)
        fresh: list[Upstream] = []
        if n_spawn:
            try:
                for _ in range(n_spawn):
                    u = self.spawn()
                    if (self.role is not None
                            and getattr(u, "role", "both") != self.role):
                        # a wrong-role replica would join the router but
                        # never this scaler's replicas() count — desired
                        # stays > current and the controller spawns
                        # forever. Fail loudly instead (start()'s loop
                        # logs + counts it).
                        self.stop(u)
                        raise ValueError(
                            f"spawn for the {self.role!r} pool returned "
                            f"an upstream with role "
                            f"{getattr(u, 'role', 'both')!r}")
                    fresh.append(u)
            finally:
                # register even a partial batch (a failed later spawn must
                # not leak the replicas already brought up); atomic list
                # swap: request threads iterate router.upstreams lock-free
                if fresh:
                    with self._router_lock:
                        self.router.upstreams = self.router.upstreams + fresh
                    with self._lock:
                        self.upscales += len(fresh)
        return len(fresh) - len(to_stop)

    # -- background controller ------------------------------------------------

    def start(self) -> "ReplicaAutoscaler":
        import logging

        log = logging.getLogger(__name__)

        def run():
            while not self._stop_event.wait(self.config.metrics_interval_s):
                try:
                    self.tick()
                except Exception:  # a failed spawn must not kill the loop
                    with self._lock:
                        self.errors += 1
                        n_errors = self.errors
                    log.exception("autoscaler tick failed for group %r "
                                  "(failure #%d)", self.group, n_errors)
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


def make_disagg_autoscalers(
    router: Router, group: str, *,
    spawn_prefill, stop_prefill, spawn_decode, stop_decode,
    prefill_config: AutoscaleConfig | None = None,
    decode_config: AutoscaleConfig | None = None,
    clock=time.time,
) -> tuple[ReplicaAutoscaler, ReplicaAutoscaler]:
    """Per-role controllers for a disaggregated group (serve/disagg.py).

    The two pools starve on DIFFERENT signals, which is the whole point
    of splitting them:

    - the **prefill pool** scales on prefill queue pressure — each
      in-flight ``/internal/handoff/prefill`` call holds ``pending`` on
      its upstream for the prefill's full duration, so the pool's
      pending sum is the number of prompts currently waiting on (or
      occupying) prefill compute;
    - the **decode pool** scales on slot occupancy — a decode upstream's
      ``pending`` counts open completion streams (the gateway's stream
      handle releases it only at stream close), i.e. occupied decode
      slots, not request arrivals.

    ``spawn_prefill``/``spawn_decode`` must return :class:`Upstream`\\ s
    with the matching ``role`` — a spawned replica with the wrong role
    joins neither pool's count and would be re-spawned forever. Defaults
    differ: prefill work is bursty and short, so its controller reacts
    faster and targets fewer ongoing requests per replica than the
    decode controller, whose streams are long-lived.
    """
    prefill_config = prefill_config or AutoscaleConfig(
        target_ongoing_requests=2.0, upscale_delay_s=10.0,
        downscale_delay_s=300.0)
    decode_config = decode_config or AutoscaleConfig(
        target_ongoing_requests=6.0, upscale_delay_s=30.0,
        downscale_delay_s=600.0)
    pre = ReplicaAutoscaler(router, group, role="prefill",
                            spawn=spawn_prefill, stop=stop_prefill,
                            config=prefill_config, clock=clock)
    dec = ReplicaAutoscaler(router, group, role="decode",
                            spawn=spawn_decode, stop=stop_decode,
                            config=decode_config, clock=clock)
    return pre, dec

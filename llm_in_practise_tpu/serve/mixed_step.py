"""Fused mixed-batch engine step: prefill chunk + multi-step decode, ONE dispatch.

The r5 long-context bench (`BENCH_SERVE_QWEN3_8B_INT8_LONG_r05.json`)
fails both SLAs the moment prefill and decode overlap: the engine ran
the batched prefill chunk and the decode as SEPARATE device dispatches
(~120 ms each through the remote-TPU tunnel, docs/perf.md Finding 5)
and hard-disabled multi-step decode whenever a prompt was mid-prefill,
degrading every active decoder to one token per TWO dispatches. Runtime
dissections of LLM serving identify exactly this prefill/decode
interference as the dominant mixed-load latency tax (arXiv:2311.03687),
and the TPU/GPU serving gap is mostly dispatch/scheduling overhead, not
FLOPs (arXiv:2605.25645).

This module is the fix: one jitted program that, against the engine
cache directly and in a single dispatch,

(a) advances every mid-prefill row one chunk — the pinned-index scatter
    idiom of ``engine._chunk_batch_fn`` (host-tracked ``starts`` pin
    each row's cache index for the forward; ``starts + lens`` pins it
    after, so only prefilling rows advance), then
(b) runs an ``n``-step ``lax.scan`` decode block over ALL rows — ready
    decoders produce ``n`` real tokens; mid-prefill and idle rows
    decode garbage that the overwrite-before-attend invariant already
    covers (every garbage row is rewritten by the chunk that owns its
    range, or by real decode in order, before any query can attend it).

Correctness bounds the scheduler must respect (enforced by
``InferenceEngine._mixed_feasible``; violation falls back to the
sequential two-dispatch path with a logged reason):

- ``n <= chunk``: the scan writes ``n`` garbage rows above each
  mid-prefill row's watermark; the next chunk's padded write (width
  ``chunk``) must cover them.
- prefill rows: ``done + chunk + n <= cache_len`` — both the chunk
  scatter and the garbage scan rows must land inside the cache (a
  clamped scatter would shift backward over attended prompt KV).
- decode rows: ``slot_len + chunk <= cache_len`` — the dead chunk
  write window must fit (same bound as the batched chunk path); the
  scan's real writes fit a fortiori since ``n <= chunk``.
- free rows: dead either way; the caller clamps their pinned index to
  ``cache_len - chunk`` so even the dead window stays in bounds.

Token-exactness: part (a) is bit-identical to ``_chunk_batch_fn`` (same
pinning arithmetic) and part (b) to ``_decode_multi_fn`` (same scan
body, same per-step key split), so greedy outputs equal the sequential
path's exactly — pinned by ``tests/test_mixed_step.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from llm_in_practise_tpu.infer.sampling import sample_token_batched


def pin_index(cache, index_vec):
    """Replace every layer's ``index`` with the host-provided vector —
    the shared pin/advance idiom of the batched chunk, draft, and fused
    mixed-step paths (one place to fix if the cache key convention
    changes)."""
    return [
        {k: (index_vec.astype(jnp.int32) if k == "index" else v)
         for k, v in layer.items()}
        for layer in cache
    ]


def decode_scan(model, params, cache, tokens, rng, temperature, top_k,
                top_p, greedy, *, n, gmask=None):
    """``n`` single-token decodes under one ``lax.scan`` — the SHARED
    body of the sequential multi-step program
    (``engine._decode_multi_fn``) and the fused mixed step, so the two
    dispatch modes can never drift apart in sampling or key-split
    order. Returns ``((B, n) tokens, cache)``.

    ``gmask`` (optional, (B, vocab) additive): the grammar logit mask
    of constrained decoding (serve/constrain.py) — 0 for allowed
    tokens, ``NEG_INF`` otherwise, zero rows for unconstrained slots.
    The SAME mask applies at every scan step, which is only correct for
    ``n == 1`` (the grammar state advances per token); the engine's
    planner caps constrained blocks at 1, and the unmasked programs
    (``gmask=None``) stay compiled-identical to pre-constraint builds.
    """
    if gmask is not None and n != 1:
        raise ValueError(
            f"grammar-masked decode blocks must be n=1, got n={n} "
            "(the per-slot mask is staged for one automaton state)")

    def body(carry, key):
        tok, c = carry
        lg, c = model.apply(
            {"params": params}, tok[:, None], deterministic=True,
            cache=c,
        )
        logits = lg[:, -1, :].astype(jnp.float32)
        if gmask is not None:
            logits = logits + gmask
        nxt = sample_token_batched(
            key, logits,
            temperature=temperature, top_k=top_k, top_p=top_p,
            greedy=greedy,
        ).astype(jnp.int32)
        return (nxt, c), nxt

    keys = jax.random.split(rng, n)
    (_, cache), toks = jax.lax.scan(body, (tokens, cache), keys)
    return toks.T, cache                                     # (B, n)


def batched_chunk(model, params, cache, chunk_ids, starts, lens):
    """Advance every row one pinned-index prefill chunk against the
    whole cache — the SHARED body of ``engine._chunk_batch_fn`` and the
    fused mixed step (see that method's docstring for the invariants).
    Returns ``((B, vocab) last-real-position logits, cache)`` with the
    cache index pinned to ``starts + lens``."""
    logits, cache = model.apply(
        {"params": params}, chunk_ids, deterministic=True,
        cache=pin_index(cache, starts)
    )
    cache = pin_index(cache, starts + lens)
    last = jnp.take_along_axis(
        logits, jnp.maximum(lens - 1, 0)[:, None, None], axis=1
    )[:, 0, :]
    return last, cache


def spec_verify_block(model, params, cache, tokens, base, mask, *, m,
                      gmasks=None):
    """Fused speculative round: verify the K drafted tokens AND run the
    remainder of the planned decode block, in ONE jitted dispatch
    (ROADMAP item 4 — "verify k proposed tokens inside the n-step
    decode dispatch").

    The pre-fusion spec path cost a contiguous engine TWO dispatches
    per round (the wide verify + a host-driven index ``_rewind``) and
    capped every round at ``n_acc + 1`` tokens however large
    ``decode_steps`` was. This body folds the whole round into one
    program:

    1. one wide forward over the K+1 proposed positions (index pinned
       to the host-tracked ``base`` — the same pin idiom as
       :func:`batched_chunk`, so idle/mid-prefill rows stop
       accumulating index drift);
    2. ON-DEVICE acceptance: ``n_acc`` = longest prefix of the drafts
       matching the forward's own greedy outputs (a cumprod over the
       matches — the host loop, vectorized);
    3. the index fixup the separate rewind dispatch used to do:
       ``base + (n_acc + 1) * mask`` (mask 0 rows — idle, mid-prefill
       — are restored to ``base`` exactly);
    4. ``m`` extra greedy scan steps from each row's bonus token
       ``out[s, n_acc]`` — the tail of the planned n-step block, so a
       spec round spans the same dispatch plan as a plain multi-step
       block (``m = block - 1``, see :func:`plan_spec_extension`).
       Each step overwrites the next rejected draft position before any
       query can attend it (overwrite-before-attend, as everywhere).

    ``tokens``: (B, K+1) — ``[last_token, draft_1..K]`` per row (zeros
    for undrafted/idle rows). ``base``: (B,) pinned pre-dispatch cache
    index. ``mask``: (B,) 1 for really-advancing rows. Returns
    ``(out (B, K+1), n_acc (B,), extra (B, m), cache)`` with the final
    index at ``base + (n_acc + 1 + m) * mask``.

    Greedy-lossless: every emitted token — accepted, bonus, or
    extension — is an argmax of this program's own forward, identical
    to what the sequential greedy path emits.

    ``gmasks`` (optional, (B, K+1, vocab) additive): grammar logit
    masks for constrained decoding — position ``j``'s row is the mask
    of the automaton state after the first ``j`` drafts (the host
    advances the grammar tentatively over the drafted tokens,
    serve/engine._try_speculative). A grammar-forbidden draft cannot be
    the masked argmax at its position, so the acceptance cumprod
    truncates there exactly like an argmax mismatch, and the bonus
    token at ``n_acc`` is masked by the right state's row. The caller
    runs constrained rounds at ``m == 0`` (the extension's scan steps
    have no host-stageable mask).
    """
    base = base.astype(jnp.int32)
    mask = mask.astype(jnp.int32)
    logits, cache = model.apply(
        {"params": params}, tokens, deterministic=True,
        cache=pin_index(cache, base),
    )
    logits = logits.astype(jnp.float32)
    if gmasks is not None:
        logits = logits + gmasks
    out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # longest accepted prefix: position j is accepted iff every draft
    # up to and including j matched the model's own output
    match = (out[:, :-1] == tokens[:, 1:]).astype(jnp.int32)   # (B, K)
    n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)        # (B,)
    if m == 0:
        cache = pin_index(cache, base + (n_acc + 1) * mask)
        extra = jnp.zeros((tokens.shape[0], 0), jnp.int32)
        return out, n_acc, extra, cache
    # bonus token = the model's continuation at the first mismatch (or
    # past the last draft) — the extension decodes onward from it
    bonus = jnp.take_along_axis(out, n_acc[:, None], axis=1)[:, 0]
    cache = pin_index(cache, base + (n_acc + 1) * mask)

    def body(carry, _):
        tok, c = carry
        lg, c = model.apply(
            {"params": params}, tok[:, None], deterministic=True,
            cache=c,
        )
        nxt = jnp.argmax(
            lg[:, -1, :].astype(jnp.float32), axis=-1).astype(jnp.int32)
        return (nxt, c), nxt

    (_, cache), extra = jax.lax.scan(body, (bonus, cache), None, length=m)
    # the scan advanced EVERY row's index by m; pin the real per-row
    # positions (masked rows return to base, same contract as the
    # fused mixed step's ``advance``)
    cache = pin_index(cache, base + (n_acc + 1 + m) * mask)
    return out, n_acc, jnp.swapaxes(extra, 0, 1), cache       # (B, m)


def plan_spec_extension(*, block: int, k: int, headroom: int) -> int:
    """Extra greedy steps ``m`` after the K-token verify, so one fused
    spec dispatch spans the same ``n``-step plan as a plain block
    (``block`` from :func:`plan_decode_block`): ``m = block - 1``,
    shrunk to ``headroom`` (= min over live rows of
    ``cache_len - (k + 1) - position`` — every write of the widened
    dispatch must land inside the cache) and, when shrunk by headroom,
    quantized DOWN to a power of two. Compile-set bound (each distinct
    ``m`` is its own compiled program): ``m`` takes values in
    ``{decode_steps - 1}`` ∪ ``{2^j - 1}`` (a capped block from
    :func:`plan_decode_block` is a power of two, so ``block - 1``
    lands one below) ∪ ``{2^j}`` (headroom quantization) ∪ ``{0}`` —
    ~2·log2(decode_steps) variants, all reachable by a warmup that
    drives queueing/prefill caps, same order as the plain block
    family.
    """
    m = block - 1
    if m <= 0 or headroom <= 0:
        return 0
    if headroom < m:
        m = headroom
        if m > 1:
            m = 1 << (m.bit_length() - 1)
    return m


def make_mixed_step(model):
    """Build the fused mixed-step function for ``model`` (jit with
    ``donate_argnums=(1,)`` and ``static_argnames=("n",)``).

    Signature of the returned function::

        chunk_last, toks, cache = fn(
            params, cache, chunk_ids, starts, lens, advance,
            tokens, rng, temperature, top_k, top_p, greedy, n=n)

    - ``chunk_ids`` (max_slots, chunk): real chunk tokens for
      mid-prefill rows, zeros elsewhere.
    - ``starts``/``lens`` (max_slots,): host-pinned cache index per row
      and real chunk length (0 for non-prefill rows).
    - ``advance`` (max_slots,): how far the decode block REALLY moves
      each row — ``n`` for ready decode rows, 0 elsewhere. The scan
      bumps every row's device index by ``n``; the final pin
      ``starts + lens + advance`` undoes that for mid-prefill and idle
      rows, so a prompt whose last chunk completes inside this dispatch
      activates at exactly ``plen`` (the next, unpinned decode dispatch
      must not leave an ``n``-row garbage gap below its write index).
    - ``tokens`` (max_slots,): last sampled token per ready decode row
      (garbage elsewhere).
    - ``chunk_last`` (max_slots, vocab): last-real-position logits of
      the chunk forward (meaningful only for prefill rows).
    - ``toks`` (max_slots, n): the decode block's sampled tokens
      (meaningful only for ready rows).

    Compiled variants: one per distinct ``n`` — the engine quantizes
    block lengths to powers of two, bounding this at
    log2(decode_steps)+1, all reachable by warmup.
    """

    def mixed_step_fn(params, cache, chunk_ids, starts, lens, advance,
                      tokens, rng, temperature, top_k, top_p, greedy,
                      *, n):
        # (a) one prefill chunk for every mid-prefill row, engine cache
        # directly — the same body _chunk_batch_fn compiles
        chunk_last, cache = batched_chunk(
            model, params, cache, chunk_ids, starts, lens)
        # (b) n-step decode block over all rows — the same body
        # _decode_multi_fn compiles
        toks, cache = decode_scan(
            model, params, cache, tokens, rng, temperature, top_k,
            top_p, greedy, n=n)
        # the scan advanced EVERY row's index by n; only ready decode
        # rows really moved — pin the rest back (see ``advance`` above)
        cache = pin_index(cache, starts + lens + advance)
        return chunk_last, toks, cache                       # (B, n)

    return mixed_step_fn


def make_masked_mixed_step(model):
    """Grammar-masked twin of :func:`make_mixed_step`: identical body
    plus a trailing ``gmask`` (max_slots, vocab) additive logit mask
    applied to the decode half (serve/constrain.py). A SEPARATE
    compiled program, not a flag on the unmasked one — unconstrained
    steps keep the exact pre-constraint program (golden parity by
    construction) and never pay the mask's host→device transfer. The
    planner caps constrained blocks at ``n == 1`` (the mask encodes one
    automaton state per slot)."""

    def masked_mixed_step_fn(params, cache, chunk_ids, starts, lens,
                             advance, tokens, rng, temperature, top_k,
                             top_p, greedy, gmask, *, n):
        chunk_last, cache = batched_chunk(
            model, params, cache, chunk_ids, starts, lens)
        toks, cache = decode_scan(
            model, params, cache, tokens, rng, temperature, top_k,
            top_p, greedy, n=n, gmask=gmask)
        cache = pin_index(cache, starts + lens + advance)
        return chunk_last, toks, cache                       # (B, n)

    return masked_mixed_step_fn


def plan_decode_block(*, decode_steps: int, queue_depth: int,
                      soonest_finish: int | None,
                      chunk: int | None,
                      prefill_headroom: int | None) -> int:
    """Token-budget planner for the decode block length ``n``
    (Sarathi-style stall-free batching, host side).

    Pure function so the policy is unit-testable without an engine:

    - start from the configured ``decode_steps``;
    - under queueing (``queue_depth > 0``) cap at the soonest
      *deterministic* completion among active rows (token budget or
      cache room), so a freed slot refills at the very next step;
    - while any row is mid-prefill, cap at ``chunk`` (the scan's
      garbage rows must be covered by the next chunk's write) and at
      ``prefill_headroom`` (= min over prefill rows of
      ``cache_len - chunk - done``: the garbage window must land inside
      the cache);
    - a CAPPED length is quantized DOWN to a power of two — every
      distinct ``n`` is its own compiled program, and an uncapped
      1..decode_steps range lets a first-seen length land a
      multi-second compile inside a latency-SLA request (measured r4:
      a 703 ms-mean-TPOT outlier in an otherwise 70 ms ladder). The
      configured ``decode_steps`` itself always runs at full value (a
      non-pow2 ``--decode-steps 6`` means 6, not 4) — it is one known,
      warmup-reachable variant.
    """
    n = decode_steps
    capped = False
    if (n > 1 and queue_depth > 0 and soonest_finish is not None
            and soonest_finish < n):
        n = max(1, soonest_finish)
        capped = True
    if chunk is not None and chunk < n:
        n = max(1, chunk)
        capped = True
    if prefill_headroom is not None and prefill_headroom < n:
        n = max(1, prefill_headroom)
        capped = True
    if capped and n > 1:
        n = 1 << (n.bit_length() - 1)
    return n

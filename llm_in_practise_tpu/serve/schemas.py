"""OpenAI chat-completions wire schemas, dependency-free.

Parity with the reference's pydantic models
(``Scripts/inference/07-deepseek1.5b-api-infr.py:66-102`` —
ChatMessage / ChatCompletionRequest / Choice / Usage / Response), rebuilt as
dataclasses with explicit validation since FastAPI/pydantic are not in the
TPU image (and a serving runtime should not need them).
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Any


class ValidationError(ValueError):
    """Bad request payload — maps to HTTP 422 like FastAPI's handler."""


@dataclasses.dataclass
class ChatMessage:
    role: str
    content: str

    VALID_ROLES = ("system", "user", "assistant", "tool")

    @classmethod
    def from_dict(cls, d: Any) -> "ChatMessage":
        if not isinstance(d, dict):
            raise ValidationError(f"message must be an object, got {type(d).__name__}")
        role, content = d.get("role"), d.get("content")
        if role not in cls.VALID_ROLES:
            raise ValidationError(f"invalid role {role!r}")
        if not isinstance(content, str):
            raise ValidationError("message content must be a string")
        return cls(role, content)


def _validate_response_format(rf: Any):
    """Shape-check OpenAI ``response_format`` (semantic schema support
    is the constrain compiler's job — serve/constrain.py)."""
    if rf is None:
        return None
    if not isinstance(rf, dict):
        raise ValidationError("'response_format' must be an object")
    rf_type = rf.get("type")
    if rf_type not in ("text", "json_object", "json_schema"):
        raise ValidationError(
            "response_format.type must be 'text', 'json_object', or "
            f"'json_schema', got {rf_type!r}")
    if rf_type == "json_schema":
        wrapper = rf.get("json_schema")
        if not isinstance(wrapper, dict) or not isinstance(
                wrapper.get("schema"), dict):
            raise ValidationError(
                "response_format.json_schema.schema must be an object")
    return rf


def _validate_tools(tools: Any, tool_choice: Any):
    """Shape-check OpenAI ``tools`` / ``tool_choice``."""
    if tools is not None:
        if not isinstance(tools, list):
            raise ValidationError("'tools' must be an array")
        for t in tools:
            if (not isinstance(t, dict) or t.get("type") != "function"
                    or not isinstance(t.get("function"), dict)
                    or not isinstance(t["function"].get("name"), str)):
                raise ValidationError(
                    "each tool must be {'type': 'function', 'function': "
                    "{'name': …, 'parameters': …}}")
    if tool_choice is None:
        return tools, None
    if isinstance(tool_choice, str):
        if tool_choice not in ("auto", "none", "required"):
            raise ValidationError(
                "tool_choice must be 'auto', 'none', 'required', or a "
                "function reference")
    elif isinstance(tool_choice, dict):
        if (tool_choice.get("type") != "function"
                or not isinstance(tool_choice.get("function"), dict)
                or not isinstance(
                    tool_choice["function"].get("name"), str)):
            raise ValidationError(
                "tool_choice object must be {'type': 'function', "
                "'function': {'name': …}}")
    else:
        raise ValidationError("tool_choice must be a string or object")
    if tool_choice not in ("auto", "none") and not tools:
        raise ValidationError(
            f"tool_choice {tool_choice!r} requires a non-empty 'tools'")
    return tools, tool_choice


@dataclasses.dataclass
class ChatCompletionRequest:
    """Request body of POST /v1/chat/completions (the fields the reference
    server accepts: model, messages, max_tokens, temperature, top_p, stream —
    ``07-…-api-infr.py:95-102`` — plus top_k and greedy-mode seed parity,
    plus the structured-output surface: ``response_format`` and
    ``tools``/``tool_choice``, enforced by grammar-compiled logit masks
    — serve/constrain.py, docs/structured-output.md)."""

    model: str
    messages: list[ChatMessage]
    max_tokens: int = 512
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    stream: bool = False
    response_format: dict | None = None
    tools: list | None = None
    tool_choice: Any = None

    @classmethod
    def from_dict(cls, d: Any) -> "ChatCompletionRequest":
        if not isinstance(d, dict):
            raise ValidationError("request body must be a JSON object")
        if not isinstance(d.get("model"), str) or not d["model"]:
            raise ValidationError("'model' is required")
        raw_msgs = d.get("messages")
        if not isinstance(raw_msgs, list) or not raw_msgs:
            raise ValidationError("'messages' must be a non-empty array")
        msgs = [ChatMessage.from_dict(m) for m in raw_msgs]

        def num(key, default, lo, hi, kind=float):
            v = d.get(key, default)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise ValidationError(f"'{key}' must be a number")
            v = kind(v)
            if not (lo <= v <= hi):
                raise ValidationError(f"'{key}' must be in [{lo}, {hi}]")
            return v

        tools, tool_choice = _validate_tools(
            d.get("tools"), d.get("tool_choice"))
        return cls(
            model=d["model"],
            messages=msgs,
            max_tokens=num("max_tokens", 512, 1, 1 << 20, int),
            temperature=num("temperature", 1.0, 0.0, 2.0),
            top_p=num("top_p", 1.0, 0.0, 1.0),
            top_k=num("top_k", 0, 0, 1 << 20, int),
            stream=bool(d.get("stream", False)),
            response_format=_validate_response_format(
                d.get("response_format")),
            tools=tools,
            tool_choice=tool_choice,
        )


@dataclasses.dataclass
class Usage:
    """Token accounting (parity ``07-…-api-infr.py:147-152``)."""

    prompt_tokens: int
    completion_tokens: int

    def to_dict(self) -> dict:
        return {
            "prompt_tokens": self.prompt_tokens,
            "completion_tokens": self.completion_tokens,
            "total_tokens": self.prompt_tokens + self.completion_tokens,
        }


def completion_id() -> str:
    return "chatcmpl-" + uuid.uuid4().hex[:24]


def chat_completion_response(
    *, req_id: str, model: str, text: str, finish_reason: str, usage: Usage,
    tool_calls: list | None = None,
) -> dict:
    """``tool_calls`` (forced tool-choice requests): the parsed calls
    replace ``content`` and the finish reason becomes ``tool_calls``,
    matching the OpenAI wire shape."""
    message: dict = {"role": "assistant", "content": text}
    if tool_calls is not None:
        message = {"role": "assistant", "content": None,
                   "tool_calls": tool_calls}
        finish_reason = "tool_calls"
    return {
        "id": req_id,
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {
                "index": 0,
                "message": message,
                "finish_reason": finish_reason,
            }
        ],
        "usage": usage.to_dict(),
    }


def tool_call_entry(name: str, arguments: str) -> dict:
    """One message.tool_calls[] entry (``arguments`` is the JSON TEXT,
    per the OpenAI wire format)."""
    return {
        "id": "call_" + uuid.uuid4().hex[:24],
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


def chat_completion_chunk(
    *, req_id: str, model: str, delta: str | None, finish_reason: str | None = None
) -> dict:
    """One SSE chunk (``object: chat.completion.chunk``)."""
    d: dict = {}
    if delta is not None:
        d["content"] = delta
    if not d and finish_reason is None:
        d = {"role": "assistant"}
    return {
        "id": req_id,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [{"index": 0, "delta": d, "finish_reason": finish_reason}],
    }

"""Prefix KV caching — the reference platform's L1 cache stage, in-engine.

The reference gets prompt-prefix reuse from vLLM's automatic prefix
caching (``07-L1-Cache/vllm-statefulset-apc.yaml`` —
``--enable-prefix-caching``) and from LMCache's remote KV pool
(``vllm-statefulset-lmcache.yaml:65-111``); warm-prefix TTFT drops from
800–1500 ms to 50–200 ms (``Inference_Platfrom/README.md:1336-1341``).

Here the same idea fits the slot engine's static-shape world: after a
prompt prefills, its per-layer KV rows (padded to the prefill bucket) are
kept in an LRU keyed by the token tuple. A new request reuses the longest
cached strict prefix — the engine then prefills only the suffix, with the
prefix rows pre-inserted and the cache index offset (positions and causal
masking follow from the index, so the math is identical to a cold
prefill). A full-prompt hit skips prefill entirely (the stored
last-position logits seed the first sampled token).

Eviction: LRU by total cached tokens. Entries are device arrays — the
budget is HBM, so default caps are modest; evictions flow into the
:mod:`.kv_pool` tiers when one is attached (the LMCache handoff).

:class:`PrefixLRU` is the shared store — the host pool and the remote
pool server in :mod:`.kv_pool` reuse the same budget/eviction/matching
logic with different value types.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict

import jax


@dataclasses.dataclass
class PrefixEntry:
    length: int           # true token count of the cached prefix
    bucket: int           # padded length of the stored rows
    rows: list            # per-layer {key: (1, bucket, ...) device array}
    last_logits: object   # (1, vocab) logits at the final prefix position
    # Cache layout the rows were sliced from: the KV buffers' slot axis
    # (0 = unrolled per-layer dicts, 1 = stacked scan layout). An engine
    # must not consume rows from the other layout — the shapes are
    # transposed relative to its writes (shared kv_pool / restart with
    # the layout toggled) — so lookup filters on this.
    slot_axis: int = 0


class PrefixLRU:
    """Token-budget LRU keyed by exact token tuples, with
    longest-strict-prefix lookup.

    Generic over the value type: ``length_of(value)`` must return the
    value's true token count. ``on_evict(key, value)`` fires (outside the
    lock) for every budget eviction — tier handoff hooks attach here.
    """

    def __init__(self, *, max_tokens: int, min_prefix: int,
                 length_of=None, on_evict=None):
        self.max_tokens = max_tokens
        self.min_prefix = min_prefix
        self.on_evict = on_evict
        self._length_of = length_of or (lambda v: v.length)
        # internal lock: the owner's worker thread mutates while /metrics
        # (or another engine thread) reads
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._total_tokens = 0  # guarded-by: _lock
        self.hits = 0
        self.misses = 0

    @property
    def cached_tokens(self) -> int:
        with self._lock:
            return self._total_tokens

    @property
    def n_entries(self) -> int:
        # deliberately not __len__: an empty cache must stay truthy
        # (callers write ``prefix_cache or None`` to normalize False)
        with self._lock:
            return len(self._entries)

    def lookup(self, prompt_ids, usable=None):
        """Longest cached value that is a prefix of ``prompt_ids``.

        ``usable(value)`` (optional) filters candidates — the engine uses
        it to reject prefixes whose suffix prefill wouldn't fit the cache.
        """
        prompt = tuple(prompt_ids)
        with self._lock:
            best_key, best = None, None
            for key, value in self._entries.items():
                length = self._length_of(value)
                if length < self.min_prefix or length > len(prompt):
                    continue
                if best is not None and length <= self._length_of(best):
                    continue
                if prompt[:length] != key:
                    continue
                if usable is not None and not usable(value):
                    continue
                best_key, best = key, value
            if best is None:
                self.misses += 1
                return None
            self._entries.move_to_end(best_key)
            self.hits += 1
            return best

    def put(self, prompt_ids, value) -> None:
        length = self._length_of(value)
        if length < self.min_prefix:
            return
        key = tuple(prompt_ids[:length])
        evicted: list[tuple[tuple, object]] = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._total_tokens -= self._length_of(old)
            self._entries[key] = value
            self._total_tokens += length
            while self._total_tokens > self.max_tokens and len(self._entries) > 1:
                ekey, evalue = self._entries.popitem(last=False)
                self._total_tokens -= self._length_of(evalue)
                evicted.append((ekey, evalue))
        if self.on_evict is not None:
            for ekey, evalue in evicted:
                self.on_evict(ekey, evalue)

    def peek(self, key) -> object | None:
        """Exact-key read without touching LRU order (accounting hooks)."""
        with self._lock:
            return self._entries.get(tuple(key))

    def pop_lru(self):
        """Evict and return the least-recently-used (key, value), or None.

        Unlike :meth:`put`'s budget loop this will empty the store —
        callers enforcing an external budget (bytes) own the floor."""
        with self._lock:
            if not self._entries:
                return None
            key, value = self._entries.popitem(last=False)
            self._total_tokens -= self._length_of(value)
        if self.on_evict is not None:
            self.on_evict(key, value)
        return key, value

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._total_tokens = 0


class PrefixCache(PrefixLRU):
    """The engine's L1: device-array prefix entries + reuse accounting."""

    def __init__(self, *, max_tokens: int = 32768, min_prefix: int = 16,
                 on_evict=None):
        super().__init__(max_tokens=max_tokens, min_prefix=min_prefix,
                         on_evict=on_evict)
        self.full_hits = 0
        self.tokens_saved = 0

    def lookup(self, prompt_ids, usable=None) -> PrefixEntry | None:
        entry = super().lookup(prompt_ids, usable)
        if entry is not None:
            self.tokens_saved += entry.length
            if entry.length == len(prompt_ids):
                self.full_hits += 1
        return entry


def slice_cache_rows(prefill_cache, bucket: int, *, axis: int = 1) -> list:
    """Keep only the first ``bucket`` rows of each layer's KV buffers
    (drop the per-layer index — the entry carries the true length).
    ``axis`` is the sequence axis: 1 in the unrolled cache layout, 2 in
    the stacked scan layout (engine passes its ``_wax``)."""
    rows = []
    for layer in prefill_cache:
        rows.append({
            k: jax.lax.slice_in_dim(v, 0, bucket, axis=axis)
            for k, v in layer.items() if k != "index"
        })
    return rows

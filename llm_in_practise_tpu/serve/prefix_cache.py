"""Prefix KV caching — the reference platform's L1 cache stage, in-engine.

The reference gets prompt-prefix reuse from vLLM's automatic prefix
caching (``07-L1-Cache/vllm-statefulset-apc.yaml`` —
``--enable-prefix-caching``) and from LMCache's remote KV pool
(``vllm-statefulset-lmcache.yaml:65-111``); warm-prefix TTFT drops from
800–1500 ms to 50–200 ms (``Inference_Platfrom/README.md:1336-1341``).

Here the same idea fits the slot engine's static-shape world: after a
prompt prefills, its per-layer KV rows (padded to the prefill bucket) are
kept in an LRU keyed by the token tuple. A new request reuses the longest
cached strict prefix — the engine then prefills only the suffix, with the
prefix rows pre-inserted and the cache index offset (positions and causal
masking follow from the index, so the math is identical to a cold
prefill). A full-prompt hit skips prefill entirely (the stored
last-position logits seed the first sampled token).

Eviction: LRU by total cached tokens. Entries are device arrays — the
budget is HBM, so default caps are modest.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict

import jax


@dataclasses.dataclass
class PrefixEntry:
    length: int           # true token count of the cached prefix
    bucket: int           # padded length of the stored rows
    rows: list            # per-layer {key: (1, bucket, ...) device array}
    last_logits: object   # (1, vocab) logits at the final prefix position


class PrefixCache:
    """LRU of prompt-prefix KV rows, keyed by exact token tuples."""

    def __init__(self, *, max_tokens: int = 32768, min_prefix: int = 16):
        self.max_tokens = max_tokens
        self.min_prefix = min_prefix
        self._entries: "OrderedDict[tuple, PrefixEntry]" = OrderedDict()
        # internal lock: the engine thread mutates while /metrics reads
        self._lock = threading.Lock()
        self._total_tokens = 0
        self.hits = 0
        self.full_hits = 0
        self.misses = 0
        self.tokens_saved = 0

    @property
    def cached_tokens(self) -> int:
        with self._lock:
            return self._total_tokens

    def lookup(self, prompt_ids: list[int], usable=None) -> PrefixEntry | None:
        """Longest cached entry that is a prefix of ``prompt_ids``.

        ``usable(entry)`` (optional) filters candidates — the engine uses it
        to reject prefixes whose suffix prefill wouldn't fit the cache.
        """
        prompt = tuple(prompt_ids)
        with self._lock:
            best_key, best = None, None
            for key, entry in self._entries.items():
                if entry.length < self.min_prefix or entry.length > len(prompt):
                    continue
                if best is not None and entry.length <= best.length:
                    continue
                if prompt[: entry.length] != key:
                    continue
                if usable is not None and not usable(entry):
                    continue
                best_key, best = key, entry
            if best is None:
                self.misses += 1
                return None
            self._entries.move_to_end(best_key)
            self.hits += 1
            if best.length == len(prompt):
                self.full_hits += 1
            self.tokens_saved += best.length
            return best

    def put(self, prompt_ids: list[int], entry: PrefixEntry) -> None:
        if entry.length < self.min_prefix:
            return
        key = tuple(prompt_ids[: entry.length])
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._total_tokens -= old.length
            self._entries[key] = entry
            self._total_tokens += entry.length
            while self._total_tokens > self.max_tokens and len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self._total_tokens -= evicted.length

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._total_tokens = 0


def slice_cache_rows(prefill_cache, bucket: int) -> list:
    """Keep only the first ``bucket`` rows of each layer's KV buffers
    (drop the per-layer index — the entry carries the true length)."""
    rows = []
    for layer in prefill_cache:
        rows.append({
            k: jax.lax.slice_in_dim(v, 0, bucket, axis=1)
            for k, v in layer.items() if k != "index"
        })
    return rows

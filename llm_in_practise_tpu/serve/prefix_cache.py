"""Prefix KV caching — the reference platform's L1 cache stage, in-engine.

The reference gets prompt-prefix reuse from vLLM's automatic prefix
caching (``07-L1-Cache/vllm-statefulset-apc.yaml`` —
``--enable-prefix-caching``) and from LMCache's remote KV pool
(``vllm-statefulset-lmcache.yaml:65-111``); warm-prefix TTFT drops from
800–1500 ms to 50–200 ms (``Inference_Platfrom/README.md:1336-1341``).

Here the same idea fits the slot engine's static-shape world: after a
prompt prefills, its per-layer KV rows (padded to the prefill bucket) are
kept in an LRU keyed by the token tuple. A new request reuses the longest
cached strict prefix — the engine then prefills only the suffix, with the
prefix rows pre-inserted and the cache index offset (positions and causal
masking follow from the index, so the math is identical to a cold
prefill). A full-prompt hit skips prefill entirely (the stored
last-position logits seed the first sampled token).

Eviction: LRU by total cached tokens. Entries are device arrays — the
budget is HBM, so default caps are modest; evictions flow into the
:mod:`.kv_pool` tiers when one is attached (the LMCache handoff).

:class:`PrefixLRU` is the shared store — the host pool and the remote
pool server in :mod:`.kv_pool` reuse the same budget/eviction/matching
logic with different value types.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from collections import OrderedDict

import jax


@dataclasses.dataclass
class PrefixEntry:
    length: int           # true token count of the cached prefix
    bucket: int           # padded length of the stored rows
    rows: list            # per-layer {key: (1, bucket, ...) device array}
    last_logits: object   # (1, vocab) logits at the final prefix position
    # Cache layout the rows were sliced from: the KV buffers' slot axis
    # (0 = unrolled per-layer dicts, 1 = stacked scan layout). An engine
    # must not consume rows from the other layout — the shapes are
    # transposed relative to its writes (shared kv_pool / restart with
    # the layout toggled) — so lookup filters on this.
    slot_axis: int = 0
    # Page-wise entries (kv_layout="paged" producers): rows span
    # ceil(length / page_size) * page_size positions — only live pages,
    # not a pow2 bucket. 0 = legacy bucket-width entry. Consumers of
    # either layout accept both; the field exists so wire accounting
    # (kv_pool) can count pages and so a reader knows the width law.
    page_size: int = 0


class PrefixLRU:
    """Token-budget LRU keyed by exact token tuples, with
    longest-strict-prefix lookup.

    Generic over the value type: ``length_of(value)`` must return the
    value's true token count. ``on_evict(key, value)`` fires (outside the
    lock) for every budget eviction — tier handoff hooks attach here.
    """

    def __init__(self, *, max_tokens: int, min_prefix: int,
                 length_of=None, on_evict=None):
        self.max_tokens = max_tokens
        self.min_prefix = min_prefix
        self.on_evict = on_evict
        self._length_of = length_of or (lambda v: v.length)
        # internal lock: the owner's worker thread mutates while /metrics
        # (or another engine thread) reads
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._total_tokens = 0  # guarded-by: _lock
        self.hits = 0
        self.misses = 0

    @property
    def cached_tokens(self) -> int:
        with self._lock:
            return self._total_tokens

    @property
    def n_entries(self) -> int:
        # deliberately not __len__: an empty cache must stay truthy
        # (callers write ``prefix_cache or None`` to normalize False)
        with self._lock:
            return len(self._entries)

    def lookup(self, prompt_ids, usable=None):
        """Longest cached value that is a prefix of ``prompt_ids``.

        ``usable(value)`` (optional) filters candidates — the engine uses
        it to reject prefixes whose suffix prefill wouldn't fit the cache.
        """
        prompt = tuple(prompt_ids)
        with self._lock:
            best_key, best = None, None
            for key, value in self._entries.items():
                length = self._length_of(value)
                if length < self.min_prefix or length > len(prompt):
                    continue
                if best is not None and length <= self._length_of(best):
                    continue
                if prompt[:length] != key:
                    continue
                if usable is not None and not usable(value):
                    continue
                best_key, best = key, value
            if best is None:
                self.misses += 1
                return None
            self._entries.move_to_end(best_key)
            self.hits += 1
            return best

    def put(self, prompt_ids, value) -> None:
        length = self._length_of(value)
        if length < self.min_prefix:
            return
        key = tuple(prompt_ids[:length])
        evicted: list[tuple[tuple, object]] = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._total_tokens -= self._length_of(old)
            self._entries[key] = value
            self._total_tokens += length
            while self._total_tokens > self.max_tokens and len(self._entries) > 1:
                ekey, evalue = self._entries.popitem(last=False)
                self._total_tokens -= self._length_of(evalue)
                evicted.append((ekey, evalue))
        if self.on_evict is not None:
            for ekey, evalue in evicted:
                self.on_evict(ekey, evalue)

    def peek(self, key) -> object | None:
        """Exact-key read without touching LRU order (accounting hooks)."""
        with self._lock:
            return self._entries.get(tuple(key))

    def pop_lru(self):
        """Evict and return the least-recently-used (key, value), or None.

        Unlike :meth:`put`'s budget loop this will empty the store —
        callers enforcing an external budget (bytes) own the floor."""
        with self._lock:
            if not self._entries:
                return None
            key, value = self._entries.popitem(last=False)
            self._total_tokens -= self._length_of(value)
        if self.on_evict is not None:
            self.on_evict(key, value)
        return key, value

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._total_tokens = 0


class PrefixCache(PrefixLRU):
    """The engine's L1: device-array prefix entries + reuse accounting."""

    def __init__(self, *, max_tokens: int = 32768, min_prefix: int = 16,
                 on_evict=None):
        super().__init__(max_tokens=max_tokens, min_prefix=min_prefix,
                         on_evict=on_evict)
        self.full_hits = 0
        self.tokens_saved = 0

    def lookup(self, prompt_ids, usable=None) -> PrefixEntry | None:
        entry = super().lookup(prompt_ids, usable)
        if entry is not None:
            self.tokens_saved += entry.length
            if entry.length == len(prompt_ids):
                self.full_hits += 1
        return entry


@dataclasses.dataclass
class _PageEntry:
    eid: int              # this entry's chain id (children key on it)
    page: int             # physical page holding the KV rows
    parent_eid: int       # 0 = chain root


class PagedPrefixIndex:
    """Page-granular prefix sharing for ``kv_layout="paged"`` engines —
    the vLLM automatic-prefix-caching idiom at its native grain.

    Where :class:`PrefixCache` stores COPIED rows keyed by whole token
    tuples (hit = longest exact entry, all-or-nothing per entry), this
    index maps **hash-per-page chains to the physical pages
    themselves**: page ``i`` of a prompt is keyed by
    ``(parent_entry_id, tokens_of_page_i)``, where ``parent_entry_id``
    identifies the entry for pages ``0..i-1``. A lookup walks the chain
    and returns every consecutively matched FULL page — a new request
    sharing 3 of a cached prompt's 5 pages reuses exactly those 3
    physical pages (refcounted, zero device copies) and prefills only
    the tail. The exact-token chain keys make collisions impossible (a
    content-hash scheme would need a verify pass; vLLM compares block
    tokens the same way).

    Copy-on-write contract: only FULL pages are ever indexed, a hit is
    capped at ``(len(prompt) - 1) // page_size`` pages (the engine must
    recompute at least the final position to obtain next-token logits),
    and slots therefore never write inside a shared page — the engine's
    defensive fork (:meth:`InferenceEngine._paged_cow_fork`) covers any
    future path that would.

    Refcounts: the index holds ONE pool reference per indexed page
    (taken at :meth:`register`); every lookup hit takes one more per
    matched page on the caller's behalf. Eviction (LRU under a token
    budget, or on-demand through :class:`~.paged_kv.PagePool`'s
    ``reclaim`` hook when admission runs dry) drops the index's
    reference — pages still mapped by live slots survive until those
    slots release them. Evicting an entry cascades to its descendants:
    a child whose parent is gone can never match again, and letting it
    linger would pin its page forever.

    Counter names mirror :class:`PrefixCache` so the
    ``llm_prefix_cache_*`` metric plumbing reads either implementation
    unchanged; ``full_hits`` counts maximal hits (every matchable page
    of the prompt matched).
    """

    def __init__(self, pool, *, max_tokens: int = 32768,
                 min_prefix: int | None = None):
        self.pool = pool
        self.page_size = pool.page_size
        self.max_tokens = max_tokens
        self.min_prefix = (min_prefix if min_prefix is not None
                           else pool.page_size)
        self._lock = threading.Lock()
        # (parent_eid, page-token tuple) -> _PageEntry, LRU-ordered
        self._entries: "OrderedDict[tuple, _PageEntry]" = OrderedDict()  # guarded-by: _lock
        self._children: dict[int, list[tuple]] = {}  # guarded-by: _lock
        self._eid = itertools.count(1)
        self.hits = 0           # guarded-by: _lock
        self.misses = 0         # guarded-by: _lock
        self.full_hits = 0      # guarded-by: _lock
        self.tokens_saved = 0   # guarded-by: _lock

    @property
    def n_entries(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def cached_tokens(self) -> int:
        with self._lock:
            return len(self._entries) * self.page_size

    def _chain_keys(self, token_ids):
        """Yield each full page's ``(page_index, tokens)`` in order."""
        P = self.page_size
        for i in range(len(token_ids) // P):
            yield i, tuple(token_ids[i * P: (i + 1) * P])

    def lookup(self, prompt_ids) -> list[int]:
        """Physical pages holding the longest indexed full-page prefix
        of ``prompt_ids`` (possibly empty). One pool reference per
        returned page is taken FOR THE CALLER — map them into a block
        table or release them."""
        plen = len(prompt_ids)
        # at least the last position must be recomputed for its logits
        max_pages = max(0, (plen - 1) // self.page_size)
        pages: list[int] = []
        with self._lock:
            parent = 0
            for i, toks in self._chain_keys(prompt_ids):
                if i >= max_pages:
                    break
                entry = self._entries.get((parent, toks))
                if entry is None:
                    break
                self._entries.move_to_end((parent, toks))
                pages.append(entry.page)
                parent = entry.eid
            if len(pages) * self.page_size < self.min_prefix:
                # too-short hits aren't worth the bookkeeping — the
                # same floor PrefixCache applies (no refs taken yet:
                # share() runs below, only for returned pages)
                pages = []
            if not pages:
                self.misses += 1
                return []
            self.hits += 1
            if len(pages) == max_pages:
                self.full_hits += 1
            self.tokens_saved += len(pages) * self.page_size
        self.pool.share(pages)
        return pages

    def register(self, token_ids, pages: list[int]) -> int:
        """Index every full page of ``token_ids`` whose chain position
        is not yet present; ``pages[i]`` must be the physical page
        holding positions ``[i*P, (i+1)*P)``. Returns how many new
        entries were created (each pinned with one pool reference)."""
        if len(token_ids) < self.min_prefix:
            return 0
        new_pages: list[int] = []
        evict: list[int] = []
        with self._lock:
            parent = 0
            created = 0
            for i, toks in self._chain_keys(token_ids):
                if i >= len(pages):
                    break
                key = (parent, toks)
                entry = self._entries.get(key)
                if entry is not None:
                    # chain position already indexed (maybe by another
                    # slot's identical prefix) — reuse ITS entry; the
                    # registering slot keeps its private copy
                    self._entries.move_to_end(key)
                    parent = entry.eid
                    continue
                entry = _PageEntry(eid=next(self._eid),
                                   page=int(pages[i]),
                                   parent_eid=parent)
                self._entries[key] = entry
                self._children.setdefault(parent, []).append(key)
                new_pages.append(entry.page)
                parent = entry.eid
                created += 1
            while (len(self._entries) * self.page_size > self.max_tokens
                   and len(self._entries) > 1):
                evict.extend(self._evict_lru_locked())
        if new_pages:
            self.pool.share(new_pages)
        if evict:
            self.pool.release(evict)
        return created

    def _evict_locked(self, key) -> list[int]:
        """Remove ``key`` and every descendant; returns their pages
        (caller releases OUTSIDE the lock — PagePool has its own).
        Iterative worklist, NOT recursion: one long conversation indexes
        as one parent-child chain, so a cache_len=32K/page_size=16 chain
        root has ~2K descendants — deeper than Python's recursion
        limit."""
        root = self._entries.get(key)
        if root is None:
            return []
        siblings = self._children.get(root.parent_eid)
        if siblings is not None:
            try:
                siblings.remove(key)
            except ValueError:
                pass
        pages: list[int] = []
        work = [key]
        while work:
            entry = self._entries.pop(work.pop(), None)
            if entry is None:
                continue
            pages.append(entry.page)
            work.extend(self._children.pop(entry.eid, []))
        return pages

    def _evict_lru_locked(self) -> list[int]:
        if not self._entries:
            return []
        key = next(iter(self._entries))
        return self._evict_locked(key)

    def evict_pages(self, n: int) -> int:
        """Reclaim hook for :class:`~.paged_kv.PagePool`: drop LRU
        entries until ``n`` index references were released (the pages
        become allocatable once no slot maps them). Returns how many
        references were dropped."""
        dropped: list[int] = []
        with self._lock:
            while len(dropped) < n and self._entries:
                dropped.extend(self._evict_lru_locked())
        if dropped:
            from llm_in_practise_tpu.obs.hbm import get_ledger

            get_ledger().note_reclaim("kv_pool.pages", "prefix_evict")
            self.pool.release(dropped)
        return len(dropped)

    def clear(self) -> None:
        with self._lock:
            pages = [e.page for e in self._entries.values()]
            self._entries.clear()
            self._children.clear()
        if pages:
            self.pool.release(pages)


def slice_cache_rows(prefill_cache, bucket: int, *, axis: int = 1) -> list:
    """Keep only the first ``bucket`` rows of each layer's KV buffers
    (drop the per-layer index — the entry carries the true length).
    ``axis`` is the sequence axis: 1 in the unrolled cache layout, 2 in
    the stacked scan layout (engine passes its ``_wax``)."""
    rows = []
    for layer in prefill_cache:
        rows.append({
            k: jax.lax.slice_in_dim(v, 0, bucket, axis=axis)
            for k, v in layer.items() if k != "index"
        })
    return rows

"""Checkpointing: portable msgpack tier + Orbax sharded/async tier."""

from llm_in_practise_tpu.ckpt.checkpoint import (  # noqa: F401
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
    save_named,
)
from llm_in_practise_tpu.ckpt.sharded import ShardedCheckpointer  # noqa: F401

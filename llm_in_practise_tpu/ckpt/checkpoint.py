"""Checkpoint save/restore — the reference's five tiers, one API.

Tiers covered (see SURVEY §5.4):
1. weights-only; 2. weights + vocab + config metadata; 3. full training state
(model + opt + step + best metric; RNG determinism via recorded seed/step);
rotation keep-last-N (``DeepSeekLike_spare_MoE_wikitext2.py:550-572``) and
``latest`` / ``best_model`` naming + auto-resume
(``temp/ddp_gpt_bpe_tokenizer_02.py:356-383,497-498``). Multi-host: only the
coordinator process writes (rank-0 gating parity).

Format: flax msgpack for the array pytree + a JSON sidecar for metadata
(config dicts, vocab, step). Works on any pytree, including sharded arrays
(gathered on save for these sizes; Orbax-style fully-sharded async save is a
later tier).
"""

from __future__ import annotations

import json
import os
import re

import jax
import numpy as np
from flax import serialization

from llm_in_practise_tpu.core import dist

_CKPT_RE = re.compile(r"^(?P<prefix>.+)_(?P<step>\d{8})\.msgpack$")


def _host_pytree(tree):
    """Bring a (possibly sharded) pytree fully addressable on host."""
    def fetch(x):
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        return x
    return jax.tree_util.tree_map(fetch, tree)


def save_checkpoint(
    ckpt_dir: str,
    tree,
    step: int,
    *,
    prefix: str = "ckpt",
    keep: int = 5,
    metadata: dict | None = None,
) -> str | None:
    """Write ``{prefix}_{step:08d}.msgpack`` (+ .json sidecar); rotate old."""
    if not dist.is_coordinator():
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"{prefix}_{step:08d}.msgpack")
    data = serialization.to_bytes(_host_pytree(tree))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    meta = dict(metadata or {})
    meta["step"] = int(step)
    with open(path.replace(".msgpack", ".json"), "w") as f:
        json.dump(meta, f, ensure_ascii=False, indent=1, default=str)
    _rotate(ckpt_dir, prefix, keep)
    return path


def save_named(ckpt_dir: str, tree, name: str, metadata: dict | None = None) -> str | None:
    """Unrotated named checkpoint, e.g. ``best_model`` / final weights."""
    if not dist.is_coordinator():
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"{name}.msgpack")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(_host_pytree(tree)))
    os.replace(tmp, path)
    if metadata is not None:
        with open(os.path.join(ckpt_dir, f"{name}.json"), "w") as f:
            json.dump(metadata, f, ensure_ascii=False, indent=1, default=str)
    return path


def latest_checkpoint(ckpt_dir: str, prefix: str = "ckpt") -> str | None:
    if not os.path.isdir(ckpt_dir):
        return None
    best = None
    for fname in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(fname)
        if m and m.group("prefix") == prefix:
            step = int(m.group("step"))
            if best is None or step > best[0]:
                best = (step, os.path.join(ckpt_dir, fname))
    return best[1] if best else None


def restore_checkpoint(path: str, target=None):
    """Restore pytree from ``path``. With ``target`` (a template pytree)
    returns the same structure; without, returns nested dicts of numpy arrays.
    Returns (tree, metadata_dict)."""
    with open(path, "rb") as f:
        data = f.read()
    tree = (
        serialization.from_bytes(target, data)
        if target is not None
        else serialization.msgpack_restore(data)
    )
    meta_path = path.replace(".msgpack", ".json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    return tree, meta


def _rotate(ckpt_dir: str, prefix: str, keep: int) -> None:
    entries = []
    for fname in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(fname)
        if m and m.group("prefix") == prefix:
            entries.append((int(m.group("step")), fname))
    entries.sort()
    for _, fname in entries[:-keep] if keep > 0 else []:
        os.remove(os.path.join(ckpt_dir, fname))
        sidecar = os.path.join(ckpt_dir, fname.replace(".msgpack", ".json"))
        if os.path.exists(sidecar):
            os.remove(sidecar)

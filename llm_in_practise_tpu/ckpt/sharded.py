"""Sharded/async checkpointing via Orbax — the distributed tier.

SURVEY §5.4 tier 4: the reference gathers full state to rank 0 (FSDP
``get_state_dict(full_state_dict=True)`` — ``fsdp_gpt_wikitext2.py:
357-367``) or saves DeepSpeed engine shards (``engine.save_checkpoint``).
The msgpack tier in :mod:`.checkpoint` is the gather-to-coordinator
equivalent; this module is the TPU-native distributed tier it points to:

- **Sharded**: every process writes its own param shards (no
  gather-to-rank-0 host OOM for 14B models on an FSDP mesh).
- **Async**: `save` returns once the on-device arrays are snapshotted;
  serialization overlaps the next training steps
  (``AsyncCheckpointer``).
- **Resume into placement**: restore takes the target sharded pytree and
  materializes each shard directly onto its devices.
- **Rotation + step tracking** via ``CheckpointManager`` (keep-last-N, the
  reference's rotating checkpoints — ``DeepSeekLike_spare_MoE…:550-572``).

Use for multi-host / large-model runs; the msgpack tier remains the
simple portable format for everything else.
"""

from __future__ import annotations

import jax


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


class ShardedCheckpointer:
    """Rotating, async, sharded train-state checkpoints."""

    def __init__(self, directory: str, *, keep: int = 5,
                 async_save: bool = True):
        ocp = _ocp()
        self._manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state) -> bool:
        """Snapshot ``state`` (any pytree of — possibly sharded — arrays)
        at ``step``; returns whether a save was performed. Async: returns
        as soon as device arrays are copied; disk I/O overlaps training."""
        ocp = _ocp()
        return self._manager.save(
            int(step), args=ocp.args.StandardSave(state))

    def restore(self, target, step: int | None = None):
        """Restore into ``target``'s structure *and sharding*: pass the
        freshly initialized (sharded) state; each process reads only its
        shards. ``step=None`` -> latest."""
        ocp = _ocp()
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array) else x,
            target,
        )
        return self._manager.restore(
            int(step), args=ocp.args.StandardRestore(abstract))

    def latest_step(self) -> int | None:
        return self._manager.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._manager.all_steps())

    def wait(self) -> None:
        """Block until pending async saves hit disk (call before exit)."""
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.close()

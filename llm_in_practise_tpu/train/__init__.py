"""Training: jitted steps, optimizers, schedules, and the Trainer loop."""

from llm_in_practise_tpu.train.step import (  # noqa: F401
    TrainState,
    create_train_state,
    make_eval_step,
    make_train_step,
)
from llm_in_practise_tpu.train.trainer import Trainer, TrainerConfig  # noqa: F401

"""Loss functions for LM training and SFT.

Next-token cross-entropy with optional label masking: the reference uses
``nn.CrossEntropyLoss`` over flattened logits for pretraining
(``minigpt2/model.py:104``) and ``ignore_index=-100`` label masking for SFT
(``Fine-Tuning/qwen3-8b-lora.py:66-103``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100


def cross_entropy(
    logits: jax.Array, labels: jax.Array, *, ignore_index: int = IGNORE_INDEX
) -> tuple[jax.Array, jax.Array]:
    """Mean token cross-entropy. Returns (loss, n_valid_tokens).

    logits: (..., vocab) float; labels: (...) int, ``ignore_index`` masked out.
    Computed in fp32 regardless of logits dtype.
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_ll = jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    n_valid = jnp.maximum(valid.sum(), 1)
    loss = -(token_ll * valid).sum() / n_valid
    return loss, n_valid


def perplexity(mean_nll: jax.Array) -> jax.Array:
    return jnp.exp(mean_nll)

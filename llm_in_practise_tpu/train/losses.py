"""Loss functions for LM training and SFT.

Next-token cross-entropy with optional label masking: the reference uses
``nn.CrossEntropyLoss`` over flattened logits for pretraining
(``minigpt2/model.py:104``) and ``ignore_index=-100`` label masking for SFT
(``Fine-Tuning/qwen3-8b-lora.py:66-103``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100


def cross_entropy(
    logits: jax.Array, labels: jax.Array, *, ignore_index: int = IGNORE_INDEX
) -> tuple[jax.Array, jax.Array]:
    """Mean token cross-entropy. Returns (loss, n_valid_tokens).

    logits: (..., vocab) float; labels: (...) int, ``ignore_index`` masked out.
    Computed in fp32 regardless of logits dtype.
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_ll = jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    n_valid = jnp.maximum(valid.sum(), 1)
    loss = -(token_ll * valid).sum() / n_valid
    return loss, n_valid


def fused_linear_cross_entropy(
    hidden: jax.Array,
    weight: jax.Array,
    labels: jax.Array,
    *,
    transpose_weight: bool = False,
    bias: jax.Array | None = None,
    ignore_index: int = IGNORE_INDEX,
    chunk: int = 4096,
    vocab_chunk: int | None = None,
    compute_dtype=jnp.bfloat16,
) -> tuple[jax.Array, jax.Array]:
    """LM-head projection fused into the loss — full logits never exist.

    The naive path materializes ``(batch, seq, vocab)`` f32 logits twice
    (log-softmax + its backward): at GPTLike scale that is 16 GB for one
    batch-512 step — larger than a v5e chip's whole HBM. Here tokens are
    processed in ``chunk``-sized slabs under a ``lax.scan``: each slab runs
    ``hidden_chunk @ weight`` on the MXU (bf16 in, f32 accumulation), reduces
    to per-token NLL, and is rematerialized in the backward
    (``jax.checkpoint``), so peak vocab-axis memory is ``chunk × vocab``
    regardless of batch. Same role as the reference's fused CE in its CUDA
    stack (torch ``nn.CrossEntropyLoss`` over flattened logits,
    ``minigpt2/model.py:104``) but restructured for HBM, not translated.

    ``vocab_chunk`` additionally tiles the VOCAB axis with a streaming
    (online-softmax) logsumexp, so no single dot ever spans the full
    vocabulary — both a memory bound (``chunk × vocab_chunk`` peak) and a
    compiler bound: very wide heads (Qwen3's 151936) have been observed
    to stall AOT TPU compilation when emitted as one dot. The actual
    tile width is the nearest divisor of the vocab size.

    hidden: (..., dim); weight: (dim, vocab), or (vocab, dim) with
    ``transpose_weight=True`` (tied-embedding ``attend`` layout);
    labels: (...) int with ``ignore_index`` masked out.
    Returns (mean_nll, n_valid_tokens).
    """
    dim = hidden.shape[-1]
    flat_h = hidden.reshape(-1, dim)
    flat_l = labels.reshape(-1)
    n_tok = flat_h.shape[0]
    chunk = min(chunk, n_tok)
    pad = -n_tok % chunk
    if pad:
        flat_h = jnp.concatenate(
            [flat_h, jnp.zeros((pad, dim), flat_h.dtype)])
        flat_l = jnp.concatenate(
            [flat_l, jnp.full((pad,), ignore_index, flat_l.dtype)])
    n_chunks = flat_h.shape[0] // chunk
    h_c = flat_h.reshape(n_chunks, chunk, dim)
    l_c = flat_l.reshape(n_chunks, chunk)

    w = weight.astype(compute_dtype)

    vocab = weight.shape[0] if transpose_weight else weight.shape[1]
    vocab_axis = 0 if transpose_weight else 1
    n_vtiles = 1
    if vocab_chunk is not None and vocab > vocab_chunk:
        # smallest tile count that divides the vocab exactly (padding the
        # weight would copy it) — but only within 4x of the requested
        # granularity: a prime-ish vocab would otherwise "tile" at width
        # 1 and turn the loss into thousands of MXU-hostile slivers.
        # No acceptable divisor -> untiled.
        target = -(-vocab // vocab_chunk)
        n_vtiles = next(
            (c for c in range(target, min(4 * target, vocab) + 1)
             if vocab % c == 0), 1)
    vtile = vocab // n_vtiles

    def _tile_logits(w, b, hc, start):
        wt = jax.lax.dynamic_slice_in_dim(w, start, vtile, axis=vocab_axis)
        contract = ((1,), (1,)) if transpose_weight else ((1,), (0,))
        logits = jax.lax.dot_general(
            hc, wt, (contract, ((), ())),
            preferred_element_type=jnp.float32,
        )
        if b is not None:
            logits = logits + jax.lax.dynamic_slice_in_dim(
                b, start, vtile, axis=0).astype(jnp.float32)
        return logits

    @jax.checkpoint
    def chunk_nll(w, b, hc, lb):
        hc = hc.astype(compute_dtype)
        valid = lb != ignore_index
        safe = jnp.where(valid, lb, 0)
        if n_vtiles == 1:
            logits = _tile_logits(w, b, hc, 0)
            lse = jax.nn.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
            return ((lse - tgt) * valid).sum(), valid.sum()

        # streaming logsumexp over vocab tiles (online softmax): running
        # (max, sumexp) per token plus the target logit picked from the
        # tile that owns it — never a full-vocab dot
        t = hc.shape[0]
        init = (jnp.full((t,), -jnp.inf, jnp.float32),   # running max
                jnp.zeros((t,), jnp.float32),            # running sumexp
                jnp.zeros((t,), jnp.float32))            # target logit

        # checkpointed per tile: without this, the inner scan's VJP stacks
        # every tile's (chunk, vtile) logits residuals and peak backward
        # memory is chunk x vocab again — the bound this tiling exists for
        @jax.checkpoint
        def tile_stats(w, b, hc, i):
            logits = _tile_logits(w, b, hc, i * vtile)
            tile_max = jnp.max(logits, axis=-1)
            sumexp = jnp.exp(logits - tile_max[:, None]).sum(-1)
            local = safe - i * vtile
            in_tile = (local >= 0) & (local < vtile)
            picked = jnp.take_along_axis(
                logits, jnp.clip(local, 0, vtile - 1)[:, None], axis=1
            )[:, 0]
            return tile_max, sumexp, in_tile, picked

        def vbody(carry, i):
            m, s, tgt = carry
            tile_max, sumexp, in_tile, picked = tile_stats(w, b, hc, i)
            new_m = jnp.maximum(m, tile_max)
            s = (s * jnp.exp(m - new_m)
                 + sumexp * jnp.exp(tile_max - new_m))
            tgt = jnp.where(in_tile, picked, tgt)
            return (new_m, s, tgt), None

        (m, s, tgt), _ = jax.lax.scan(vbody, init, jnp.arange(n_vtiles))
        lse = m + jnp.log(s)
        return ((lse - tgt) * valid).sum(), valid.sum()

    def body(carry, xs):
        hc, lb = xs
        nll, nv = chunk_nll(w, bias, hc, lb)
        return (carry[0] + nll, carry[1] + nv), None

    (total, n_valid), _ = jax.lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        (h_c, l_c),
    )
    n_valid = jnp.maximum(n_valid, 1)
    return total / n_valid, n_valid


def perplexity(mean_nll: jax.Array) -> jax.Array:
    return jnp.exp(mean_nll)

"""LR schedules mirroring the reference's set.

- constant (MiniGPT — ``minigpt2/model.py:89-94``)
- cosine with warmup (``temp/ddp_gpt_bpe_tokenizer_02.py`` cosine; HF Trainer
  ``lr_scheduler_type="cosine"`` in every Fine-Tuning script)
- StepLR-style step decay (``DeepSeekLike_spare_MoE_wikitext2.py`` StepLR)
"""

from __future__ import annotations

import optax


def constant(lr: float) -> optax.Schedule:
    return optax.constant_schedule(lr)


def cosine_with_warmup(
    lr: float, total_steps: int, warmup_steps: int = 0, final_scale: float = 0.0
) -> optax.Schedule:
    if total_steps <= 0:
        raise ValueError("cosine schedule requires total_steps > 0")
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0 if warmup_steps else lr,
        peak_value=lr,
        warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1),
        end_value=lr * final_scale,
    )


def step_decay(lr: float, step_size: int, gamma: float = 0.5) -> optax.Schedule:
    def schedule(count):
        return lr * gamma ** (count // step_size)

    return schedule


def by_name(name: str, lr: float, *, total_steps: int = 0, warmup_steps: int = 0,
            step_size: int = 1000, gamma: float = 0.5) -> optax.Schedule:
    if name == "constant":
        return constant(lr)
    if name == "cosine":
        return cosine_with_warmup(lr, total_steps, warmup_steps)
    if name == "step":
        return step_decay(lr, step_size, gamma)
    raise ValueError(f"unknown schedule {name!r}")

"""Trainer: the reference's full-featured training loops as one class.

Absorbs every loop variant in the reference (SURVEY §2.4):

- plain epoch loop (``GPTLike_wikitext2.py:143-175``),
- DDP loop with ``sampler.set_epoch`` + rank-0 saves
  (``ddp_basics/ddp_gpt_wikitext2.py:289-332``),
- the full-featured loop: grad accumulation, cosine LR, distributed eval,
  best/latest checkpoints with RNG state, early stopping, per-rank logs
  (``temp/ddp_gpt_bpe_tokenizer_02.py:385-557``),
- DeepSpeed engine loop (``DeepSpeed-GPTLike-ZeRO-1.py:275-363``) — here the
  "engine" is a Strategy (NamedSharding placement) + one jitted step,
- HF ``Trainer``/``TrainingArguments`` surface (``HF_Basics/trainer_demo.py:
  86-127``, all ``Fine-Tuning/*.py``) — ``TrainerConfig`` is the
  TrainingArguments analog, with DeepSpeed-JSON ``"auto"``/precedence
  semantics via :mod:`llm_in_practise_tpu.core.config`.

TPU-first mechanics: the model is initialized directly into its sharded
layout (no replicate-then-shard), every strategy runs the identical jitted
step, batches are device_put against the mesh's batch sharding, and eval
reduction is a compiled mean — no ``dist.reduce``/``broadcast`` calls.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from llm_in_practise_tpu.ckpt import checkpoint as ckpt_lib
from llm_in_practise_tpu.core import config as config_lib
from llm_in_practise_tpu.core import mesh as mesh_lib
from llm_in_practise_tpu.data.loader import batch_iterator
from llm_in_practise_tpu.obs import Throughput, EpochTimer, RollingMean, get_logger
from llm_in_practise_tpu.parallel import strategy as strategy_lib
from llm_in_practise_tpu.train import optim, schedules
from llm_in_practise_tpu.train.step import make_eval_step, make_train_step

AUTO = config_lib.AUTO


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    """TrainingArguments analog; JSON-loadable with file>CLI precedence."""

    # optimizer / schedule
    lr: float = 3e-4
    weight_decay: float = 0.01
    clip_norm: float | None = 1.0
    grad_accum_steps: int = 1
    schedule: str = "constant"          # constant | cosine | step
    warmup_steps: int = 0
    total_steps: int | str = AUTO       # "auto" -> epochs * steps_per_epoch
    # loop
    epochs: int = 1
    batch_size: int = 8
    eval_every_steps: int = 0           # 0 = once per epoch
    log_every_steps: int = 50
    early_stop_patience: int = 0        # evals without improvement; 0 = off
    seed: int = 42
    # checkpointing (tier-3: full state incl. opt + RNG, rotation, best)
    ckpt_dir: str | None = None
    save_every_steps: int = 0           # 0 = once per epoch
    keep_checkpoints: int = 5
    resume: bool = True
    # parallelism
    strategy: str = "ddp"               # name in parallel.strategy.STRATEGIES
    mesh_data: int = -1
    mesh_fsdp: int = 1
    mesh_model: int = 1
    mesh_expert: int = 1
    mesh_seq: int = 1
    # Opt-in for a pinned mesh smaller than the host's device count (debug
    # meshes). Off by default so a stale config on bigger hardware fails
    # loudly instead of silently training on a fraction of the chips.
    allow_device_subset: bool = False

    @classmethod
    def from_sources(cls, *, config_file=None, cli_namespace=None, **auto):
        return config_lib.load(
            cls, config_file=config_file, cli_namespace=cli_namespace,
            auto_resolvers=auto or None,
        )


class Trainer:
    """``Trainer(model, cfg).train((x, y), eval_data=(xv, yv))``.

    ``train_data`` / ``eval_data``: tuples of aligned host arrays (inputs,
    targets), batched internally with epoch-seeded shuffling
    (``DistributedSampler.set_epoch`` parity), or any callable
    ``epoch -> iterable of (x, y)`` for custom pipelines.
    """

    def __init__(
        self,
        model,
        cfg: TrainerConfig,
        *,
        loss_fn: Callable | None = None,
        eval_loss_fn: Callable | None = None,
        strategy: strategy_lib.Strategy | None = None,
        metadata: dict | None = None,
        callbacks: Iterable[Any] = (),
    ):
        self.model = model
        self.cfg = cfg
        self.loss_fn = loss_fn
        self.eval_loss_fn = eval_loss_fn
        self.metadata = metadata or {}
        self.callbacks = list(callbacks)
        self.log = get_logger("trainer")

        self.strategy = strategy or self._build_strategy()
        self.mesh = self.strategy.build_mesh(allow_subset=cfg.allow_device_subset)
        if self.mesh.devices.size < len(jax.devices()):
            self.log.warning(
                "mesh uses %d of %d devices", self.mesh.devices.size,
                len(jax.devices()),
            )
        self.train_step = make_train_step(
            loss_fn=loss_fn, offload_opt=self.strategy.offload_opt
        )
        self.eval_step = make_eval_step(loss_fn=eval_loss_fn)
        self.state = None
        self.history: list[dict] = []

    def _build_strategy(self) -> strategy_lib.Strategy:
        c = self.cfg
        spec = mesh_lib.MeshSpec(
            data=c.mesh_data, fsdp=c.mesh_fsdp, model=c.mesh_model,
            expert=c.mesh_expert, seq=c.mesh_seq,
        )
        base = strategy_lib.by_name(c.strategy)
        return dataclasses.replace(base, mesh_spec=spec)

    # --- state ----------------------------------------------------------------

    def _make_tx(self, steps_per_epoch: int) -> optax.GradientTransformation:
        c = self.cfg
        total = c.total_steps
        if total == AUTO:
            if steps_per_epoch == 0 and c.schedule != "constant":
                raise ValueError(
                    f"schedule {c.schedule!r} needs total_steps, which cannot "
                    "be inferred from a callable data pipeline — set "
                    "TrainerConfig.total_steps explicitly"
                )
            total = max(1, c.epochs * steps_per_epoch // max(1, c.grad_accum_steps))
        lr = schedules.by_name(
            c.schedule, c.lr, total_steps=int(total), warmup_steps=c.warmup_steps
        )
        return optim.adamw(
            lr, weight_decay=c.weight_decay, clip_norm=c.clip_norm,
            grad_accum_steps=c.grad_accum_steps,
        )

    def _init_state(self, example_input, steps_per_epoch: int):
        tx = self._make_tx(steps_per_epoch)
        state = strategy_lib.shard_init(
            self.model, self.strategy, self.mesh, tx,
            jax.random.PRNGKey(self.cfg.seed), jnp.asarray(example_input),
        )
        if self.cfg.resume and self.cfg.ckpt_dir:
            latest = ckpt_lib.latest_checkpoint(self.cfg.ckpt_dir)
            if latest:
                host, meta = ckpt_lib.restore_checkpoint(latest, target=jax.device_get(state))
                shardings = jax.tree_util.tree_map(lambda x: x.sharding, state)
                state = jax.device_put(host, shardings)
                self.log.info("resumed from %s (step %d)", latest, int(state.step))
        return state

    # --- loops ----------------------------------------------------------------

    def _batches(self, data, epoch: int, eval_mode: bool = False):
        if callable(data):
            yield from data(epoch)
            return
        yield from batch_iterator(
            tuple(np.asarray(a) for a in data),
            # eval scores every sample (incl. the tail batch); train drops
            # the ragged tail to keep step shapes static.
            min(self.cfg.batch_size, len(data[0])) if eval_mode else self.cfg.batch_size,
            shuffle=not eval_mode,
            drop_last=not eval_mode,
            seed=self.cfg.seed,
            epoch=epoch,
        )

    def evaluate(self, eval_data) -> float:
        """Weighted mean eval loss; compiled reduction replaces the
        reference's ``dist.reduce``+``broadcast`` (``temp/…_02.py:326-339``)."""
        total, count = 0.0, 0.0
        sharding = mesh_lib.batch_sharding(self.mesh)
        # The ragged tail batch (eval scores every sample) usually won't
        # divide over data×fsdp — replicate it instead of crashing the
        # device_put; it's one small batch, once per eval.
        n_shards = self.mesh.shape["data"] * self.mesh.shape["fsdp"]
        replicated = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec()
        )
        with self.mesh:
            for batch in self._batches(eval_data, epoch=0, eval_mode=True):
                arrays = _as_arrays(batch)
                placement = (
                    sharding if arrays[0].shape[0] % n_shards == 0 else replicated
                )
                batch = jax.device_put(arrays, placement)
                m = self.eval_step(self.state, batch)
                n = float(m.get("n_valid", batch[0].size))
                total += float(m["loss"]) * n
                count += n
        return total / max(count, 1.0)

    def train(self, train_data, eval_data=None) -> list[dict]:
        c = self.cfg
        # Peek one batch for init shapes, then stitch it back so a one-shot
        # callable pipeline doesn't lose its first batch (and an array
        # pipeline isn't rebuilt twice for epoch 0).
        first_iter = iter(self._batches(train_data, epoch=0))
        first = next(first_iter)
        first_iter = itertools.chain([first], first_iter)
        steps_per_epoch = (
            len(train_data[0]) // c.batch_size if not callable(train_data) else 0
        )
        if self.state is None:
            self.state = self._init_state(first[0][:1], steps_per_epoch)

        best = float("inf")
        evals_since_best = 0
        rolling = RollingMean(50)
        meter = Throughput()
        sharding = mesh_lib.batch_sharding(self.mesh)
        stop = False

        start_epoch = 0
        if steps_per_epoch:
            start_epoch = int(self.state.step) // steps_per_epoch

        for epoch in range(start_epoch, c.epochs):
            timer = EpochTimer()
            epoch_losses = []
            batches = (
                first_iter if epoch == 0 and first_iter is not None
                else self._batches(train_data, epoch=epoch)
            )
            with self.mesh:
                for batch in batches:
                    batch = jax.device_put(_as_arrays(batch), sharding)
                    self.state, metrics = self.train_step(self.state, batch)
                    step = int(self.state.step)
                    loss = float(metrics["loss"])
                    epoch_losses.append(loss)
                    rolling.update(loss)
                    meter.step(int(np.prod(batch[0].shape)))

                    if c.log_every_steps and step % c.log_every_steps == 0:
                        self.log.info(
                            "epoch %d step %d | loss %.4f (last50 %.4f) | "
                            "%.0f tok/s",
                            epoch + 1, step, loss, rolling.mean,
                            meter.tokens_per_sec,
                        )
                    for cb in self.callbacks:
                        if hasattr(cb, "on_step"):
                            cb.on_step(self, step, metrics)
                    if c.eval_every_steps and step % c.eval_every_steps == 0 \
                            and eval_data is not None:
                        best, evals_since_best, stop = self._eval_and_track(
                            eval_data, best, evals_since_best
                        )
                        if stop:
                            break
                    if c.save_every_steps and step % c.save_every_steps == 0:
                        self._save(step)
            # (a mid-epoch early stop falls through: the epoch record,
            # callbacks, and final checkpoint below must still run)

            record = {
                "epoch": epoch + 1,
                "step": int(self.state.step),
                "train_loss": float(np.mean(epoch_losses)) if epoch_losses else None,
                "time_s": timer.elapsed(),
                "tokens_per_sec": meter.tokens_per_sec,
            }
            if eval_data is not None and not c.eval_every_steps:
                best, evals_since_best, stop = self._eval_and_track(
                    eval_data, best, evals_since_best
                )
                record["eval_loss"] = self._last_eval
            self.history.append(record)
            self.log.info(
                "epoch %d/%d done | train %.4f%s | %.1fs",
                epoch + 1, c.epochs, record["train_loss"] or float("nan"),
                f" | eval {record.get('eval_loss'):.4f}"
                if record.get("eval_loss") is not None else "",
                record["time_s"],
            )
            for cb in self.callbacks:
                if hasattr(cb, "on_epoch"):
                    cb.on_epoch(self, epoch, record)
            if not c.save_every_steps:
                self._save(int(self.state.step))
            if stop:
                self.log.info("early stopping (patience %d)", c.early_stop_patience)
                break
        return self.history

    _last_eval: float | None = None

    def _eval_and_track(self, eval_data, best, since_best):
        loss = self.evaluate(eval_data)
        self._last_eval = loss
        improved = loss < best
        if improved:
            best = loss
            since_best = 0
            if self.cfg.ckpt_dir:
                ckpt_lib.save_named(
                    self.cfg.ckpt_dir, jax.device_get(self.state.params),
                    "best_model",
                    metadata={**self.metadata, "eval_loss": loss,
                              "step": int(self.state.step)},
                )
        else:
            since_best += 1
        stop = (
            self.cfg.early_stop_patience > 0
            and since_best >= self.cfg.early_stop_patience
        )
        return best, since_best, stop

    def _save(self, step: int):
        if not self.cfg.ckpt_dir:
            return
        ckpt_lib.save_checkpoint(
            self.cfg.ckpt_dir, self.state, step,
            keep=self.cfg.keep_checkpoints,
            metadata={**self.metadata, "config": config_lib.to_dict(self.cfg)},
        )


def _as_arrays(batch):
    return tuple(jnp.asarray(a) for a in batch)

"""Jitted train/eval steps — the hot loop, compiled once.

The reference's hot loop (forward → CE loss → backward → clip → step —
``minigpt2/model.py:99-112``, ``ddp_gpt_wikitext2.py:289-310``) becomes a
single jitted function over a TrainState; under a sharded mesh XLA compiles
the gradient all-reduce / reduce-scatter into the same program (no DDP hooks,
no engine.backward).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from llm_in_practise_tpu.train.losses import (
    cross_entropy,
    fused_linear_cross_entropy,
)


class TrainState(train_state.TrainState):
    """flax TrainState + dropout rng seed folded per step."""

    rng: jax.Array = None


def create_train_state(model, params, tx, rng) -> TrainState:
    return TrainState.create(apply_fn=model.apply, params=params, tx=tx, rng=rng)


def head_weight(params) -> tuple[jax.Array, bool, jax.Array | None]:
    """(LM-head weight, transpose?, bias) from a params tree —
    ``lm_head/kernel`` (dim, vocab) when untied, else the tied
    ``tok_embed/embedding`` (vocab, dim). Shared naming across every
    in-tree model family."""
    if "lm_head" in params:
        return (params["lm_head"]["kernel"], False,
                params["lm_head"].get("bias"))
    return params["tok_embed"]["embedding"], True, None


def make_fused_ce_loss(*, chunk: int = 4096, vocab_chunk: int | None = None,
                       compute_dtype="bfloat16") -> Callable:
    """Next-token loss with the LM-head projection fused into the CE
    (:func:`..train.losses.fused_linear_cross_entropy`) — the full
    ``(batch, seq, vocab)`` logits tensor never exists, so large-batch /
    large-vocab steps fit in HBM. Pass as ``make_train_step(loss_fn=...)``."""

    def loss(params, apply_fn, batch, rng):
        x, y = batch
        hidden = apply_fn(
            {"params": params}, x, deterministic=False,
            rngs={"dropout": rng}, return_hidden=True,
        )
        w, transpose, bias = head_weight(params)
        loss_val, n_valid = fused_linear_cross_entropy(
            hidden, w, y, transpose_weight=transpose, bias=bias,
            chunk=chunk, vocab_chunk=vocab_chunk,
            compute_dtype=jnp.dtype(compute_dtype),
        )
        return loss_val, {"n_valid": n_valid}

    return loss


def make_train_step(
    *,
    loss_fn: Callable | None = None,
    donate: bool = True,
    offload_opt: bool = False,
) -> Callable[[TrainState, tuple[jax.Array, jax.Array]], tuple[TrainState, dict]]:
    """Build the jitted step. ``loss_fn(params, apply_fn, batch, rng)`` may be
    overridden (e.g. MoE aux losses); default is next-token cross-entropy.

    ``offload_opt`` (ZeRO-Offload parity): the optimizer state arrives in
    pinned host memory, is streamed to device inside the compiled step, and
    is parked back on the host after — DeepSpeed's CPUAdam data motion with
    the transfer schedule owned by XLA.
    """

    def default_loss(params, apply_fn, batch, rng):
        x, y = batch
        logits = apply_fn(
            {"params": params}, x, deterministic=False, rngs={"dropout": rng}
        )
        loss, n_valid = cross_entropy(logits, y)
        return loss, {"n_valid": n_valid}

    loss_fn = loss_fn or default_loss

    def step(state: TrainState, batch) -> tuple[TrainState, dict[str, Any]]:
        if offload_opt:
            from jax.memory import Space

            state = state.replace(
                opt_state=jax.device_put(state.opt_state, Space.Device)
            )
        rng = jax.random.fold_in(state.rng, state.step)
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, state.apply_fn, batch, rng
        )
        new_state = state.apply_gradients(grads=grads)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads), **aux}
        return new_state, metrics

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())
    if not offload_opt:
        return jitted

    def offloaded_step(state, batch):
        host_shardings = jax.tree_util.tree_map(
            lambda x: x.sharding, state.opt_state
        )
        new_state, metrics = jitted(state, batch)
        new_state = new_state.replace(
            opt_state=jax.device_put(new_state.opt_state, host_shardings)
        )
        return new_state, metrics

    return offloaded_step


def make_eval_step(*, loss_fn: Callable | None = None):
    def default_loss(params, apply_fn, batch):
        x, y = batch
        logits = apply_fn({"params": params}, x, deterministic=True)
        loss, n_valid = cross_entropy(logits, y)
        return loss, n_valid

    loss_fn = loss_fn or default_loss

    def step(state: TrainState, batch):
        loss, n_valid = loss_fn(state.params, state.apply_fn, batch)
        return {"loss": loss, "n_valid": n_valid}

    return jax.jit(step)

"""8-bit (blockwise-quantized) Adam states — ``paged_adamw_8bit`` parity.

The reference fine-tunes with bitsandbytes' 8-bit paged AdamW
(``optim="paged_adamw_8bit"`` — ``Fine-Tuning/qwen3-14b-qlora-dist-
deepspeed.py:151``), whose CUDA kernels keep Adam's m/v moments in int8 with
per-block scales, cutting optimizer memory 4×. Here the same storage scheme
is a pure optax transform: moments live as int8 codes + f32 absmax scales
(block 256), dequantized/requantized inside the jitted update — XLA fuses
the codec into the update arithmetic, so there is no separate kernel to
write. The "paged" half (spill to host RAM under pressure) is the
``pinned_host`` memory-kind placement in
:mod:`llm_in_practise_tpu.parallel.strategy` (ZeRO-Offload parity).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import chex
import jax
import jax.numpy as jnp
import optax

BLOCK = 256


@dataclasses.dataclass
class Q8Moment:
    """One blockwise-int8 tensor (codes + per-block absmax scales)."""

    codes: jax.Array   # (n_pad,) int8
    scales: jax.Array  # (n_blocks,) f32
    shape: tuple       # original shape — static pytree aux, not a leaf

    @property
    def nbytes(self) -> int:
        return self.codes.nbytes + self.scales.nbytes


jax.tree_util.register_pytree_node(
    Q8Moment,
    lambda m: ((m.codes, m.scales), m.shape),
    lambda shape, leaves: Q8Moment(*leaves, shape=shape),
)

# msgpack checkpointing (shape is rebuilt from the restore target).
from flax import serialization as _ser  # noqa: E402

_ser.register_serialization_state(
    Q8Moment,
    lambda m: {"codes": m.codes, "scales": m.scales},
    lambda m, sd: Q8Moment(sd["codes"], sd["scales"], m.shape),
)


def q8_encode(x: jax.Array) -> Q8Moment:
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.size) % BLOCK
    if pad:
        flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, BLOCK)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.maximum(absmax / 127.0, 1e-12)
    codes = jnp.round(blocks / scales[:, None]).astype(jnp.int8).reshape(-1)
    return Q8Moment(codes, scales, shape)


def q8_decode(m: Q8Moment) -> jax.Array:
    n = 1
    for d in m.shape:
        n *= d
    flat = (
        m.codes.astype(jnp.float32).reshape(-1, BLOCK) * m.scales[:, None]
    ).reshape(-1)[:n]
    return flat.reshape(m.shape)


class ScaleByAdamQ8State(NamedTuple):
    count: chex.Array
    mu: chex.ArrayTree   # pytree of Q8Moment
    nu: chex.ArrayTree


def scale_by_adam_q8(
    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8
) -> optax.GradientTransformation:
    """Adam scaling with int8 moment storage (bnb 8-bit optimizer parity)."""

    def init_fn(params):
        z = jax.tree_util.tree_map(lambda p: q8_encode(jnp.zeros_like(p, jnp.float32)), params)
        z2 = jax.tree_util.tree_map(lambda p: q8_encode(jnp.zeros_like(p, jnp.float32)), params)
        return ScaleByAdamQ8State(jnp.zeros([], jnp.int32), z, z2)

    def update_fn(updates, state, params=None):
        del params
        count = optax.safe_int32_increment(state.count)
        # Q8Moment leaves are themselves pytrees, so a 3-tree tree_map would
        # mismatch structures — flatten against the updates' treedef instead.
        flat_u, treedef = jax.tree_util.tree_flatten(updates)
        flat_mu = treedef.flatten_up_to(state.mu)
        flat_nu = treedef.flatten_up_to(state.nu)
        new_m, new_n, out = [], [], []
        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** count.astype(jnp.float32)
        for g, mq, nq in zip(flat_u, flat_mu, flat_nu):
            m = b1 * q8_decode(mq) + (1 - b1) * g.astype(jnp.float32)
            # nu is stored in sqrt-domain: linear int8 on sqrt(nu) gives the
            # SAME relative truncation threshold as m (absmax/127 on |g|),
            # so an element can never keep a nonzero m while its nu rounds
            # to zero — the m_hat/eps explosion mode of naive int8 moments.
            n = b2 * jnp.square(q8_decode(nq)) \
                + (1 - b2) * jnp.square(g.astype(jnp.float32))
            v_hat = n / bc2
            upd = jnp.where(
                v_hat > 0.0,
                (m / bc1) / (jnp.sqrt(v_hat) + eps),
                0.0,  # nu truncated -> gradient history negligible, skip
            )
            new_m.append(q8_encode(m))
            new_n.append(q8_encode(jnp.sqrt(n)))
            out.append(upd.astype(g.dtype))
        return (
            jax.tree_util.tree_unflatten(treedef, out),
            ScaleByAdamQ8State(
                count,
                jax.tree_util.tree_unflatten(treedef, new_m),
                jax.tree_util.tree_unflatten(treedef, new_n),
            ),
        )

    return optax.GradientTransformation(init_fn, update_fn)


def adamw_8bit(
    learning_rate,
    *,
    weight_decay: float = 0.01,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    clip_norm: float | None = 1.0,
    grad_accum_steps: int = 1,
) -> optax.GradientTransformation:
    """AdamW with 8-bit moments: [clip] -> adam_q8 -> wd -> lr [-> accum]."""
    parts = []
    if clip_norm is not None:
        parts.append(optax.clip_by_global_norm(clip_norm))
    parts += [
        scale_by_adam_q8(b1, b2, eps),
        optax.add_decayed_weights(weight_decay),
        optax.scale_by_learning_rate(learning_rate),
    ]
    tx = optax.chain(*parts)
    if grad_accum_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=grad_accum_steps)
    return tx


def moment_nbytes(opt_state) -> int:
    """Bytes held by quantized moments (for the 4x-savings assertion)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(opt_state):
        total += getattr(leaf, "nbytes", 0)
    return total

"""Elastic training supervisor — restart-on-failure with checkpoint resume.

The reference has no elastic training (SURVEY §2.3: torchrun elastic
unused); its recovery story is "restart the job and resume from
``latest_checkpoint.pt``" (``temp/ddp_gpt_bpe_tokenizer_02.py:497-498``),
done by hand. This module automates exactly that loop, the way
torchrun's ``--max-restarts`` does for the reference's stack:

- :func:`supervise` relaunches a training command on non-zero exit with
  exponential backoff, up to ``max_restarts`` times. Because every in-tree
  trainer resumes from its checkpoint directory
  (``TrainerConfig.resume``), a crash costs at most
  ``save_every_steps`` of work.
- A restart *budget window*: exits spaced further apart than
  ``window_s`` reset the restart counter (long-running jobs shouldn't die
  because they hit N transient faults over a week).

Use: ``python -m llm_in_practise_tpu.train.elastic --max-restarts 3 --
python examples/dist_train.py --config ds.json``.
"""

from __future__ import annotations

import subprocess
import sys
import time


def supervise(
    argv: list[str],
    *,
    max_restarts: int = 3,
    backoff_s: float = 5.0,
    window_s: float = 3600.0,
    _run=subprocess.call,
    _sleep=time.sleep,
    _clock=time.monotonic,
) -> int:
    """Run ``argv``; restart on failure. Returns the final exit code."""
    restarts = 0
    window_start = _clock()
    attempt = 0
    while True:
        attempt += 1
        start = _clock()
        code = _run(argv)
        if code == 0:
            return 0
        now = _clock()
        if now - window_start > window_s:
            restarts = 0          # healthy for a full window: reset budget
            window_start = now    # a fresh window starts at this failure —
            # anchoring at the (old) run start would grant a second free
            # reset to an immediate crash after one long run
        if restarts >= max_restarts:
            print(f"[elastic] giving up after {restarts} restarts "
                  f"(exit {code})", file=sys.stderr)
            return code
        restarts += 1
        delay = backoff_s * 2 ** (restarts - 1)
        print(f"[elastic] attempt {attempt} exited {code}; restart "
              f"{restarts}/{max_restarts} in {delay:.0f}s", file=sys.stderr)
        _sleep(delay)


def main() -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="restart-on-failure supervisor for training commands")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--backoff", type=float, default=5.0)
    p.add_argument("--window", type=float, default=3600.0)
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- then the training command")
    args = p.parse_args()
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no command given (usage: ... -- python train.py ...)")
    return supervise(cmd, max_restarts=args.max_restarts,
                     backoff_s=args.backoff, window_s=args.window)


if __name__ == "__main__":
    raise SystemExit(main())

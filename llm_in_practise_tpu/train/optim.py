"""Optimizers: AdamW + grad clipping + accumulation, as one optax chain.

Covers the reference's optimizer surface: AdamW with weight decay
(``minigpt2/model.py:89-94``), ``clip_grad_norm_(1.0)`` (``:108``), gradient
accumulation (``temp/ddp_gpt_bpe_tokenizer_02.py:402-418``), and the
DeepSpeed/HF fused-Adam settings expressed as plain optax. Quantized (8-bit)
optimizer state — the ``paged_adamw_8bit`` analog — lives in
:mod:`llm_in_practise_tpu.train.quant_opt`.
"""

from __future__ import annotations

import optax


def adamw(
    learning_rate,
    *,
    weight_decay: float = 0.01,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    clip_norm: float | None = 1.0,
    grad_accum_steps: int = 1,
) -> optax.GradientTransformation:
    """AdamW chain: [clip] -> adamw [-> accumulate]."""
    parts = []
    if clip_norm is not None:
        parts.append(optax.clip_by_global_norm(clip_norm))
    parts.append(
        optax.adamw(learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay)
    )
    tx = optax.chain(*parts)
    if grad_accum_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=grad_accum_steps)
    return tx


def sgd(learning_rate, *, momentum: float = 0.0, clip_norm: float | None = None):
    parts = []
    if clip_norm is not None:
        parts.append(optax.clip_by_global_norm(clip_norm))
    parts.append(optax.sgd(learning_rate, momentum=momentum))
    return optax.chain(*parts)

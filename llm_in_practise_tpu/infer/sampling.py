"""Token sampling: greedy / temperature / top-k / top-p.

Parity with the reference's decode styles: greedy argmax
(``llm-demo/minigpt/generate.py:14-28``), temperature + multinomial
(``minigpt2/test_model.py:35-57``), top-k/top-p HF ``generate`` kwargs
(``Scripts/inference/04-*.py``). All jittable (static shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_token(
    rng: jax.Array,
    logits: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    greedy: bool = False,
) -> jax.Array:
    """Sample next token ids from (..., vocab) logits."""
    if greedy or temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    if top_p is not None and 0.0 < top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative prob exceeds top_p (always keep top-1)
        cutoff_mask = cum - probs > top_p
        cutoff_logit = jnp.min(
            jnp.where(cutoff_mask, jnp.inf, sorted_logits), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff_logit, NEG_INF, logits)
    return jax.random.categorical(rng, logits, axis=-1)

"""Token sampling: greedy / temperature / top-k / top-p.

Parity with the reference's decode styles: greedy argmax
(``llm-demo/minigpt/generate.py:14-28``), temperature + multinomial
(``minigpt2/test_model.py:35-57``), top-k/top-p HF ``generate`` kwargs
(``Scripts/inference/04-*.py``). All jittable (static shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_token(
    rng: jax.Array,
    logits: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    greedy: bool = False,
) -> jax.Array:
    """Sample next token ids from (..., vocab) logits."""
    if greedy or temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    if top_p is not None and top_p < 1.0:  # 0.0 = keep only the top token
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative prob exceeds top_p (always keep top-1)
        cutoff_mask = cum - probs > top_p
        cutoff_logit = jnp.min(
            jnp.where(cutoff_mask, jnp.inf, sorted_logits), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff_logit, NEG_INF, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def sample_token_batched(
    rng: jax.Array,
    logits: jax.Array,
    *,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
    greedy: jax.Array,
) -> jax.Array:
    """Per-row sampling params — the continuous-batching sampler.

    Every slot in the serving engine carries its own request's sampling
    settings, so all params are ``(B,)`` vectors: ``temperature`` floats,
    ``top_k`` ints (0 disables), ``top_p`` floats (>=1.0 disables),
    ``greedy`` bools. logits: ``(B, vocab)``. Jittable, static shapes.
    """
    n_vocab = logits.shape[-1]
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    # One O(V log V) sort serves both filters (the top-k masking below keeps
    # descending order, so no re-sort for top-p).
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]

    # Row-wise top-k: kth-largest threshold per row (k=0 -> keep all).
    k_idx = jnp.clip(top_k - 1, 0, n_vocab - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    k_on = top_k[:, None] > 0
    scaled = jnp.where(k_on & (scaled < kth), NEG_INF, scaled)
    sorted_desc = jnp.where(
        k_on & (jnp.arange(n_vocab)[None, :] > k_idx[:, None]), NEG_INF, sorted_desc
    )

    # Row-wise top-p over the filtered logits; top_p=0 is most restrictive
    # (keeps exactly the top-1), >=1 disables.
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_mask = cum - probs > top_p[:, None]
    cutoff_logit = jnp.min(
        jnp.where(cutoff_mask, jnp.inf, sorted_desc), axis=-1, keepdims=True
    )
    use_p = (top_p < 1.0)[:, None]
    scaled = jnp.where(use_p & (scaled < cutoff_logit), NEG_INF, scaled)

    sampled = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1), sampled)

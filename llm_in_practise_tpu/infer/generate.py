"""Autoregressive generation with a static-shape KV cache.

TPU-first replacement for the reference's decode loops
(``llm-demo/minigpt/generate.py:14-28`` greedy sliding window;
``minigpt2/test_model.py:35-57`` temperature sampling; HF ``generate`` in
``Scripts/inference``): prefill once over the prompt, then a jitted
one-token decode step reusing a pre-allocated cache — both compiled once and
replayed, no per-token retracing.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from llm_in_practise_tpu.infer.sampling import sample_token


def max_positions(config) -> int | None:
    """Longest position the model's RoPE / position tables cover.

    Beyond this, position gathers clamp silently under jit and corrupt
    logits — callers must never let a KV cache grow past it.
    """
    for field in ("max_seq_len", "seq_len"):
        v = getattr(config, field, None)
        if v is not None:
            return int(v)
    return None


def make_decode_fns(model) -> tuple[Callable, Callable]:
    """Returns (prefill, decode_step), both jitted.

    prefill(params, prompt_ids, cache) -> (last_logits, cache)
    decode_step(params, token, cache)  -> (logits, cache)
    """

    @jax.jit
    def prefill(params, prompt_ids, cache):
        logits, cache = model.apply(
            {"params": params}, prompt_ids, deterministic=True, cache=cache
        )
        return logits[:, -1, :], cache

    @jax.jit
    def decode_step(params, token, cache):
        logits, cache = model.apply(
            {"params": params}, token[:, None], deterministic=True, cache=cache
        )
        return logits[:, -1, :], cache

    return prefill, decode_step


def generate(
    model,
    params,
    prompt_ids,
    *,
    max_new_tokens: int = 50,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    greedy: bool = False,
    eos_id: int | None = None,
    rng: jax.Array | None = None,
    cache_len: int | None = None,
    cache_dtype=jnp.bfloat16,
) -> jax.Array:
    """Generate token ids. prompt_ids: (B, Lp) int32. Returns (B, <=Lp+N).

    The prompt is cropped to fit the cache, mirroring the reference's
    sliding-window crop (``minigpt/generate.py:18-20``).
    """
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    b, prompt_len = prompt_ids.shape
    # position tables (learned/sinusoidal/rope cos-sin) only cover
    # seq_len/max_seq_len rows; beyond that jit silently clamps the gather,
    # so cap the cache.
    limit = max_positions(model.config)
    cache_len = min(cache_len or limit, limit)
    if prompt_len >= cache_len:
        prompt_ids = prompt_ids[:, -(cache_len - 1):]
        prompt_len = prompt_ids.shape[1]
    max_new_tokens = min(max_new_tokens, cache_len - prompt_len)
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    cache = model.init_cache(b, cache_len, dtype=cache_dtype)
    prefill, decode_step = make_decode_fns(model)
    logits, cache = prefill(params, prompt_ids, cache)

    tokens = [prompt_ids]
    sample = functools.partial(
        sample_token, temperature=temperature, top_k=top_k, top_p=top_p, greedy=greedy
    )
    finished = jnp.zeros((b,), bool)
    for step in range(max_new_tokens):
        rng, step_rng = jax.random.split(rng)
        next_token = sample(step_rng, logits).astype(jnp.int32)
        if eos_id is not None:
            next_token = jnp.where(finished, eos_id, next_token)
            finished = finished | (next_token == eos_id)
        tokens.append(next_token[:, None])
        if step == max_new_tokens - 1 or (
            eos_id is not None and bool(finished.all())
        ):
            break
        logits, cache = decode_step(params, next_token, cache)
    return jnp.concatenate(tokens, axis=1)

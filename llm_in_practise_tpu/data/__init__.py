from llm_in_practise_tpu.data.bpe import BPETokenizer, train_or_load
from llm_in_practise_tpu.data.chardata import CharTokenizer, char_lm_examples
from llm_in_practise_tpu.data.hf_tokenizer import HFTokenizerAdapter
from llm_in_practise_tpu.data.lm_dataset import (
    block_chunk,
    prepare_data,
    synthetic_corpus,
    tokenize_corpus,
    train_val_split,
)
from llm_in_practise_tpu.data.loader import batch_iterator
from llm_in_practise_tpu.data.sft import (
    IGNORE_INDEX,
    SFTBatch,
    build_sft_dataset,
    render_chatml,
    self_cognition_records,
    tokenize_for_sft,
)

__all__ = [
    "BPETokenizer",
    "CharTokenizer",
    "HFTokenizerAdapter",
    "IGNORE_INDEX",
    "SFTBatch",
    "batch_iterator",
    "block_chunk",
    "build_sft_dataset",
    "char_lm_examples",
    "prepare_data",
    "render_chatml",
    "self_cognition_records",
    "synthetic_corpus",
    "tokenize_corpus",
    "tokenize_for_sft",
    "train_or_load",
]
